"""Deterministic TPC-H data generator (numpy, vectorized).

The reference generates benchmark data with external tools
(`/root/reference/benchmarks/gen-tpch.sh` uses tpchgen-rs); data files are
not vendored (testdata is LFS). This generator produces schema-correct,
distribution-plausible TPC-H tables at any scale factor — deterministic by
seed so correctness tests are reproducible. It follows the TPC-H spec's
cardinalities and value domains (spec is public); it is NOT a byte-exact
dbgen clone, which is fine because correctness tests compare our engine
against a trusted oracle (pandas/duck-style reference executor) on the SAME
generated data, and benchmarks measure relative engine speed.

Cardinalities at SF=1: region 5, nation 25, supplier 10k, customer 150k,
part 200k, partsupp 800k, orders 1.5M, lineitem ~6M.
"""

from __future__ import annotations

import numpy as np

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
_INSTRUCTIONS = [
    "COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN",
]
_TYPES_P1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
_TYPES_P2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_TYPES_P3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_CONTAINERS_P1 = ["SM", "MED", "JUMBO", "WRAP", "LG"]
_CONTAINERS_P2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
_COMMENT_WORDS = (
    "the of and regular deposits carefully quickly furiously final special "
    "express ironic pending bold slyly blithely even silent unusual requests "
    "accounts packages theodolites foxes ideas dependencies instructions "
    "platelets pinto beans sleep haggle nag use wake cajole detect integrate"
).split()

# P_NAME is a concatenation of color words in the TPC-H spec; queries
# FILTER on them (q9 `like '%green%'`, q20 `like 'forest%'`), so a name
# pool without colors makes those queries vacuously return 0 rows — a
# parity check that can never fail. Subset of the spec's color list.
_COLOR_WORDS = (
    "almond antique aquamarine azure beige bisque black blanched blue "
    "blush brown burlywood burnished chartreuse chiffon chocolate coral "
    "cornflower cornsilk cream cyan dark deep dim dodger drab firebrick "
    "floral forest frosted gainsboro ghost goldenrod green grey honeydew "
    "hot indian ivory khaki lace lavender lawn lemon light lime linen "
    "magenta maroon medium metallic midnight mint misty moccasin navajo "
    "navy olive orange orchid pale papaya peach peru pink plum powder "
    "puff purple red rose rosy royal saddle salmon sandy seashell sienna "
    "sky slate smoke snow spring steel tan thistle tomato turquoise "
    "violet wheat white yellow"
).split()

_EPOCH_1992 = 8035  # days 1970-01-01 -> 1992-01-01
_EPOCH_1998_AUG2 = 10440  # last possible o_orderdate (1998-08-02)


def _dates(rng, n, lo=_EPOCH_1992, hi=_EPOCH_1998_AUG2):
    return rng.integers(lo, hi + 1, n).astype(np.int32)


def _comments(rng, n, max_words=8):
    k = rng.integers(2, max_words + 1, n)
    words = np.array(_COMMENT_WORDS, dtype=object)
    # vectorized-ish: sample a matrix of word indices, join per row
    idx = rng.integers(0, len(words), (n, max_words))
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = " ".join(words[idx[i, : k[i]]])
    return out


def _phones(rng, n, nation_keys):
    a = nation_keys.astype(np.int64) + 10
    b = rng.integers(100, 1000, n)
    c = rng.integers(100, 1000, n)
    d = rng.integers(1000, 10000, n)
    return np.array(
        [f"{ai}-{bi}-{ci}-{di}" for ai, bi, ci, di in zip(a, b, c, d)],
        dtype=object,
    )


def gen_tpch(sf: float = 0.01, seed: int = 0) -> dict:
    """-> {table_name: pyarrow.Table} for all 8 TPC-H tables."""
    import pyarrow as pa

    rng = np.random.default_rng(seed)

    n_supp = max(int(10_000 * sf), 10)
    n_cust = max(int(150_000 * sf), 30)
    n_part = max(int(200_000 * sf), 40)
    n_psupp = n_part * 4
    n_ord = max(int(1_500_000 * sf), 150)

    region = pa.table(
        {
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": np.array(_REGIONS, dtype=object),
            "r_comment": _comments(rng, 5),
        }
    )

    n_nationkey = np.arange(25, dtype=np.int64)
    nation = pa.table(
        {
            "n_nationkey": n_nationkey,
            "n_name": np.array([n for n, _ in _NATIONS], dtype=object),
            "n_regionkey": np.array([r for _, r in _NATIONS], dtype=np.int64),
            "n_comment": _comments(rng, 25),
        }
    )

    s_nation = rng.integers(0, 25, n_supp)
    supplier = pa.table(
        {
            "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
            "s_name": np.array(
                [f"Supplier#{i:09d}" for i in range(1, n_supp + 1)], dtype=object
            ),
            "s_address": _comments(rng, n_supp, 3),
            "s_nationkey": s_nation.astype(np.int64),
            "s_phone": _phones(rng, n_supp, s_nation),
            "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_supp), 2),
            "s_comment": _comments(rng, n_supp),
        }
    )
    # TPC-H q16/q20 need "Customer Complaints" / special comments; seed a few
    sup_comments = supplier.column("s_comment").to_pylist()
    for i in range(0, n_supp, 19):
        sup_comments[i] = "wake Customer slyly Complaints haggle"
    supplier = supplier.set_column(
        6, "s_comment", pa.array(sup_comments, type=pa.string())
    )

    c_nation = rng.integers(0, 25, n_cust)
    customer = pa.table(
        {
            "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
            "c_name": np.array(
                [f"Customer#{i:09d}" for i in range(1, n_cust + 1)], dtype=object
            ),
            "c_address": _comments(rng, n_cust, 3),
            "c_nationkey": c_nation.astype(np.int64),
            "c_phone": _phones(rng, n_cust, c_nation),
            "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust), 2),
            "c_mktsegment": np.array(_SEGMENTS, dtype=object)[
                rng.integers(0, 5, n_cust)
            ],
            "c_comment": _comments(rng, n_cust),
        }
    )

    p1 = rng.integers(0, len(_TYPES_P1), n_part)
    p2 = rng.integers(0, len(_TYPES_P2), n_part)
    p3 = rng.integers(0, len(_TYPES_P3), n_part)
    p_type = np.array(
        [
            f"{_TYPES_P1[a]} {_TYPES_P2[b]} {_TYPES_P3[c]}"
            for a, b, c in zip(p1, p2, p3)
        ],
        dtype=object,
    )
    brand_m = rng.integers(1, 6, n_part)
    brand_n = rng.integers(1, 6, n_part)
    c1 = rng.integers(0, len(_CONTAINERS_P1), n_part)
    c2 = rng.integers(0, len(_CONTAINERS_P2), n_part)
    part = pa.table(
        {
            "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
            # spec shape: five space-joined color words (q9/q20 filter on
            # these; see _COLOR_WORDS)
            "p_name": np.array(
                [
                    " ".join(row)
                    for row in np.array(_COLOR_WORDS, dtype=object)[
                        rng.integers(0, len(_COLOR_WORDS), (n_part, 5))
                    ]
                ],
                dtype=object,
            ),
            "p_mfgr": np.array(
                [f"Manufacturer#{m}" for m in brand_m], dtype=object
            ),
            "p_brand": np.array(
                [f"Brand#{m}{n}" for m, n in zip(brand_m, brand_n)], dtype=object
            ),
            "p_type": p_type,
            "p_size": rng.integers(1, 51, n_part).astype(np.int32),
            "p_container": np.array(
                [
                    f"{_CONTAINERS_P1[a]} {_CONTAINERS_P2[b]}"
                    for a, b in zip(c1, c2)
                ],
                dtype=object,
            ),
            "p_retailprice": np.round(
                900 + (np.arange(1, n_part + 1) % 1000) / 10
                + 100 * (np.arange(1, n_part + 1) % 10), 2
            ),
            "p_comment": _comments(rng, n_part, 3),
        }
    )

    ps_part = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4)
    ps_supp = (
        (ps_part + (np.tile(np.arange(4), n_part) * (n_supp // 4 + 1)))
        % n_supp
    ) + 1
    partsupp = pa.table(
        {
            "ps_partkey": ps_part,
            "ps_suppkey": ps_supp.astype(np.int64),
            "ps_availqty": rng.integers(1, 10_000, n_psupp).astype(np.int32),
            "ps_supplycost": np.round(rng.uniform(1.0, 1000.0, n_psupp), 2),
            "ps_comment": _comments(rng, n_psupp),
        }
    )

    o_cust = rng.integers(1, n_cust + 1, n_ord).astype(np.int64)
    o_date = _dates(rng, n_ord)
    lines_per_order = rng.integers(1, 8, n_ord)
    n_li = int(lines_per_order.sum())

    li_order = np.repeat(np.arange(1, n_ord + 1, dtype=np.int64), lines_per_order)
    li_odate = np.repeat(o_date, lines_per_order)
    li_linenumber = (
        np.arange(n_li) - np.repeat(
            np.cumsum(lines_per_order) - lines_per_order, lines_per_order
        ) + 1
    ).astype(np.int32)
    li_part = rng.integers(1, n_part + 1, n_li).astype(np.int64)
    # supplier chosen among the 4 suppliers of that part (partsupp relation)
    which = rng.integers(0, 4, n_li)
    li_supp = ((li_part + which * (n_supp // 4 + 1)) % n_supp + 1).astype(np.int64)
    qty = rng.integers(1, 51, n_li).astype(np.float64)
    extprice = np.round(qty * (90000 + (li_part % 20001) + 100) / 100.0, 2)
    discount = np.round(rng.integers(0, 11, n_li) / 100.0, 2)
    tax = np.round(rng.integers(0, 9, n_li) / 100.0, 2)
    shipdate = li_odate + rng.integers(1, 122, n_li)
    commitdate = li_odate + rng.integers(30, 91, n_li)
    receiptdate = shipdate + rng.integers(1, 31, n_li)
    today = 10452  # 1998-08-14-ish cutoff for status
    returnflag = np.where(
        receiptdate <= 10225,
        np.where(rng.random(n_li) < 0.5, "R", "A"),
        "N",
    )
    linestatus = np.where(shipdate > today - 61, "O", "F")

    lineitem = pa.table(
        {
            "l_orderkey": li_order,
            "l_partkey": li_part,
            "l_suppkey": li_supp,
            "l_linenumber": li_linenumber,
            "l_quantity": qty,
            "l_extendedprice": extprice,
            "l_discount": discount,
            "l_tax": tax,
            "l_returnflag": pa.array(returnflag.tolist(), type=pa.string()),
            "l_linestatus": pa.array(linestatus.tolist(), type=pa.string()),
            "l_shipdate": pa.array(
                shipdate.astype("int32"), type=pa.int32()
            ).cast(pa.date32()),
            "l_commitdate": pa.array(
                commitdate.astype("int32"), type=pa.int32()
            ).cast(pa.date32()),
            "l_receiptdate": pa.array(
                receiptdate.astype("int32"), type=pa.int32()
            ).cast(pa.date32()),
            "l_shipinstruct": np.array(_INSTRUCTIONS, dtype=object)[
                rng.integers(0, len(_INSTRUCTIONS), n_li)
            ],
            "l_shipmode": np.array(_SHIPMODES, dtype=object)[
                rng.integers(0, len(_SHIPMODES), n_li)
            ],
            "l_comment": _comments(rng, n_li, 4),
        }
    )

    # order status/totalprice derived from lineitems
    import pandas as pd

    li_df = pd.DataFrame(
        {
            "o": li_order,
            "rev": extprice * (1 + tax),
            "open": linestatus == "O",
        }
    )
    per_order = li_df.groupby("o").agg(total=("rev", "sum"), any_open=("open", "any"),
                                       all_open=("open", "all"))
    totalprice = np.round(per_order["total"].reindex(
        np.arange(1, n_ord + 1)).fillna(0.0).to_numpy(), 2)
    any_open = per_order["any_open"].reindex(np.arange(1, n_ord + 1)).fillna(False).to_numpy()
    all_open = per_order["all_open"].reindex(np.arange(1, n_ord + 1)).fillna(False).to_numpy()
    status = np.where(all_open, "O", np.where(any_open, "P", "F"))

    orders = pa.table(
        {
            "o_orderkey": np.arange(1, n_ord + 1, dtype=np.int64),
            "o_custkey": o_cust,
            "o_orderstatus": pa.array(status.tolist(), type=pa.string()),
            "o_totalprice": totalprice,
            "o_orderdate": pa.array(o_date, type=pa.int32()).cast(pa.date32()),
            "o_orderpriority": np.array(_PRIORITIES, dtype=object)[
                rng.integers(0, 5, n_ord)
            ],
            "o_clerk": np.array(
                [f"Clerk#{i:09d}" for i in rng.integers(1, max(n_supp // 10, 2), n_ord)],
                dtype=object,
            ),
            "o_shippriority": np.zeros(n_ord, dtype=np.int32),
            "o_comment": _comments(rng, n_ord),
        }
    )
    # q13 needs 'special requests' patterns in o_comment
    oc = orders.column("o_comment").to_pylist()
    for i in range(0, n_ord, 17):
        oc[i] = "blithely special foxes requests nag"
    orders = orders.set_column(8, "o_comment", pa.array(oc, type=pa.string()))

    return {
        "region": region,
        "nation": nation,
        "supplier": supplier,
        "customer": customer,
        "part": part,
        "partsupp": partsupp,
        "orders": orders,
        "lineitem": lineitem,
    }


def register_tpch(ctx, sf: float = 0.01, seed: int = 0) -> dict:
    """Generate + register all TPC-H tables in a SessionContext; returns the
    pyarrow tables (for oracle comparison)."""
    tables = gen_tpch(sf, seed)
    for name, arrow in tables.items():
        ctx.register_arrow(name, arrow)
    return tables
