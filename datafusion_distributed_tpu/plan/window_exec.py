"""Physical window operator over ops/window.py's segmented-scan kernels."""

from __future__ import annotations

from typing import Sequence

from datafusion_distributed_tpu.ops.sort import SortKey
from datafusion_distributed_tpu.ops.window import WindowFunc, window_compute
from datafusion_distributed_tpu.ops.table import Table
from datafusion_distributed_tpu.plan.physical import ExecContext, ExecutionPlan
from datafusion_distributed_tpu.schema import Field, Schema


class WindowExec(ExecutionPlan):
    """Appends window-function columns. Partition/order/argument expressions
    are materialized as named columns by the planner below this node (same
    convention as HashAggregateExec)."""

    def __init__(
        self,
        child: ExecutionPlan,
        funcs: Sequence[WindowFunc],
        partition_names: Sequence[str],
        order_keys: Sequence[SortKey],
        out_fields: Sequence[Field],
    ):
        super().__init__()
        self.child = child
        self.funcs = list(funcs)
        self.partition_names = list(partition_names)
        self.order_keys = list(order_keys)
        self.out_fields = list(out_fields)

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return WindowExec(
            children[0], self.funcs, self.partition_names, self.order_keys,
            self.out_fields,
        )

    def schema(self):
        return Schema(list(self.child.schema().fields) + self.out_fields)

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        cols = window_compute(
            t, self.partition_names, self.order_keys, self.funcs
        )
        for name, col in cols.items():
            t = t.with_column(name, col)
        return t

    def display(self):
        fs = ", ".join(
            f"{f.func}({f.input_name or ''}) AS {f.output_name}"
            for f in self.funcs
        )
        pb = ", ".join(self.partition_names)
        ob = ", ".join(
            f"{k.name} {'ASC' if k.ascending else 'DESC'}"
            for k in self.order_keys
        )
        return f"Window [{fs}] partition=[{pb}] order=[{ob}]"
