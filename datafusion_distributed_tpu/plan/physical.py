"""Physical plan IR: the ExecutionPlan tree.

The reference builds on DataFusion's `ExecutionPlan` trait (async per-partition
`RecordBatch` streams; SURVEY.md L0) and inserts its distributed operators into
that tree (`/root/reference/src/execution_plans/`). The TPU re-design keeps
the *tree* (the planner passes need it) but changes the execution contract:

- an operator's `execute(ctx)` does not stream; it **traces** the whole
  per-task pipeline into one XLA computation over padded Tables. XLA fusion
  replaces the volcano pipeline — filter+project+partial-agg become one fused
  kernel on the device.
- per-task intra-operator partitions collapse to 1: on a TPU the chip's
  parallelism comes from XLA, not operator threads. The reference's
  partition-level parallelism maps to *tasks* (devices) instead; see
  parallel/ for the exchange operators.
- leaf scans run on the host (Parquet decode) *before* tracing; the executor
  passes their Tables in as pytree arguments so the traced function is
  shape-stable and cacheable across batches of the same capacity.

Every node computes a static `output_capacity` — the padded row bound that
makes XLA shapes static (SURVEY.md §7 hard part (c)).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu.ops.aggregate import AggSpec, hash_aggregate
from datafusion_distributed_tpu.ops.sort import SortKey, limit_table, sort_table
from datafusion_distributed_tpu.ops.table import (
    Column,
    Table,
    concat_tables,
    round_up_pow2,
)
from datafusion_distributed_tpu.plan.expressions import (
    PhysicalExpr,
    expr_to_column,
)
from datafusion_distributed_tpu.schema import DataType, Field, Schema


# ---------------------------------------------------------------------------
# Task context
# ---------------------------------------------------------------------------


@dataclass
class DistributedTaskContext:
    """Which task of a stage this execution is (reference:
    `src/stage.rs` DistributedTaskContext)."""

    task_index: int = 0
    task_count: int = 1


@dataclass
class ExecContext:
    """Carried through `execute` tracing."""

    task: DistributedTaskContext
    inputs: dict[int, Table]  # leaf node_id -> loaded device Table
    overflow_flags: list = dc_field(default_factory=list)
    config: dict = dc_field(default_factory=dict)
    # traced per-node metrics: (node_id, metric_name, traced scalar). The
    # executor returns these as program outputs and stitches them into a
    # MetricsStore host-side (runtime/metrics.py).
    metrics: list = dc_field(default_factory=list)
    # exchange-node memoization (node_id -> Table): collectives must execute
    # exactly once per program and OUTSIDE any lax.cond (all tasks
    # participate unconditionally); IsolatedArmExec relies on this to
    # pre-execute an arm's exchanges before conditioning its local compute
    exchange_cache: dict = dc_field(default_factory=dict)

    def record_overflow(self, node: "ExecutionPlan", flag) -> None:
        self.overflow_flags.append((node.label(), flag))

    def record_precision_error(self, node: "ExecutionPlan", flag) -> None:
        """A 32-bit accumulator left its exact range (tpu precision mode).
        Distinct from capacity overflow: growing the hash table cannot fix
        it, so the executor raises a non-retryable error instead."""
        self.overflow_flags.append((_PRECISION_TAG + node.label(), flag))

    def record_metric(self, node: "ExecutionPlan", name: str, value) -> None:
        if self.config.get("collect_metrics", True):
            self.metrics.append((node.node_id, name, value))


_PRECISION_TAG = "precision!"

_NODE_COUNTER = itertools.count()


# ---------------------------------------------------------------------------
# Base node
# ---------------------------------------------------------------------------


class ExecutionPlan:
    """Base of the physical plan tree."""

    #: statistics annotations stamped by the SQL planner from catalog NDV
    #: (the role DataFusion table-provider statistics play for the
    #: reference's cost model): estimated output rows / filter selectivity.
    #: Consumed by planner/statistics.estimate_rows; preserved across
    #: with_new_children rebuilds by the __init_subclass__ hook below.
    est_rows: "float | None" = None
    est_selectivity: "float | None" = None
    #: runtime-adaptivity annotations stamped by the distributed planner's
    #: partial-aggregate push-down pass: marks a "partial" aggregate whose
    #: measured reduction the coordinator may probe and bail out of
    #: (runtime/adaptivity.py). Coordinator-side only — never fingerprinted,
    #: never serialized — but must survive the with_new_children rebuilds
    #: the coordinator performs while resolving nested exchange scans.
    bailout_candidate: "bool | None" = None
    predicted_partial_rows: "int | None" = None
    #: multiway-join fusion annotations (planner/distributed
    #: _multiway_fusion_pass): a fused MultiwayHashJoinExec the coordinator
    #: may bail back to its binary chain when measured build sizes diverge,
    #: and the estimated-selectivity probe order the statistics module
    #: picked (a hint only — steps execute in plan order, reordering would
    #: change the output column order).
    multiway_bailout_candidate: "bool | None" = None
    probe_order_hint: "tuple | None" = None
    #: shuffles the fusion pass deleted building this node (identity
    #: re-partitions); surfaced in EXPLAIN and asserted by tests
    multiway_deleted_exchanges: "int | None" = None
    #: global-hash-agg annotation (_inject_aggregate): marks a single-mode
    #: aggregate the planner chose over partial+final because predicted NDV
    #: was too high for partial states to shrink the exchange; guards the
    #: push-down pass from re-rewriting it.
    global_agg_selected: "bool | None" = None

    #: annotations the __init_subclass__ hook carries across rebuilds
    _PRESERVED_ANNOTATIONS = (
        "est_rows", "est_selectivity",
        "bailout_candidate", "predicted_partial_rows",
        "multiway_bailout_candidate", "probe_order_hint",
        "multiway_deleted_exchanges", "global_agg_selected",
    )

    def __init__(self) -> None:
        self.node_id = next(_NODE_COUNTER)

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        impl = cls.__dict__.get("with_new_children")
        if impl is None:
            return
        import functools

        @functools.wraps(impl)
        def wrapped(self, children, _impl=impl):
            n = _impl(self, children)
            if n is not self and type(n) is type(self):
                for a in self._PRESERVED_ANNOTATIONS:
                    v = getattr(self, a, None)
                    if v is not None and getattr(n, a, None) is None:
                        setattr(n, a, v)
            return n

        cls.with_new_children = wrapped

    # -- tree ---------------------------------------------------------------
    def children(self) -> list["ExecutionPlan"]:
        raise NotImplementedError

    def with_new_children(self, children: list["ExecutionPlan"]) -> "ExecutionPlan":
        raise NotImplementedError

    # -- properties ---------------------------------------------------------
    def schema(self) -> Schema:
        raise NotImplementedError

    def output_capacity(self) -> int:
        raise NotImplementedError

    # -- execution ----------------------------------------------------------
    def execute(self, ctx: ExecContext) -> Table:
        """Trace this operator; records the per-node output_rows metric
        (the DataFusion baseline metric set analogue)."""
        out = self._execute(ctx)
        ctx.record_metric(self, "output_rows", out.num_rows)
        return out

    def _execute(self, ctx: ExecContext) -> Table:
        raise NotImplementedError

    # -- display ------------------------------------------------------------
    def label(self) -> str:
        return type(self).__name__.removesuffix("Exec")

    def display(self) -> str:
        return self.label()

    def display_tree(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.display()]
        for c in self.children():
            lines.append(c.display_tree(indent + 1))
        return "\n".join(lines)

    # -- traversal helpers --------------------------------------------------
    def transform_up(self, f: Callable[["ExecutionPlan"], "ExecutionPlan"]):
        new_children = [c.transform_up(f) for c in self.children()]
        node = self.with_new_children(new_children) if new_children else self
        return f(node)

    def transform_down(self, f: Callable[["ExecutionPlan"], "ExecutionPlan"]):
        node = f(self)
        children = [c.transform_down(f) for c in node.children()]
        return node.with_new_children(children) if children else node

    def collect(self, pred: Callable[["ExecutionPlan"], bool]) -> list["ExecutionPlan"]:
        out = [self] if pred(self) else []
        for c in self.children():
            out.extend(c.collect(pred))
        return out


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------


class MemoryScanExec(ExecutionPlan):
    """Scan over pre-loaded per-task device Tables.

    The reference's `DistributedLeafExec` holds per-task variants of a leaf
    and picks by `task_index` (`src/execution_plans/distributed_leaf.rs`);
    here each task's slice is one padded Table in `tasks`.
    """

    def __init__(self, tasks: Sequence[Table], schema: Schema,
                 pinned: bool = False, replicated: bool = False):
        super().__init__()
        self.tasks = list(tasks)
        self._schema = schema
        # pinned: this scan is already task-specialized (holds exactly the
        # executing task's slice); ignore task_index on load
        self.pinned = pinned
        # replicated: one logical table served identically to EVERY task
        # (coalesce/broadcast exchange outputs) — load ignores task_index,
        # and the coordinator may run a stage reading only replicated scans
        # as a single task (its output is the complete result)
        self.replicated = replicated

    def children(self):
        return []

    def with_new_children(self, children):
        assert not children
        return self

    def schema(self):
        return self._schema

    def output_capacity(self):
        # default=8: a co-shuffled group's PLACEHOLDER scan (adaptive
        # coordinator, `_finish_shuffle`) is empty while sibling feeds
        # materialize; parents rebuilt over it during that window get a
        # floor capacity, corrected by resize_for_inputs at dispatch
        return max((t.capacity for t in self.tasks), default=8)

    def load(self, task: DistributedTaskContext) -> Table:
        if self.pinned or self.replicated:
            return self.tasks[0]
        if task.task_index >= len(self.tasks):
            # Tasks beyond the data slices read nothing (the reference's
            # short coalesce groups yield empty streams the same way).
            ref = self.tasks[0]
            return Table.empty(self._schema, ref.capacity, _dicts_of(ref))
        return self.tasks[task.task_index]

    def _execute(self, ctx: ExecContext) -> Table:
        return ctx.inputs[self.node_id]

    def display(self):
        return f"MemoryScan tasks={len(self.tasks)} cap={self.output_capacity()}"


class ParquetScanExec(ExecutionPlan):
    """Parquet leaf: per-task file groups decoded on the host, uploaded padded.

    Mirrors the role of DataFusion's `DataSourceExec` + the reference's
    task-specialized file-group slicing (`task_estimator.rs` scale_up path).
    """

    def __init__(
        self,
        file_groups: Sequence[Sequence[str]],  # one list of files per task
        schema: Schema,
        capacity: int,
        projection: Optional[Sequence[str]] = None,
        dictionaries: Optional[dict] = None,
    ):
        super().__init__()
        self.file_groups = [list(g) for g in file_groups]
        self._schema = schema if projection is None else schema.select(projection)
        self.projection = list(projection) if projection else None
        self.capacity = capacity
        self.dictionaries = dictionaries

    def children(self):
        return []

    def with_new_children(self, children):
        assert not children
        return self

    def schema(self):
        return self._schema

    def output_capacity(self):
        return self.capacity

    def load(self, task: DistributedTaskContext) -> Table:
        from datafusion_distributed_tpu.io.parquet import read_parquet

        files = (
            self.file_groups[task.task_index]
            if task.task_index < len(self.file_groups)
            else []
        )
        if not files:
            return Table.empty(self._schema, self.capacity, self.dictionaries)
        return read_parquet(
            files,
            columns=self.projection,
            capacity=self.capacity,
            dictionaries=self.dictionaries,
        )

    def _execute(self, ctx: ExecContext) -> Table:
        return ctx.inputs[self.node_id]

    def display(self):
        nfiles = sum(len(g) for g in self.file_groups)
        return (
            f"ParquetScan tasks={len(self.file_groups)} files={nfiles} "
            f"cap={self.capacity}"
        )


# ---------------------------------------------------------------------------
# Unary operators
# ---------------------------------------------------------------------------


class FilterExec(ExecutionPlan):
    def __init__(self, predicate: PhysicalExpr, child: ExecutionPlan):
        super().__init__()
        self.predicate = predicate
        self.child = child

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return FilterExec(self.predicate, children[0])

    def schema(self):
        return self.child.schema()

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        v = self.predicate.evaluate(t)
        keep = v.data.astype(jnp.bool_) & v.valid_mask()
        return t.compact(keep)

    def display(self):
        return f"Filter: {self.predicate.display()}"


class ProjectionExec(ExecutionPlan):
    def __init__(self, exprs: Sequence[tuple[PhysicalExpr, str]], child: ExecutionPlan):
        super().__init__()
        self.exprs = list(exprs)
        self.child = child

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return ProjectionExec(self.exprs, children[0])

    def schema(self):
        child_schema = self.child.schema()
        fields = []
        for expr, name in self.exprs:
            f = expr.output_field(child_schema)
            fields.append(Field(name, f.dtype, f.nullable))
        return Schema(fields)

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        cols = {}
        for expr, name in self.exprs:
            cols[name] = expr_to_column(expr.evaluate(t))
        return Table(tuple(cols.keys()), tuple(cols.values()), t.num_rows)

    def display(self):
        inner = ", ".join(f"{e.display()} AS {n}" for e, n in self.exprs)
        return f"Projection: {inner}"


class HashAggregateExec(ExecutionPlan):
    """GROUP BY over named columns (planner materializes expressions below
    via a ProjectionExec). Modes: single | partial | final, as in the
    reference's use of DataFusion AggregateMode (+ PartialReduce analogue to
    come with the distributed planner)."""

    def __init__(
        self,
        mode: str,
        group_names: Sequence[str],
        aggs: Sequence[AggSpec],
        child: ExecutionPlan,
        num_slots: Optional[int] = None,
    ):
        super().__init__()
        assert mode in ("single", "partial", "final", "partial_reduce")
        self.mode = mode
        self.group_names = list(group_names)
        self.aggs = list(aggs)
        self.child = child
        # Default table size: 2x the input bound keeps the load factor <= 0.5
        # even in the all-rows-distinct worst case, so the claim loop
        # converges well inside max_rounds (see ops/aggregate.py docstring).
        self.num_slots = num_slots or min(
            round_up_pow2(2 * max(child.output_capacity(), 16)), 1 << 20
        )
        # OUTPUT capacity: groups <= live input rows, so the packed result
        # never needs more than pow2(input capacity) — downstream operators
        # (the final sort especially) pay capacity-proportional work, and
        # slots = 2x input would hand them double-width padding for free.
        # DFTPU_AGG_COMPACT=0 is the A/B lever.
        import os as _os

        if _os.environ.get("DFTPU_AGG_COMPACT", "1") == "1":
            self.out_capacity = min(
                self.num_slots,
                round_up_pow2(max(child.output_capacity(), 16)),
            )
        else:
            self.out_capacity = self.num_slots

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return HashAggregateExec(
            self.mode, self.group_names, self.aggs, children[0], self.num_slots
        )

    def schema(self):
        child_schema = self.child.schema()
        fields = [child_schema.field(g) for g in self.group_names]
        for a in self.aggs:
            fields.extend(_agg_output_fields(a, child_schema, self.mode))
        return Schema(fields)

    def output_capacity(self):
        return self.out_capacity if self.group_names else self.num_slots

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        prec_flags: list = []
        if not self.group_names:
            from datafusion_distributed_tpu.ops.aggregate import global_aggregate

            out = global_aggregate(t, self.aggs, self.mode,
                                   prec_flags=prec_flags)
        else:
            out, overflow = hash_aggregate(
                t, self.group_names, self.aggs, self.num_slots, self.mode,
                prec_flags=prec_flags, out_capacity=self.out_capacity,
            )
            ctx.record_overflow(self, overflow)
        for f in prec_flags:
            ctx.record_precision_error(self, f)
        return out

    def display(self):
        aggs = ", ".join(f"{a.func}({a.input_name or '*'})" for a in self.aggs)
        return (
            f"HashAggregate mode={self.mode} gby=[{', '.join(self.group_names)}] "
            f"aggs=[{aggs}] slots={self.num_slots}"
        )


def _agg_output_fields(a: AggSpec, child_schema: Schema, mode: str) -> list[Field]:
    from datafusion_distributed_tpu.ops.aggregate import _VARIANCE_FUNCS

    if a.func == "count_star" or a.func == "count":
        return [Field(a.output_name, DataType.INT64, nullable=False)]
    if a.func == "avg":
        if mode in ("partial", "partial_reduce"):
            return [
                Field(f"{a.output_name}__sum", DataType.FLOAT64, True),
                Field(f"{a.output_name}__count", DataType.INT64, False),
            ]
        return [Field(a.output_name, DataType.FLOAT64, True)]
    if a.func in _VARIANCE_FUNCS:
        if mode in ("partial", "partial_reduce"):
            return [
                Field(f"{a.output_name}__sum", DataType.FLOAT64, True),
                Field(f"{a.output_name}__sumsq", DataType.FLOAT64, True),
                Field(f"{a.output_name}__count", DataType.INT64, False),
            ]
        return [Field(a.output_name, DataType.FLOAT64, True)]
    if mode in ("final", "partial_reduce"):
        # Final mode consumes the partial stage's accumulator column, which
        # already carries the merged dtype under the output name.
        src = child_schema.field(a.output_name)
        return [Field(a.output_name, src.dtype, True)]
    src = child_schema.field(a.input_name) if a.input_name else None
    if a.func == "sum":
        dt = DataType.FLOAT64 if src.dtype.is_float else DataType.INT64
        return [Field(a.output_name, dt, True)]
    # min/max keep input type
    return [Field(a.output_name, src.dtype, True)]


class PartialPassthroughExec(ExecutionPlan):
    """Per-row partial-aggregation states — the bail-out form of a
    pushed-down ``HashAggregateExec(mode="partial")``. Emits, for every
    input row, the singleton accumulator a one-row group would produce
    (ops/aggregate.py `singleton_partial_states`), under the exact
    partial-mode schema, so the downstream final aggregate merges either
    operator's output interchangeably. The runtime swaps this in for the
    remaining tasks of a stage whose probed first task showed the
    sampled-NDV prediction was wrong and the partial barely reduces
    (runtime/adaptivity.py): pure elementwise work instead of a hash
    table that pays without shrinking the exchange."""

    def __init__(self, group_names: Sequence[str], aggs: Sequence[AggSpec],
                 child: ExecutionPlan):
        super().__init__()
        self.group_names = list(group_names)
        self.aggs = list(aggs)
        self.child = child

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return PartialPassthroughExec(self.group_names, self.aggs,
                                      children[0])

    def schema(self):
        child_schema = self.child.schema()
        fields = [child_schema.field(g) for g in self.group_names]
        for a in self.aggs:
            fields.extend(_agg_output_fields(a, child_schema, "partial"))
        return Schema(fields)

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        from datafusion_distributed_tpu.ops.aggregate import (
            singleton_partial_states,
        )

        return singleton_partial_states(
            self.child.execute(ctx), self.group_names, self.aggs
        )

    def display(self):
        aggs = ", ".join(f"{a.func}({a.input_name or '*'})" for a in self.aggs)
        return (
            f"PartialPassthrough gby=[{', '.join(self.group_names)}] "
            f"aggs=[{aggs}]"
        )


class SortExec(ExecutionPlan):
    def __init__(self, keys: Sequence[SortKey], child: ExecutionPlan,
                 fetch: Optional[int] = None):
        super().__init__()
        self.keys = list(keys)
        self.child = child
        self.fetch = fetch

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return SortExec(self.keys, children[0], self.fetch)

    def schema(self):
        return self.child.schema()

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        t = sort_table(self.child.execute(ctx), self.keys)
        if self.fetch is not None:
            t = t.head(self.fetch)
        return t

    def display(self):
        ks = ", ".join(
            f"{k.name} {'ASC' if k.ascending else 'DESC'}" for k in self.keys
        )
        fetch = f" fetch={self.fetch}" if self.fetch is not None else ""
        return f"Sort: [{ks}]{fetch}"


class LimitExec(ExecutionPlan):
    def __init__(self, child: ExecutionPlan, fetch: int, skip: int = 0):
        super().__init__()
        self.child = child
        self.fetch = fetch
        self.skip = skip

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return LimitExec(children[0], self.fetch, self.skip)

    def schema(self):
        return self.child.schema()

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        return limit_table(self.child.execute(ctx), self.fetch, self.skip)

    def display(self):
        skip = f" skip={self.skip}" if self.skip else ""
        return f"Limit: fetch={self.fetch}{skip}"


class CoalescePartitionsExec(ExecutionPlan):
    """N input partitions -> 1. In the per-task model a task's plan already
    yields one Table, so locally this is identity; it exists as the planner's
    stage-head marker (the reference wraps plans in CoalescePartitionsExec
    before staging, `distributed_query_planner.rs` shape pass)."""

    def __init__(self, child: ExecutionPlan):
        super().__init__()
        self.child = child

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return CoalescePartitionsExec(children[0])

    def schema(self):
        return self.child.schema()

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        return self.child.execute(ctx)


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


def collect_leaves(plan: ExecutionPlan) -> list[ExecutionPlan]:
    return plan.collect(lambda n: not n.children())


def execute_plan(
    plan: ExecutionPlan,
    task: Optional[DistributedTaskContext] = None,
    config: Optional[dict] = None,
    check_overflow: bool = True,
    metrics_store=None,
    task_label: Optional[str] = None,
    use_cache: bool = True,
    shared_cache: Optional[dict] = None,
    shared_key=None,
) -> Table:
    """Run a (single-task) plan: host-load leaves, trace+jit the rest once.

    The compile cache is keyed on the plan's STRUCTURAL FINGERPRINT
    (plan/fingerprint.py) — node kinds, expressions, capacities, the task
    lattice — not object identity, so a fresh submission of an identical
    query (new ``ctx.sql()`` call) reuses the compiled executable, and a
    literal-hoisted template variant reuses it with new parameter inputs
    (the analogue of the reference's task re-execution against the cached
    plan in `TaskData`, extended across queries). Plans containing nodes the
    fingerprint cannot canonicalize fall back to object-identity keying.
    When ``metrics_store`` is given, the traced
    per-node metrics are returned as program outputs and inserted under
    ``task_label`` (runtime/metrics.py MetricsStore protocol).

    ``shared_cache``/``shared_key`` let a caller share ONE traced program
    across *distinct plan objects of the same stage* (the worker runtime:
    every task of a stage decodes its own plan copy, but the padded-capacity
    lattice makes the traced computation task-invariant — only the leaf
    *data* differs, and that enters as a program input). The caller is
    responsible for only passing plans whose trace does not branch on
    ``task_index`` (see Worker.execute_task: IsolatedArmExec disables it);
    the structural fingerprint plus the input pytree structure +
    shapes/dtypes are appended to the key here, so same-stage tasks with
    divergent trees or leaf shapes simply miss (they can no longer silently
    bind another stage's inputs)."""
    from datafusion_distributed_tpu.plan.fingerprint import (
        bound_params,
        prepare_plan,
    )

    # lock-while-compiling witness (runtime/lockcheck.py, opt-in via
    # DFTPU_LOCK_CHECK=1): entering the XLA trace/compile/execute entry
    # point with an engine lock held stalls every contender for seconds —
    # the harness records it; no-op (one module-attr read) when off
    from datafusion_distributed_tpu.runtime import lockcheck as _lockcheck

    if _lockcheck.enabled():
        _lockcheck.note_blocking("xla_compile")

    task = task or DistributedTaskContext()
    # content-address the program: literal-hoisted plan + structural
    # fingerprint (None -> legacy object-identity keying). The hoisted
    # plan reuses the original's leaf objects, so leaf traversal order —
    # the positional input binding — is unchanged.
    prep = prepare_plan(plan)
    exec_target = prep.plan
    params = prep.param_arrays()
    leaves = collect_leaves(exec_target)
    # positional inputs, rebound to node ids INSIDE run via the closure
    # plan's own leaf order: node ids are minted per decode, so a shared
    # program traced from one task's plan copy must not see another copy's
    # ids in its input pytree — leaf traversal order is the cross-copy
    # stable identity (fingerprint-equal trees traverse identically)
    leaf_ids = [leaf.node_id for leaf in leaves if hasattr(leaf, "load")]
    input_list = [
        leaf.load(task) for leaf in leaves if hasattr(leaf, "load")
    ]

    overflow_box: list = []
    metric_names: list = []

    def run(inp_list, param_vecs):
        _TRACE_STATS["traces"] += 1
        inp = dict(zip(leaf_ids, inp_list))
        ctx = ExecContext(task=task, inputs=inp, config=config or {})
        with bound_params(param_vecs):
            out = exec_target.execute(ctx)
        overflow_box.clear()
        overflow_box.extend(ctx.overflow_flags)
        # metric names are POSITION-addressed (pre-order traversal index),
        # not node-id-addressed: a fingerprint-shared program executes for
        # plan copies whose node ids differ from the creator's, and
        # fingerprint-equal trees traverse identically — the caller remaps
        # positions to ITS plan's node ids at insert time
        pos_of = {
            n.node_id: i
            for i, n in enumerate(exec_target.collect(lambda _n: True))
        }
        metric_names.clear()
        metric_names.extend(
            (pos_of.get(nid, -1), name) for nid, name, _ in ctx.metrics
        )
        metric_vals = [v for _, _, v in ctx.metrics]
        cap_flags = [
            f for name, f in ctx.overflow_flags
            if not name.startswith(_PRECISION_TAG)
        ]
        prec_flags = [
            f for name, f in ctx.overflow_flags
            if name.startswith(_PRECISION_TAG)
        ]
        any_overflow = (
            jnp.any(jnp.stack(cap_flags)) if cap_flags else jnp.asarray(False)
        )
        any_precision = (
            jnp.any(jnp.stack(prec_flags)) if prec_flags
            else jnp.asarray(False)
        )
        # ONE packed flag vector: each separate scalar device->host fetch
        # costs a full tunnel round-trip (~80 ms measured), so both checks
        # ride a single transfer
        return out, jnp.stack([any_overflow, any_precision]), metric_vals

    # the distributed-tracing wire context (runtime/tracing.py
    # TRACE_CTX_KEY) must NEVER key a compiled program: its span ids
    # differ per task/query, so admitting it would force one XLA trace
    # per task. Worker.execute_task already strips it; this filter is the
    # defense for direct execute_plan callers.
    cfg_items = tuple(sorted(
        (k, v) for k, v in (config or {}).items() if k != "trace_ctx"
    ))
    # structural fingerprint -> content-addressed entry shared across plan
    # objects (fresh ctx.sql() submissions, literal-hoisted template
    # variants); no fingerprint -> legacy object-identity keying
    if prep.fingerprint is not None:
        cache_key = ("fp", prep.fingerprint, task.task_index,
                     task.task_count, cfg_items)
    else:
        cache_key = ("id", plan.node_id, task.task_index,
                     task.task_count, cfg_items)
    # the trace-time boxes (overflow names, metric names) must come from the
    # SAME closure as the cached executable, or cache hits would see them
    # empty. use_cache=False (worker path: per-task programs go through the
    # TTL'd stage-share cache instead) keeps one-shot programs out of the
    # global cache so their closures don't pin shipped task tables.
    cached = None
    if use_cache:
        with _CACHE_LOCK:
            cached = _COMPILE_CACHE.get(cache_key)
            if cached is not None:
                # move-to-end: LRU eviction must not take a live entry
                _COMPILE_CACHE.pop(cache_key)
                _COMPILE_CACHE[cache_key] = cached
    first_call_gate = None
    if cached is None and shared_cache is not None:
        # stage-shared program: key on the caller's stage identity, the
        # structural fingerprint (an order/identity mismatch between plan
        # copies now misses instead of silently binding wrong inputs), and
        # the input pytree structure + leaf shapes/dtypes (the only thing
        # that can legitimately differ between same-stage tasks)
        flat, treedef = jax.tree_util.tree_flatten(input_list)
        sig = tuple(
            (getattr(l, "shape", None), str(getattr(l, "dtype", type(l))))
            for l in flat
        )
        skey = (shared_key, prep.fingerprint, treedef, sig)
        # get-or-create under a lock: same-stage tasks fan out on coordinator
        # threads, and an unsynchronized check-then-act would have the first
        # wave all miss and compile duplicates — the exact cost this cache
        # removes. The creator also takes the entry's first-call gate so
        # concurrent siblings wait for its trace+compile instead of racing
        # jax's own dispatch into duplicate compiles.
        with _SHARED_LOCK:
            cached = shared_cache.get(skey)
            if cached is None:
                _SHARED_STATS["miss"] += 1
                # entry cap: each entry's closure pins its creator task's
                # decoded plan (incl. device tables) until the query slot's
                # TTL/LRU turnover — a wide stage whose keys fragment
                # (per-task dictionary identity, remainder shapes) must not
                # retain one plan per task. Insertion-order eviction; an
                # evicted program just recompiles on next use.
                while len(shared_cache) >= _SHARED_ENTRY_CAP:
                    shared_cache.pop(next(iter(shared_cache)))
                cached = (
                    jax.jit(run), overflow_box, metric_names,
                    {"lock": threading.Lock(), "warmed": False},
                )
                shared_cache[skey] = cached
            else:
                _SHARED_STATS["hit"] += 1
        first_call_gate = cached[3]
        cached = cached[:3]
    if cached is None:
        cached = (jax.jit(run), overflow_box, metric_names)
        if use_cache:
            with _CACHE_LOCK:
                # bounded LRU eviction (was: a full clear() at the cap — a
                # cliff that recompiled EVERY live query at once)
                while len(_COMPILE_CACHE) >= _COMPILE_CACHE_MAX:
                    _COMPILE_CACHE.pop(next(iter(_COMPILE_CACHE)))
                _COMPILE_CACHE[cache_key] = cached
    fn, overflow_box, metric_names = cached
    result = None
    if first_call_gate is not None and not first_call_gate["warmed"]:
        with first_call_gate["lock"]:
            # double-check: threads that queued behind the creator must
            # NOT execute under the gate (that would serialize the whole
            # task wave) — only the creator's trace+compile+first-run is
            # serialized; everyone else re-checks and runs concurrently
            if not first_call_gate["warmed"]:
                result = fn(input_list, params)
                first_call_gate["warmed"] = True
    if result is None:
        result = fn(input_list, params)
    out, flags, metric_vals = result
    flags = np.asarray(flags)  # one fetch for both sentinel checks
    any_overflow, any_precision = bool(flags[0]), bool(flags[1])
    if check_overflow and any_overflow:
        raise RuntimeError(
            f"hash table overflow in plan (nodes: "
            f"{[name for name, _ in overflow_box if not name.startswith(_PRECISION_TAG)]}); "
            "re-plan with more slots"
        )
    if any_precision:
        # deliberately does NOT contain the word "overflow": the session's
        # capacity-retry loop must not retry this (a bigger hash table can't
        # restore int32 exactness).
        raise RuntimeError(
            "int32 accumulator range exceeded in plan (nodes: "
            f"{[name for name, _ in overflow_box if name.startswith(_PRECISION_TAG)]}); "
            "run with DFTPU_PRECISION=x64 for 64-bit accumulation"
        )
    if metrics_store is not None:
        # positions -> THIS submission's node ids (hoisting preserves the
        # original ids, so callers can look metrics up on their own plan)
        nodes = plan.collect(lambda _n: True)
        node_metrics: dict = {}
        for (pos, name), v in zip(metric_names, metric_vals):
            if 0 <= pos < len(nodes):
                node_metrics.setdefault(nodes[pos].node_id, {})[name] = int(v)
        metrics_store.insert(task_label or f"task{task.task_index}", node_metrics)
    return out


_COMPILE_CACHE: dict = {}  # insertion order == LRU order (move-to-end on hit)
_CACHE_LOCK = threading.Lock()
# stage-shared program cache observability: hits = task executions that
# reused another task's traced program (each hit ~= one XLA compile avoided)
_SHARED_STATS = {"hit": 0, "miss": 0}
_SHARED_LOCK = threading.Lock()
_SHARED_ENTRY_CAP = 32  # per-query distinct (stage, shape-class) programs


def _plan_cache_default() -> int:
    import os as _os

    try:
        return max(int(_os.environ.get("DFTPU_PLAN_CACHE", "512")), 1)
    except ValueError:
        return 512


_COMPILE_CACHE_MAX = _plan_cache_default()


def set_plan_cache_size(n) -> None:
    """Resize the compiled-program LRU (SET distributed.plan_cache_size /
    DFTPU_PLAN_CACHE). Shrinking evicts oldest entries immediately."""
    global _COMPILE_CACHE_MAX
    _COMPILE_CACHE_MAX = max(int(n), 1)
    with _CACHE_LOCK:
        while len(_COMPILE_CACHE) > _COMPILE_CACHE_MAX:
            _COMPILE_CACHE.pop(next(iter(_COMPILE_CACHE)))


# program-trace counter: incremented once per traced program body (the
# 1:1 proxy for XLA compiles — cache hits never re-run the traced python).
# The recompile-regression tests assert on deltas of this counter.
_TRACE_STATS = {"traces": 0}


def trace_count() -> int:
    return _TRACE_STATS["traces"]


def _dicts_of(table: Table) -> dict:
    return {
        n: c.dictionary
        for n, c in zip(table.names, table.columns)
        if c.dictionary is not None
    }
