"""Physical expression IR, evaluated to device arrays.

The reference delegates expression evaluation to DataFusion's `PhysicalExpr`
kernels over Arrow arrays (SURVEY.md L0). Here expressions are a small tree IR
that *traces* to jnp operations over the padded device columns — so a whole
filter/projection pipeline fuses into one XLA computation, with no
per-expression materialization (the XLA analogue of Arrow kernel fusion).

Key TPU-first choices:
- SQL three-valued logic is carried as an explicit (data, validity) pair; the
  VPU evaluates both lanes in parallel.
- String comparisons never touch strings on device: dictionaries are sorted,
  so `col op literal` compiles to an int32 code comparison against a host-side
  `searchsorted` of the literal (exact, even for literals absent from the
  dictionary).
- LIKE / IN on strings evaluate the predicate over the *dictionary* on the
  host at trace time and become a boolean lookup-table gather by code — O(NDV)
  host work, O(rows) device work.
"""

from __future__ import annotations

import datetime
import re
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu.ops.table import Column, Dictionary, Table
from datafusion_distributed_tpu.schema import DataType, Field, Schema

# Dictionary minting must be DETERMINISTIC across repeated evaluations of
# the same expression: IsolatedArmExec traces an arm twice (shape probe +
# lax.cond branch) and cond requires both traces' pytree metadata —
# which includes Dictionary identity — to match. Fresh per-evaluate
# Dictionaries also defeat the jit cache (dict_id is static aux data).
# Concurrency discipline: stage tasks evaluate expressions from worker
# threads, so get-or-mint is under a lock, and eviction is LRU (never the
# just-used entry — a wholesale clear between an arm's probe and branch
# traces would remint mid-trace and recreate the divergence).
from datafusion_distributed_tpu.ops.table import lru_get_or_create

_LITERAL_DICT_CACHE: dict = {}
_DERIVED_DICT_CACHE: dict = {}


def _literal_dictionary(value: str) -> Dictionary:
    return lru_get_or_create(
        _LITERAL_DICT_CACHE, value,
        lambda: Dictionary.from_strings([value]), cap=512,
    )


def _derived_dictionary(src: Dictionary, op_key, derive):
    """Memoized (source dict, operation) -> (sorted-unique Dictionary,
    int32 inverse LUT). `derive(values) -> array of derived strings`."""

    def mint():
        derived = np.asarray(derive(src.values), dtype=object)
        uniq, inverse = np.unique(derived.astype(str), return_inverse=True)
        return (Dictionary(uniq.astype(object)), inverse.astype(np.int32))

    return lru_get_or_create(
        _DERIVED_DICT_CACHE, (src.dict_id, op_key), mint, cap=256,
    )


# ---------------------------------------------------------------------------
# Evaluation result: device data + optional validity (None = all valid)
# ---------------------------------------------------------------------------


@dataclass
class ExprValue:
    data: jnp.ndarray
    validity: Optional[jnp.ndarray]  # bool array or None (= all valid)
    dtype: DataType
    dictionary: Optional[Dictionary] = None

    def valid_mask(self) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones(self.data.shape, dtype=jnp.bool_)
        return self.validity


def _remap_codes(codes: jnp.ndarray, lut) -> jnp.ndarray:
    """Apply a unify_dictionaries LUT (None = identity)."""
    if lut is None:
        return codes
    if len(lut) == 0:
        return jnp.zeros(codes.shape, dtype=jnp.int32)
    return jnp.asarray(lut)[jnp.clip(codes, 0, len(lut) - 1)]


def _merge_validity(*vs: Optional[jnp.ndarray]) -> Optional[jnp.ndarray]:
    present = [v for v in vs if v is not None]
    if not present:
        return None
    out = present[0]
    for v in present[1:]:
        out = out & v
    return out


def parse_date(s: str) -> int:
    """'YYYY-MM-DD' -> int32 days since epoch."""
    d = datetime.date.fromisoformat(s)
    return (d - datetime.date(1970, 1, 1)).days


# ---------------------------------------------------------------------------
# Expression nodes
# ---------------------------------------------------------------------------


class PhysicalExpr:
    """Base class. ``evaluate(table)`` returns an ExprValue whose arrays have
    the table's capacity; garbage rows (>= num_rows) may hold anything."""

    def evaluate(self, table: Table) -> ExprValue:
        raise NotImplementedError

    def output_field(self, schema: Schema) -> Field:
        raise NotImplementedError

    def children(self) -> list["PhysicalExpr"]:
        return []

    def display(self) -> str:
        return repr(self)


@dataclass
class Col(PhysicalExpr):
    name: str

    def evaluate(self, table: Table) -> ExprValue:
        c = table.column(self.name)
        return ExprValue(c.data, c.validity, c.dtype, c.dictionary)

    def output_field(self, schema: Schema) -> Field:
        return schema.field(self.name)

    def display(self) -> str:
        return self.name


@dataclass
class Literal(PhysicalExpr):
    value: Any  # python scalar: int/float/bool/str/None; dates pre-parsed int
    dtype: DataType

    def evaluate(self, table: Table) -> ExprValue:
        cap = table.capacity
        if self.value is None:
            data = jnp.zeros(cap, dtype=self.dtype.np_dtype)
            return ExprValue(data, jnp.zeros(cap, dtype=jnp.bool_), self.dtype)
        if self.dtype == DataType.STRING:
            # Bare string literal with no column context: keep as dtype STRING
            # with an INTERNED single-entry dictionary (same value -> same
            # Dictionary object, so re-tracing the expression yields
            # identical pytree metadata). Comparisons against columns
            # resolve via the column's dictionary (see Cmp).
            d = _literal_dictionary(self.value)
            data = jnp.zeros(cap, dtype=np.int32)
            return ExprValue(data, None, self.dtype, d)
        val = np.asarray(self.value, dtype=self.dtype.np_dtype)
        data = jnp.full(cap, val, dtype=self.dtype.np_dtype)
        return ExprValue(data, None, self.dtype)

    def output_field(self, schema: Schema) -> Field:
        return Field(str(self.value), self.dtype, nullable=self.value is None)

    def display(self) -> str:
        return repr(self.value)


_ARITH_OPS = {"+", "-", "*", "/", "%"}
_CMP_OPS = {"==", "!=", "<", "<=", ">", ">="}


def _promote(a: DataType, b: DataType) -> DataType:
    order = [
        DataType.BOOL,
        DataType.INT32,
        DataType.DATE32,
        DataType.INT64,
        DataType.FLOAT32,
        DataType.FLOAT64,
    ]
    if a == b:
        return a
    # untyped NULL adopts the peer's type (SQL NULL literal typing)
    if a == DataType.NULL:
        return b
    if b == DataType.NULL:
        return a
    if a == DataType.STRING or b == DataType.STRING:
        return DataType.STRING
    return max(a, b, key=order.index)


@dataclass
class BinaryOp(PhysicalExpr):
    """Arithmetic/comparison. String comparisons compile to code comparisons
    against the column dictionary (sorted => order-preserving)."""

    op: str
    left: PhysicalExpr
    right: PhysicalExpr

    def children(self):
        return [self.left, self.right]

    def evaluate(self, table: Table) -> ExprValue:
        l = self.left.evaluate(table)
        r = self.right.evaluate(table)
        validity = _merge_validity(l.validity, r.validity)
        if self.op in _CMP_OPS:
            data = self._compare(l, r, table)
            return ExprValue(data, validity, DataType.BOOL)
        # arithmetic
        out_dtype = _promote(l.dtype, r.dtype)
        if self.op == "/" and out_dtype.is_integer:
            out_dtype = DataType.FLOAT64
        ldata = l.data.astype(out_dtype.np_dtype)
        rdata = r.data.astype(out_dtype.np_dtype)
        if self.op == "+":
            data = ldata + rdata
        elif self.op == "-":
            data = ldata - rdata
        elif self.op == "*":
            data = ldata * rdata
        elif self.op == "/":
            data = ldata / jnp.where(rdata == 0, 1, rdata)
            validity = _merge_validity(validity, r.data != 0)
        elif self.op == "%":
            data = jnp.where(rdata == 0, 0, ldata % jnp.where(rdata == 0, 1, rdata))
            validity = _merge_validity(validity, r.data != 0)
        else:
            raise NotImplementedError(self.op)
        return ExprValue(data, validity, out_dtype)

    def _compare(self, l: ExprValue, r: ExprValue, table: Table) -> jnp.ndarray:
        # SQL coercion: DATE <op> 'yyyy-mm-dd' parses the string literal.
        if l.dtype == DataType.DATE32 and isinstance(self.right, Literal) and (
            self.right.dtype == DataType.STRING
        ):
            days = parse_date(self.right.value)
            return _apply_cmp(self.op, l.data, jnp.asarray(days, dtype=jnp.int32))
        if r.dtype == DataType.DATE32 and isinstance(self.left, Literal) and (
            self.left.dtype == DataType.STRING
        ):
            days = parse_date(self.left.value)
            return _apply_cmp(
                self.op, jnp.asarray(days, dtype=jnp.int32), r.data
            )
        # String vs string-literal comparison: resolve via sorted dictionary.
        if l.dtype == DataType.STRING or r.dtype == DataType.STRING:
            return self._compare_strings(l, r)
        common = _promote(l.dtype, r.dtype)
        a = l.data.astype(common.np_dtype)
        b = r.data.astype(common.np_dtype)
        return _apply_cmp(self.op, a, b)

    def _compare_strings(self, l: ExprValue, r: ExprValue) -> jnp.ndarray:
        lit_side = None
        col_side = None
        if isinstance(self.right, Literal) and self.right.dtype == DataType.STRING:
            lit_side, col_side, op = self.right, l, self.op
        elif isinstance(self.left, Literal) and self.left.dtype == DataType.STRING:
            lit_side, col_side, op = self.left, r, _flip_cmp(self.op)
        if lit_side is not None:
            d = col_side.dictionary
            if d is None:
                raise ValueError("string column missing dictionary")
            lit = lit_side.value
            codes = col_side.data
            if op in ("==", "!="):
                code = d.code_of(lit)
                if code < 0:
                    same = jnp.zeros(codes.shape, dtype=jnp.bool_)
                else:
                    same = codes == code
                return same if op == "==" else ~same
            # Order comparison: sorted dictionary => searchsorted boundary.
            pos_left = int(np.searchsorted(d.values.astype(str), lit, side="left"))
            pos_right = int(np.searchsorted(d.values.astype(str), lit, side="right"))
            if op == "<":
                return codes < pos_left
            if op == "<=":
                return codes < pos_right
            if op == ">":
                return codes >= pos_right
            if op == ">=":
                return codes >= pos_left
            raise NotImplementedError(op)
        # column vs column. Same dictionary: codes compare directly (sorted
        # dictionaries preserve order). Different dictionaries (e.g. one side
        # is UPPER(...) with a derived dictionary): map both code spaces to
        # ranks in the sorted union vocabulary at trace time — equal strings
        # land on equal ranks and order is preserved, so every comparison op
        # works (shared helper: ops.table.unify_dictionaries).
        if l.dictionary is None or r.dictionary is None:
            raise ValueError("string column comparison requires dictionaries")
        from datafusion_distributed_tpu.ops.table import unify_dictionaries

        _, luts = unify_dictionaries([l.dictionary, r.dictionary])
        a = _remap_codes(l.data, luts[0])
        b = _remap_codes(r.data, luts[1])
        return _apply_cmp(self.op, a, b)

    def output_field(self, schema: Schema) -> Field:
        lf = self.left.output_field(schema)
        rf = self.right.output_field(schema)
        nullable = lf.nullable or rf.nullable or self.op in ("/", "%")
        if self.op in _CMP_OPS:
            return Field(self.display(), DataType.BOOL, nullable)
        out = _promote(lf.dtype, rf.dtype)
        if self.op == "/" and out.is_integer:
            out = DataType.FLOAT64
        return Field(self.display(), out, nullable)

    def display(self) -> str:
        return f"({self.left.display()} {self.op} {self.right.display()})"


def _apply_cmp(op: str, a, b):
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise NotImplementedError(op)


def _flip_cmp(op: str) -> str:
    return {"==": "==", "!=": "!=", "<": ">", "<=": ">=", ">": "<", ">=": "<="}[op]


@dataclass
class BooleanOp(PhysicalExpr):
    """AND/OR with SQL Kleene three-valued logic."""

    op: str  # "and" | "or"
    left: PhysicalExpr
    right: PhysicalExpr

    def children(self):
        return [self.left, self.right]

    def evaluate(self, table: Table) -> ExprValue:
        l = self.left.evaluate(table)
        r = self.right.evaluate(table)
        lv, rv = l.valid_mask(), r.valid_mask()
        ld = l.data.astype(jnp.bool_)
        rd = r.data.astype(jnp.bool_)
        if self.op == "and":
            data = ld & rd
            # null AND true = null; null AND false = false
            validity = (lv & rv) | (lv & ~ld) | (rv & ~rd)
        elif self.op == "or":
            data = ld | rd
            validity = (lv & rv) | (lv & ld) | (rv & rd)
        else:
            raise NotImplementedError(self.op)
        if l.validity is None and r.validity is None:
            validity = None
        return ExprValue(data, validity, DataType.BOOL)

    def output_field(self, schema: Schema) -> Field:
        return Field(self.display(), DataType.BOOL, True)

    def display(self) -> str:
        return f"({self.left.display()} {self.op.upper()} {self.right.display()})"


@dataclass
class Not(PhysicalExpr):
    child: PhysicalExpr

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        return ExprValue(~c.data.astype(jnp.bool_), c.validity, DataType.BOOL)

    def output_field(self, schema: Schema) -> Field:
        return Field(self.display(), DataType.BOOL, True)

    def display(self) -> str:
        return f"NOT {self.child.display()}"


@dataclass
class IsNull(PhysicalExpr):
    child: PhysicalExpr
    negated: bool = False

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        isnull = (
            ~c.valid_mask() if c.validity is not None
            else jnp.zeros(c.data.shape, dtype=jnp.bool_)
        )
        return ExprValue(~isnull if self.negated else isnull, None, DataType.BOOL)

    def output_field(self, schema: Schema) -> Field:
        return Field(self.display(), DataType.BOOL, False)

    def display(self) -> str:
        return f"{self.child.display()} IS {'NOT ' if self.negated else ''}NULL"


@dataclass
class Cast(PhysicalExpr):
    child: PhysicalExpr
    to: DataType

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype == self.to:
            return c
        if c.dtype == DataType.NULL:
            # an untyped NULL casts to anything: all-null column of the
            # target type (dictionary-less for STRING; concat unification
            # adopts a peer vocabulary)
            data = jnp.zeros(c.data.shape, dtype=self.to.np_dtype)
            return ExprValue(data, c.valid_mask() & False, self.to)
        if c.dtype == DataType.STRING:
            # dictionary-LUT cast: parse each vocab entry host-side at trace
            # time, device gathers by code (unparseable entries -> null)
            if c.dictionary is None:
                raise NotImplementedError("string cast without dictionary")
            vals = c.dictionary.values.astype(str)
            parsed = np.zeros(max(len(vals), 1), dtype=self.to.np_dtype)
            ok = np.zeros(max(len(vals), 1), dtype=np.bool_)
            for i, v in enumerate(vals):
                try:
                    if self.to == DataType.DATE32:
                        parsed[i] = parse_date(v)
                    elif self.to.is_float:
                        parsed[i] = float(v)
                    else:
                        parsed[i] = int(float(v))
                    ok[i] = True
                except (ValueError, OverflowError):
                    pass
            idx = jnp.clip(c.data, 0, max(len(vals) - 1, 0))
            data = jnp.asarray(parsed)[idx]
            valid = jnp.asarray(ok)[idx]
            validity = _merge_validity(c.validity, valid)
            return ExprValue(data, validity, self.to)
        if self.to == DataType.STRING:
            raise NotImplementedError("cast to string is not supported")
        return ExprValue(c.data.astype(self.to.np_dtype), c.validity, self.to)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(f.name, self.to, f.nullable)

    def display(self) -> str:
        return f"CAST({self.child.display()} AS {self.to.value})"


def _sql_like_to_regex(pattern: str) -> str:
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        elif ch == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 1
        else:
            out.append(re.escape(ch))
        i += 1
    return "".join(out)


@dataclass
class Like(PhysicalExpr):
    """LIKE on a dictionary column: regex over the host dictionary at trace
    time -> boolean LUT -> device gather by code."""

    child: PhysicalExpr
    pattern: str
    negated: bool = False

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype != DataType.STRING or c.dictionary is None:
            raise ValueError("LIKE requires a dictionary string column")
        rx = re.compile(_sql_like_to_regex(self.pattern), re.DOTALL)
        lut = np.asarray(
            [bool(rx.fullmatch(v)) for v in c.dictionary.values], dtype=np.bool_
        )
        if self.negated:
            lut = ~lut
        if len(lut) == 0:
            data = jnp.full(c.data.shape, bool(self.negated))
        else:
            data = jnp.asarray(lut)[jnp.clip(c.data, 0, len(lut) - 1)]
        return ExprValue(data, c.validity, DataType.BOOL)

    def output_field(self, schema: Schema) -> Field:
        return Field(self.display(), DataType.BOOL, True)

    def display(self) -> str:
        return (
            f"{self.child.display()} {'NOT ' if self.negated else ''}"
            f"LIKE {self.pattern!r}"
        )


@dataclass
class InList(PhysicalExpr):
    child: PhysicalExpr
    values: tuple
    negated: bool = False

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype == DataType.STRING:
            if c.dictionary is None:
                raise ValueError("IN on string requires dictionary")
            codes = [c.dictionary.code_of(v) for v in self.values]
            codes = [x for x in codes if x >= 0]
            if not codes:
                data = jnp.zeros(c.data.shape, dtype=jnp.bool_)
            else:
                data = jnp.isin(c.data, jnp.asarray(codes, dtype=c.data.dtype))
        else:
            items = list(self.values)
            if c.dtype == DataType.DATE32:
                # date IN ('yyyy-mm-dd', ...) — parse string items to days
                items = [
                    parse_date(v) if isinstance(v, str) else v for v in items
                ]
            vals = np.asarray(items, dtype=c.dtype.np_dtype)
            data = jnp.isin(c.data, jnp.asarray(vals))
        if self.negated:
            data = ~data
        return ExprValue(data, c.validity, DataType.BOOL)

    def output_field(self, schema: Schema) -> Field:
        return Field(self.display(), DataType.BOOL, True)

    def display(self) -> str:
        neg = "NOT " if self.negated else ""
        return f"{self.child.display()} {neg}IN {self.values!r}"


@dataclass
class Case(PhysicalExpr):
    """CASE WHEN ... THEN ... [ELSE ...] END (searched form)."""

    branches: tuple  # tuple[(cond PhysicalExpr, value PhysicalExpr), ...]
    otherwise: Optional[PhysicalExpr] = None

    def children(self):
        out = []
        for c, v in self.branches:
            out += [c, v]
        if self.otherwise:
            out.append(self.otherwise)
        return out

    def evaluate(self, table: Table) -> ExprValue:
        results = [(c.evaluate(table), v.evaluate(table)) for c, v in self.branches]
        out_dtype = results[0][1].dtype
        for _, v in results[1:]:
            out_dtype = _promote(out_dtype, v.dtype)
        if self.otherwise is not None:
            else_v = self.otherwise.evaluate(table)
            out_dtype = _promote(out_dtype, else_v.dtype)
            data = else_v.data.astype(out_dtype.np_dtype)
            validity = else_v.valid_mask()
        else:
            cap = table.capacity
            data = jnp.zeros(cap, dtype=out_dtype.np_dtype)
            validity = jnp.zeros(cap, dtype=jnp.bool_)
        # Apply branches in reverse so the FIRST matching branch wins.
        for cond, val in reversed(results):
            take = cond.data.astype(jnp.bool_) & cond.valid_mask()
            data = jnp.where(take, val.data.astype(out_dtype.np_dtype), data)
            validity = jnp.where(take, val.valid_mask(), validity)
        return ExprValue(data, validity, out_dtype)

    def output_field(self, schema: Schema) -> Field:
        out = self.branches[0][1].output_field(schema).dtype
        for _, v in self.branches[1:]:
            out = _promote(out, v.output_field(schema).dtype)
        if self.otherwise is not None:
            out = _promote(out, self.otherwise.output_field(schema).dtype)
        return Field(self.display(), out, True)

    def display(self) -> str:
        parts = " ".join(
            f"WHEN {c.display()} THEN {v.display()}" for c, v in self.branches
        )
        e = f" ELSE {self.otherwise.display()}" if self.otherwise else ""
        return f"CASE {parts}{e} END"


def _civil_from_days(z: jnp.ndarray):
    """days-since-epoch -> (year, month, day), vectorized (Howard Hinnant's
    public-domain civil_from_days algorithm, integer-only so it runs on the
    VPU)."""
    z = z.astype(jnp.int32) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - doe // 1460 + doe // 36524 - doe // 146096, 365
    )
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


@dataclass
class Extract(PhysicalExpr):
    """EXTRACT(part FROM x). DATE32 children are days since epoch; integer
    children are interpreted as epoch SECONDS (the ClickBench convention:
    `extract(minute from to_timestamp_seconds("EventTime"))`)."""

    part: str
    child: PhysicalExpr

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype == DataType.DATE32:
            days = c.data
            secs_of_day = None
        else:
            days = jnp.floor_divide(c.data.astype(jnp.int32), 86400)
            secs_of_day = jnp.mod(c.data.astype(jnp.int32), 86400)
        if self.part in ("hour", "minute", "second"):
            if secs_of_day is None:
                secs_of_day = jnp.zeros_like(days)
            out = {
                "hour": secs_of_day // 3600,
                "minute": (secs_of_day // 60) % 60,
                "second": secs_of_day % 60,
            }[self.part]
        else:
            y, m, d = _civil_from_days(days)
            out = {"year": y, "month": m, "day": d}[self.part]
        return ExprValue(out.astype(DataType.INT64.np_dtype), c.validity, DataType.INT64)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.display(), DataType.INT64, f.nullable)

    def display(self) -> str:
        return f"EXTRACT({self.part} FROM {self.child.display()})"


@dataclass
class DateTrunc(PhysicalExpr):
    """DATE_TRUNC(unit, x) over epoch-seconds integers (ClickBench) or
    DATE32 days: truncate to the unit boundary, keeping the input dtype."""

    unit: str
    child: PhysicalExpr

    _SECONDS = {"second": 1, "minute": 60, "hour": 3600, "day": 86400}

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        unit = self.unit.lower()
        if c.dtype == DataType.DATE32:
            if unit in ("second", "minute", "hour", "day"):
                return c
            raise NotImplementedError(f"DATE_TRUNC {unit} on date32")
        step = self._SECONDS.get(unit)
        if step is None:
            raise NotImplementedError(f"DATE_TRUNC unit {unit}")
        data = c.data - jnp.mod(c.data, step)
        return ExprValue(data, c.validity, c.dtype)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.display(), f.dtype, f.nullable)

    def display(self) -> str:
        return f"DATE_TRUNC('{self.unit}', {self.child.display()})"


@dataclass
class Substring(PhysicalExpr):
    """SUBSTRING on a dictionary string column: transforms the dictionary on
    the host at trace time and remaps codes (derived dictionary)."""

    child: PhysicalExpr
    start: int  # 1-based, SQL semantics
    length: Optional[int]

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype != DataType.STRING or c.dictionary is None:
            raise ValueError("SUBSTRING requires a dictionary string column")
        vals = c.dictionary.values
        # SQL semantics: positions before 1 exist but hold nothing, so a
        # start of 0 with FOR 2 yields just the first character.
        begin = self.start - 1
        b = max(begin, 0)
        if self.length is None:
            derive = lambda vs: [v[b:] for v in vs]  # noqa: E731
        else:
            end = begin + self.length
            derive = lambda vs: [  # noqa: E731
                v[b:end] if end > b else "" for v in vs
            ]
        new_dict, inverse = _derived_dictionary(
            c.dictionary, ("substr", self.start, self.length), derive
        )
        lut = jnp.asarray(inverse)
        if len(vals) == 0:
            codes = c.data
        else:
            codes = lut[jnp.clip(c.data, 0, len(vals) - 1)]
        return ExprValue(codes, c.validity, DataType.STRING, new_dict)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.display(), DataType.STRING, f.nullable)

    def display(self) -> str:
        ln = f" FOR {self.length}" if self.length is not None else ""
        return f"SUBSTRING({self.child.display()} FROM {self.start}{ln})"


@dataclass
class Coalesce(PhysicalExpr):
    """COALESCE(a, b, ...): first non-null value per row. String children
    resolve through a union dictionary built at trace time (the derived-
    dictionary pattern of Substring)."""

    args: tuple

    def children(self):
        return list(self.args)

    def evaluate(self, table: Table) -> ExprValue:
        vals = [a.evaluate(table) for a in self.args]
        if any(v.dtype == DataType.STRING for v in vals):
            return self._evaluate_strings(vals, table)
        out_dtype = vals[0].dtype
        for v in vals[1:]:
            out_dtype = _promote(out_dtype, v.dtype)
        data = vals[-1].data.astype(out_dtype.np_dtype)
        validity = vals[-1].valid_mask()
        for v in reversed(vals[:-1]):
            take = v.valid_mask()
            data = jnp.where(take, v.data.astype(out_dtype.np_dtype), data)
            validity = take | validity
        if all(v.validity is None for v in vals):
            validity = None
        elif any(v.validity is None for v in vals):
            validity = None  # some child is always valid -> result is too
        return ExprValue(data, validity, out_dtype)

    def _evaluate_strings(self, vals, table: Table) -> ExprValue:
        if not all(v.dtype == DataType.STRING for v in vals):
            raise ValueError("COALESCE mixes string and non-string types")
        from datafusion_distributed_tpu.ops.table import unify_dictionaries

        union, luts = unify_dictionaries([v.dictionary for v in vals])
        data = jnp.zeros(table.capacity, dtype=np.int32)
        validity = jnp.zeros(table.capacity, dtype=jnp.bool_)
        for v, lut in zip(reversed(vals), reversed(luts)):
            codes = _remap_codes(v.data, lut)
            take = v.valid_mask()
            data = jnp.where(take, codes, data)
            validity = take | validity
        out_validity = None if any(v.validity is None for v in vals) else (
            validity
        )
        return ExprValue(data, out_validity, DataType.STRING, union)

    def output_field(self, schema: Schema) -> Field:
        f0 = self.args[0].output_field(schema)
        out = f0.dtype
        for a in self.args[1:]:
            fa = a.output_field(schema)
            if out != DataType.STRING or fa.dtype != DataType.STRING:
                out = _promote(out, fa.dtype)
        nullable = all(a.output_field(schema).nullable for a in self.args)
        return Field(self.display(), out, nullable)

    def display(self) -> str:
        inner = ", ".join(a.display() for a in self.args)
        return f"COALESCE({inner})"


@dataclass
class Abs(PhysicalExpr):
    child: PhysicalExpr

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        return ExprValue(jnp.abs(c.data), c.validity, c.dtype)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.display(), f.dtype, f.nullable)

    def display(self) -> str:
        return f"ABS({self.child.display()})"


@dataclass
class Round(PhysicalExpr):
    child: PhysicalExpr
    digits: int = 0

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype.is_integer:
            return c
        scale = 10.0 ** self.digits
        data = jnp.round(c.data * scale) / scale
        return ExprValue(data, c.validity, c.dtype)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.display(), f.dtype, f.nullable)

    def display(self) -> str:
        return f"ROUND({self.child.display()}, {self.digits})"


@dataclass
class StringCase(PhysicalExpr):
    """UPPER/LOWER on a dictionary string column: host-side dictionary
    transform + code remap (same pattern as Substring)."""

    child: PhysicalExpr
    upper: bool

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype != DataType.STRING or c.dictionary is None:
            raise ValueError("UPPER/LOWER requires a dictionary string column")
        vals = c.dictionary.values
        new_dict, inverse = _derived_dictionary(
            c.dictionary, ("case", self.upper),
            lambda vs: (np.char.upper if self.upper else np.char.lower)(
                vs.astype(str)
            ),
        )
        if len(vals) == 0:
            return ExprValue(c.data, c.validity, DataType.STRING, new_dict)
        lut = jnp.asarray(inverse)
        codes = lut[jnp.clip(c.data, 0, len(vals) - 1)]
        return ExprValue(codes, c.validity, DataType.STRING, new_dict)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.display(), DataType.STRING, f.nullable)

    def display(self) -> str:
        fn = "UPPER" if self.upper else "LOWER"
        return f"{fn}({self.child.display()})"


@dataclass
class StrLength(PhysicalExpr):
    """LENGTH(str): dictionary-LUT transform (host computes per-vocab-entry
    lengths at trace time; device gathers by code)."""

    child: PhysicalExpr

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype != DataType.STRING or c.dictionary is None:
            raise ValueError("LENGTH requires a dictionary string column")
        vals = c.dictionary.values.astype(str)
        lut = np.asarray([len(v) for v in vals], dtype=np.int32)
        if len(lut) == 0:
            data = jnp.zeros(c.data.shape, dtype=jnp.int32)
        else:
            data = jnp.asarray(lut)[jnp.clip(c.data, 0, len(lut) - 1)]
        return ExprValue(data, c.validity, DataType.INT32)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.display(), DataType.INT32, f.nullable)

    def display(self) -> str:
        return f"LENGTH({self.child.display()})"


@dataclass
class RegexpReplace(PhysicalExpr):
    """REGEXP_REPLACE(str, pattern, replacement): host re.sub over the
    dictionary at trace time, derived dictionary + code remap."""

    child: PhysicalExpr
    pattern: str
    replacement: str

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype != DataType.STRING or c.dictionary is None:
            raise ValueError(
                "REGEXP_REPLACE requires a dictionary string column"
            )
        rx = re.compile(self.pattern)
        # SQL regex replacement uses \1 backrefs; python re.sub shares that
        repl = self.replacement
        vals = c.dictionary.values
        new_dict, inverse = _derived_dictionary(
            c.dictionary, ("re", self.pattern, repl),
            lambda vs: [rx.sub(repl, v) for v in vs.astype(str)],
        )
        if len(vals) == 0:
            return ExprValue(c.data, c.validity, DataType.STRING, new_dict)
        lut = jnp.asarray(inverse)
        codes = lut[jnp.clip(c.data, 0, len(vals) - 1)]
        return ExprValue(codes, c.validity, DataType.STRING, new_dict)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.display(), DataType.STRING, f.nullable)

    def display(self) -> str:
        return (
            f"REGEXP_REPLACE({self.child.display()}, "
            f"{self.pattern!r}, {self.replacement!r})"
        )


_CONCAT_COMBO_CAP = 1 << 22
_CONCAT_DICT_CACHE: dict = {}


@dataclass
class ConcatStrings(PhysicalExpr):
    """CONCAT over string columns/literals: the combined dictionary is the
    cross product of the children's dictionaries (built host-side at trace
    time), and per-row codes compose positionally — device work stays a
    couple of integer ops + one gather. Bounded by the combo cap; wide-NDV
    concatenations should dictionary-encode upstream first."""

    args: tuple

    def children(self):
        return list(self.args)

    def evaluate(self, table: Table) -> ExprValue:
        vals = [a.evaluate(table) for a in self.args]
        dict_parts = []  # (index into vals, values array)
        for i, v in enumerate(vals):
            if v.dtype != DataType.STRING or v.dictionary is None:
                raise ValueError("CONCAT requires string children")
            dict_parts.append((i, v.dictionary.values.astype(str)))
        sizes = [max(len(d), 1) for _, d in dict_parts]
        total = 1
        for s in sizes:
            total *= s
        if total > _CONCAT_COMBO_CAP:
            raise ValueError(
                f"CONCAT dictionary cross product {total} exceeds cap "
                f"{_CONCAT_COMBO_CAP}"
            )
        # combo index = sum(code_i * stride_i), strides right-to-left
        strides = [1] * len(sizes)
        for i in range(len(sizes) - 2, -1, -1):
            strides[i] = strides[i + 1] * sizes[i + 1]
        # the derived dictionary depends only on the input dictionaries —
        # memoize per dict-id tuple so per-task re-traces (workers, retries)
        # don't redo the cross-product host work
        cache_key = tuple(
            v.dictionary.dict_id for v in vals
        )
        cached = _CONCAT_DICT_CACHE.get(cache_key)
        if cached is None:
            import itertools as _it

            combos = [""] * total
            for flat, parts in enumerate(
                _it.product(*[d if len(d) else [""] for _, d in dict_parts])
            ):
                combos[flat] = "".join(parts)
            uniq, inverse = np.unique(
                np.asarray(combos, dtype=object).astype(str),
                return_inverse=True,
            )
            cached = (Dictionary(uniq.astype(object)),
                      inverse.astype(np.int32))
            if len(_CONCAT_DICT_CACHE) > 64:
                _CONCAT_DICT_CACHE.clear()
            _CONCAT_DICT_CACHE[cache_key] = cached
        new_dict, inverse_np = cached
        lut = jnp.asarray(inverse_np)
        flat_code = jnp.zeros(table.capacity, dtype=jnp.int32)
        for (i, d), size, stride in zip(dict_parts, sizes, strides):
            code = jnp.clip(vals[i].data, 0, size - 1)
            flat_code = flat_code + code * np.int32(stride)
        codes = lut[jnp.clip(flat_code, 0, total - 1)]
        validity = _merge_validity(*[v.validity for v in vals])
        return ExprValue(codes, validity, DataType.STRING, new_dict)

    def output_field(self, schema: Schema) -> Field:
        nullable = any(a.output_field(schema).nullable for a in self.args)
        return Field(self.display(), DataType.STRING, nullable)

    def display(self) -> str:
        inner = ", ".join(a.display() for a in self.args)
        return f"CONCAT({inner})"


@dataclass
class Alias(PhysicalExpr):
    child: PhysicalExpr
    name: str

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        return self.child.evaluate(table)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.name, f.dtype, f.nullable)

    def display(self) -> str:
        return f"{self.child.display()} AS {self.name}"


@dataclass
class Negate(PhysicalExpr):
    child: PhysicalExpr

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        return ExprValue(-c.data, c.validity, c.dtype)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(f"(- {f.name})", f.dtype, f.nullable)

    def display(self) -> str:
        return f"(- {self.child.display()})"


def expr_to_column(value: ExprValue) -> Column:
    return Column(value.data, value.validity, value.dtype, value.dictionary)
