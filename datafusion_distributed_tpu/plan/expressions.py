"""Physical expression IR, evaluated to device arrays.

The reference delegates expression evaluation to DataFusion's `PhysicalExpr`
kernels over Arrow arrays (SURVEY.md L0). Here expressions are a small tree IR
that *traces* to jnp operations over the padded device columns — so a whole
filter/projection pipeline fuses into one XLA computation, with no
per-expression materialization (the XLA analogue of Arrow kernel fusion).

Key TPU-first choices:
- SQL three-valued logic is carried as an explicit (data, validity) pair; the
  VPU evaluates both lanes in parallel.
- String comparisons never touch strings on device: dictionaries are sorted,
  so `col op literal` compiles to an int32 code comparison against a host-side
  `searchsorted` of the literal (exact, even for literals absent from the
  dictionary).
- LIKE / IN on strings evaluate the predicate over the *dictionary* on the
  host at trace time and become a boolean lookup-table gather by code — O(NDV)
  host work, O(rows) device work.
"""

from __future__ import annotations

import datetime
import re
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu.ops.table import Column, Dictionary, Table
from datafusion_distributed_tpu.schema import DataType, Field, Schema


# ---------------------------------------------------------------------------
# Evaluation result: device data + optional validity (None = all valid)
# ---------------------------------------------------------------------------


@dataclass
class ExprValue:
    data: jnp.ndarray
    validity: Optional[jnp.ndarray]  # bool array or None (= all valid)
    dtype: DataType
    dictionary: Optional[Dictionary] = None

    def valid_mask(self) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones(self.data.shape, dtype=jnp.bool_)
        return self.validity


def _merge_validity(*vs: Optional[jnp.ndarray]) -> Optional[jnp.ndarray]:
    present = [v for v in vs if v is not None]
    if not present:
        return None
    out = present[0]
    for v in present[1:]:
        out = out & v
    return out


def parse_date(s: str) -> int:
    """'YYYY-MM-DD' -> int32 days since epoch."""
    d = datetime.date.fromisoformat(s)
    return (d - datetime.date(1970, 1, 1)).days


# ---------------------------------------------------------------------------
# Expression nodes
# ---------------------------------------------------------------------------


class PhysicalExpr:
    """Base class. ``evaluate(table)`` returns an ExprValue whose arrays have
    the table's capacity; garbage rows (>= num_rows) may hold anything."""

    def evaluate(self, table: Table) -> ExprValue:
        raise NotImplementedError

    def output_field(self, schema: Schema) -> Field:
        raise NotImplementedError

    def children(self) -> list["PhysicalExpr"]:
        return []

    def display(self) -> str:
        return repr(self)


@dataclass
class Col(PhysicalExpr):
    name: str

    def evaluate(self, table: Table) -> ExprValue:
        c = table.column(self.name)
        return ExprValue(c.data, c.validity, c.dtype, c.dictionary)

    def output_field(self, schema: Schema) -> Field:
        return schema.field(self.name)

    def display(self) -> str:
        return self.name


@dataclass
class Literal(PhysicalExpr):
    value: Any  # python scalar: int/float/bool/str/None; dates pre-parsed int
    dtype: DataType

    def evaluate(self, table: Table) -> ExprValue:
        cap = table.capacity
        if self.value is None:
            data = jnp.zeros(cap, dtype=self.dtype.np_dtype)
            return ExprValue(data, jnp.zeros(cap, dtype=jnp.bool_), self.dtype)
        if self.dtype == DataType.STRING:
            # Bare string literal with no column context: keep as dtype STRING
            # with a private single-entry dictionary. Comparisons against
            # columns resolve via the column's dictionary (see Cmp).
            d = Dictionary.from_strings([self.value])
            data = jnp.zeros(cap, dtype=np.int32)
            return ExprValue(data, None, self.dtype, d)
        val = np.asarray(self.value, dtype=self.dtype.np_dtype)
        data = jnp.full(cap, val, dtype=self.dtype.np_dtype)
        return ExprValue(data, None, self.dtype)

    def output_field(self, schema: Schema) -> Field:
        return Field(str(self.value), self.dtype, nullable=self.value is None)

    def display(self) -> str:
        return repr(self.value)


_ARITH_OPS = {"+", "-", "*", "/", "%"}
_CMP_OPS = {"==", "!=", "<", "<=", ">", ">="}


def _promote(a: DataType, b: DataType) -> DataType:
    order = [
        DataType.BOOL,
        DataType.INT32,
        DataType.DATE32,
        DataType.INT64,
        DataType.FLOAT32,
        DataType.FLOAT64,
    ]
    if a == b:
        return a
    if a == DataType.STRING or b == DataType.STRING:
        return DataType.STRING
    return max(a, b, key=order.index)


@dataclass
class BinaryOp(PhysicalExpr):
    """Arithmetic/comparison. String comparisons compile to code comparisons
    against the column dictionary (sorted => order-preserving)."""

    op: str
    left: PhysicalExpr
    right: PhysicalExpr

    def children(self):
        return [self.left, self.right]

    def evaluate(self, table: Table) -> ExprValue:
        l = self.left.evaluate(table)
        r = self.right.evaluate(table)
        validity = _merge_validity(l.validity, r.validity)
        if self.op in _CMP_OPS:
            data = self._compare(l, r, table)
            return ExprValue(data, validity, DataType.BOOL)
        # arithmetic
        out_dtype = _promote(l.dtype, r.dtype)
        if self.op == "/" and out_dtype.is_integer:
            out_dtype = DataType.FLOAT64
        ldata = l.data.astype(out_dtype.np_dtype)
        rdata = r.data.astype(out_dtype.np_dtype)
        if self.op == "+":
            data = ldata + rdata
        elif self.op == "-":
            data = ldata - rdata
        elif self.op == "*":
            data = ldata * rdata
        elif self.op == "/":
            data = ldata / jnp.where(rdata == 0, 1, rdata)
            validity = _merge_validity(validity, r.data != 0)
        elif self.op == "%":
            data = jnp.where(rdata == 0, 0, ldata % jnp.where(rdata == 0, 1, rdata))
            validity = _merge_validity(validity, r.data != 0)
        else:
            raise NotImplementedError(self.op)
        return ExprValue(data, validity, out_dtype)

    def _compare(self, l: ExprValue, r: ExprValue, table: Table) -> jnp.ndarray:
        # SQL coercion: DATE <op> 'yyyy-mm-dd' parses the string literal.
        if l.dtype == DataType.DATE32 and isinstance(self.right, Literal) and (
            self.right.dtype == DataType.STRING
        ):
            days = parse_date(self.right.value)
            return _apply_cmp(self.op, l.data, jnp.asarray(days, dtype=jnp.int32))
        if r.dtype == DataType.DATE32 and isinstance(self.left, Literal) and (
            self.left.dtype == DataType.STRING
        ):
            days = parse_date(self.left.value)
            return _apply_cmp(
                self.op, jnp.asarray(days, dtype=jnp.int32), r.data
            )
        # String vs string-literal comparison: resolve via sorted dictionary.
        if l.dtype == DataType.STRING or r.dtype == DataType.STRING:
            return self._compare_strings(l, r)
        common = _promote(l.dtype, r.dtype)
        a = l.data.astype(common.np_dtype)
        b = r.data.astype(common.np_dtype)
        return _apply_cmp(self.op, a, b)

    def _compare_strings(self, l: ExprValue, r: ExprValue) -> jnp.ndarray:
        lit_side = None
        col_side = None
        if isinstance(self.right, Literal) and self.right.dtype == DataType.STRING:
            lit_side, col_side, op = self.right, l, self.op
        elif isinstance(self.left, Literal) and self.left.dtype == DataType.STRING:
            lit_side, col_side, op = self.left, r, _flip_cmp(self.op)
        if lit_side is not None:
            d = col_side.dictionary
            if d is None:
                raise ValueError("string column missing dictionary")
            lit = lit_side.value
            codes = col_side.data
            if op in ("==", "!="):
                code = d.code_of(lit)
                if code < 0:
                    same = jnp.zeros(codes.shape, dtype=jnp.bool_)
                else:
                    same = codes == code
                return same if op == "==" else ~same
            # Order comparison: sorted dictionary => searchsorted boundary.
            pos_left = int(np.searchsorted(d.values.astype(str), lit, side="left"))
            pos_right = int(np.searchsorted(d.values.astype(str), lit, side="right"))
            if op == "<":
                return codes < pos_left
            if op == "<=":
                return codes < pos_right
            if op == ">":
                return codes >= pos_right
            if op == ">=":
                return codes >= pos_left
            raise NotImplementedError(op)
        # column vs column: only valid when dictionaries are unified
        if l.dictionary != r.dictionary:
            raise ValueError(
                "string column comparison requires a unified dictionary"
            )
        return _apply_cmp(self.op, l.data, r.data)

    def output_field(self, schema: Schema) -> Field:
        lf = self.left.output_field(schema)
        rf = self.right.output_field(schema)
        nullable = lf.nullable or rf.nullable or self.op in ("/", "%")
        if self.op in _CMP_OPS:
            return Field(self.display(), DataType.BOOL, nullable)
        out = _promote(lf.dtype, rf.dtype)
        if self.op == "/" and out.is_integer:
            out = DataType.FLOAT64
        return Field(self.display(), out, nullable)

    def display(self) -> str:
        return f"({self.left.display()} {self.op} {self.right.display()})"


def _apply_cmp(op: str, a, b):
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise NotImplementedError(op)


def _flip_cmp(op: str) -> str:
    return {"==": "==", "!=": "!=", "<": ">", "<=": ">=", ">": "<", ">=": "<="}[op]


@dataclass
class BooleanOp(PhysicalExpr):
    """AND/OR with SQL Kleene three-valued logic."""

    op: str  # "and" | "or"
    left: PhysicalExpr
    right: PhysicalExpr

    def children(self):
        return [self.left, self.right]

    def evaluate(self, table: Table) -> ExprValue:
        l = self.left.evaluate(table)
        r = self.right.evaluate(table)
        lv, rv = l.valid_mask(), r.valid_mask()
        ld = l.data.astype(jnp.bool_)
        rd = r.data.astype(jnp.bool_)
        if self.op == "and":
            data = ld & rd
            # null AND true = null; null AND false = false
            validity = (lv & rv) | (lv & ~ld) | (rv & ~rd)
        elif self.op == "or":
            data = ld | rd
            validity = (lv & rv) | (lv & ld) | (rv & rd)
        else:
            raise NotImplementedError(self.op)
        if l.validity is None and r.validity is None:
            validity = None
        return ExprValue(data, validity, DataType.BOOL)

    def output_field(self, schema: Schema) -> Field:
        return Field(self.display(), DataType.BOOL, True)

    def display(self) -> str:
        return f"({self.left.display()} {self.op.upper()} {self.right.display()})"


@dataclass
class Not(PhysicalExpr):
    child: PhysicalExpr

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        return ExprValue(~c.data.astype(jnp.bool_), c.validity, DataType.BOOL)

    def output_field(self, schema: Schema) -> Field:
        return Field(self.display(), DataType.BOOL, True)

    def display(self) -> str:
        return f"NOT {self.child.display()}"


@dataclass
class IsNull(PhysicalExpr):
    child: PhysicalExpr
    negated: bool = False

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        isnull = (
            ~c.valid_mask() if c.validity is not None
            else jnp.zeros(c.data.shape, dtype=jnp.bool_)
        )
        return ExprValue(~isnull if self.negated else isnull, None, DataType.BOOL)

    def output_field(self, schema: Schema) -> Field:
        return Field(self.display(), DataType.BOOL, False)

    def display(self) -> str:
        return f"{self.child.display()} IS {'NOT ' if self.negated else ''}NULL"


@dataclass
class Cast(PhysicalExpr):
    child: PhysicalExpr
    to: DataType

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype == self.to:
            return c
        if c.dtype == DataType.STRING or self.to == DataType.STRING:
            raise NotImplementedError("string casts happen at plan time")
        return ExprValue(c.data.astype(self.to.np_dtype), c.validity, self.to)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(f.name, self.to, f.nullable)

    def display(self) -> str:
        return f"CAST({self.child.display()} AS {self.to.value})"


def _sql_like_to_regex(pattern: str) -> str:
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        elif ch == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 1
        else:
            out.append(re.escape(ch))
        i += 1
    return "".join(out)


@dataclass
class Like(PhysicalExpr):
    """LIKE on a dictionary column: regex over the host dictionary at trace
    time -> boolean LUT -> device gather by code."""

    child: PhysicalExpr
    pattern: str
    negated: bool = False

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype != DataType.STRING or c.dictionary is None:
            raise ValueError("LIKE requires a dictionary string column")
        rx = re.compile(_sql_like_to_regex(self.pattern), re.DOTALL)
        lut = np.asarray(
            [bool(rx.fullmatch(v)) for v in c.dictionary.values], dtype=np.bool_
        )
        if self.negated:
            lut = ~lut
        if len(lut) == 0:
            data = jnp.full(c.data.shape, bool(self.negated))
        else:
            data = jnp.asarray(lut)[jnp.clip(c.data, 0, len(lut) - 1)]
        return ExprValue(data, c.validity, DataType.BOOL)

    def output_field(self, schema: Schema) -> Field:
        return Field(self.display(), DataType.BOOL, True)

    def display(self) -> str:
        return (
            f"{self.child.display()} {'NOT ' if self.negated else ''}"
            f"LIKE {self.pattern!r}"
        )


@dataclass
class InList(PhysicalExpr):
    child: PhysicalExpr
    values: tuple
    negated: bool = False

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype == DataType.STRING:
            if c.dictionary is None:
                raise ValueError("IN on string requires dictionary")
            codes = [c.dictionary.code_of(v) for v in self.values]
            codes = [x for x in codes if x >= 0]
            if not codes:
                data = jnp.zeros(c.data.shape, dtype=jnp.bool_)
            else:
                data = jnp.isin(c.data, jnp.asarray(codes, dtype=c.data.dtype))
        else:
            vals = np.asarray(list(self.values), dtype=c.dtype.np_dtype)
            data = jnp.isin(c.data, jnp.asarray(vals))
        if self.negated:
            data = ~data
        return ExprValue(data, c.validity, DataType.BOOL)

    def output_field(self, schema: Schema) -> Field:
        return Field(self.display(), DataType.BOOL, True)

    def display(self) -> str:
        neg = "NOT " if self.negated else ""
        return f"{self.child.display()} {neg}IN {self.values!r}"


@dataclass
class Case(PhysicalExpr):
    """CASE WHEN ... THEN ... [ELSE ...] END (searched form)."""

    branches: tuple  # tuple[(cond PhysicalExpr, value PhysicalExpr), ...]
    otherwise: Optional[PhysicalExpr] = None

    def children(self):
        out = []
        for c, v in self.branches:
            out += [c, v]
        if self.otherwise:
            out.append(self.otherwise)
        return out

    def evaluate(self, table: Table) -> ExprValue:
        results = [(c.evaluate(table), v.evaluate(table)) for c, v in self.branches]
        out_dtype = results[0][1].dtype
        for _, v in results[1:]:
            out_dtype = _promote(out_dtype, v.dtype)
        if self.otherwise is not None:
            else_v = self.otherwise.evaluate(table)
            out_dtype = _promote(out_dtype, else_v.dtype)
            data = else_v.data.astype(out_dtype.np_dtype)
            validity = else_v.valid_mask()
        else:
            cap = table.capacity
            data = jnp.zeros(cap, dtype=out_dtype.np_dtype)
            validity = jnp.zeros(cap, dtype=jnp.bool_)
        # Apply branches in reverse so the FIRST matching branch wins.
        for cond, val in reversed(results):
            take = cond.data.astype(jnp.bool_) & cond.valid_mask()
            data = jnp.where(take, val.data.astype(out_dtype.np_dtype), data)
            validity = jnp.where(take, val.valid_mask(), validity)
        return ExprValue(data, validity, out_dtype)

    def output_field(self, schema: Schema) -> Field:
        out = self.branches[0][1].output_field(schema).dtype
        for _, v in self.branches[1:]:
            out = _promote(out, v.output_field(schema).dtype)
        if self.otherwise is not None:
            out = _promote(out, self.otherwise.output_field(schema).dtype)
        return Field(self.display(), out, True)

    def display(self) -> str:
        parts = " ".join(
            f"WHEN {c.display()} THEN {v.display()}" for c, v in self.branches
        )
        e = f" ELSE {self.otherwise.display()}" if self.otherwise else ""
        return f"CASE {parts}{e} END"


def _civil_from_days(z: jnp.ndarray):
    """days-since-epoch -> (year, month, day), vectorized (Howard Hinnant's
    public-domain civil_from_days algorithm, integer-only so it runs on the
    VPU)."""
    z = z.astype(jnp.int32) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - doe // 1460 + doe // 36524 - doe // 146096, 365
    )
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


@dataclass
class Extract(PhysicalExpr):
    """EXTRACT(year|month|day FROM date_col)."""

    part: str
    child: PhysicalExpr

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        y, m, d = _civil_from_days(c.data)
        out = {"year": y, "month": m, "day": d}[self.part]
        return ExprValue(out.astype(DataType.INT64.np_dtype), c.validity, DataType.INT64)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.display(), DataType.INT64, f.nullable)

    def display(self) -> str:
        return f"EXTRACT({self.part} FROM {self.child.display()})"


@dataclass
class Substring(PhysicalExpr):
    """SUBSTRING on a dictionary string column: transforms the dictionary on
    the host at trace time and remaps codes (derived dictionary)."""

    child: PhysicalExpr
    start: int  # 1-based, SQL semantics
    length: Optional[int]

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        if c.dtype != DataType.STRING or c.dictionary is None:
            raise ValueError("SUBSTRING requires a dictionary string column")
        vals = c.dictionary.values
        # SQL semantics: positions before 1 exist but hold nothing, so a
        # start of 0 with FOR 2 yields just the first character.
        begin = self.start - 1
        if self.length is None:
            b = max(begin, 0)
            derived = np.asarray([v[b:] for v in vals], dtype=object)
        else:
            end = begin + self.length
            b = max(begin, 0)
            derived = np.asarray(
                [v[b:end] if end > b else "" for v in vals], dtype=object
            )
        uniq, inverse = np.unique(derived.astype(str), return_inverse=True)
        new_dict = Dictionary(uniq.astype(object))
        lut = jnp.asarray(inverse.astype(np.int32))
        if len(vals) == 0:
            codes = c.data
        else:
            codes = lut[jnp.clip(c.data, 0, len(vals) - 1)]
        return ExprValue(codes, c.validity, DataType.STRING, new_dict)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.display(), DataType.STRING, f.nullable)

    def display(self) -> str:
        ln = f" FOR {self.length}" if self.length is not None else ""
        return f"SUBSTRING({self.child.display()} FROM {self.start}{ln})"


@dataclass
class Alias(PhysicalExpr):
    child: PhysicalExpr
    name: str

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        return self.child.evaluate(table)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(self.name, f.dtype, f.nullable)

    def display(self) -> str:
        return f"{self.child.display()} AS {self.name}"


@dataclass
class Negate(PhysicalExpr):
    child: PhysicalExpr

    def children(self):
        return [self.child]

    def evaluate(self, table: Table) -> ExprValue:
        c = self.child.evaluate(table)
        return ExprValue(-c.data, c.validity, c.dtype)

    def output_field(self, schema: Schema) -> Field:
        f = self.child.output_field(schema)
        return Field(f"(- {f.name})", f.dtype, f.nullable)

    def display(self) -> str:
        return f"(- {self.child.display()})"


def expr_to_column(value: ExprValue) -> Column:
    return Column(value.data, value.validity, value.dtype, value.dictionary)
