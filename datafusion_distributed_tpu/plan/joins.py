"""Physical join + union operators.

The reference uses DataFusion's HashJoinExec/NestedLoopJoinExec/CrossJoinExec
and wraps their build sides in BroadcastExec when distributing
(`/root/reference/src/distributed_planner/insert_broadcast.rs`). Here the
join kernel is ops/join.py's vectorized build/probe/expand; this module is the
plan-tree layer: key materialization, residual predicates, mark/semi/anti
modes, and capacity policy.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax.numpy as jnp

from datafusion_distributed_tpu.ops.join import build_join_table, hash_join
from datafusion_distributed_tpu.ops.table import (
    Column,
    Table,
    concat_tables,
    round_up_pow2,
)
from datafusion_distributed_tpu.plan.expressions import PhysicalExpr
from datafusion_distributed_tpu.plan.physical import ExecContext, ExecutionPlan
from datafusion_distributed_tpu.schema import DataType, Field, Schema

_PROBE_IDX = "__probe_idx"


_MAX_DERIVED_JOIN_CAPACITY = 1 << 25


class HashJoinExec(ExecutionPlan):
    """Hash join. probe = left child (preserved side), build = right child.

    join_type: inner | left | semi | anti | mark.
    Keys are column names (the planner materializes key expressions into
    columns below the join). `residual` is an extra predicate over the
    combined schema, used for non-equi correlated EXISTS (TPC-H q21 shape).
    """

    def __init__(
        self,
        probe: ExecutionPlan,
        build: ExecutionPlan,
        probe_keys: Sequence[str],
        build_keys: Sequence[str],
        join_type: str,
        residual: Optional[PhysicalExpr] = None,
        out_capacity: Optional[int] = None,
        num_slots: Optional[int] = None,
        mark_name: str = "__mark",
        expansion_factor: float = 1.0,
        null_aware: bool = False,
    ):
        super().__init__()
        self.probe = probe
        self.build = build
        # NOT IN semantics: a NULL anywhere in the subquery result means no
        # probe row passes, and NULL probe keys never pass.
        self.null_aware = null_aware
        self.probe_keys = list(probe_keys)
        self.build_keys = list(build_keys)
        self.join_type = join_type
        self.residual = residual
        self.mark_name = mark_name
        self.expansion_factor = expansion_factor
        self.num_slots = num_slots or min(
            round_up_pow2(2 * max(build.output_capacity(), 8)), 1 << 21
        )
        if out_capacity is None:
            base = probe.output_capacity()
            # hard ceiling on the EXPANSION (chained joins multiply
            # capacities and the overflow retry quadruples expansion
            # factors — unbounded, the product can demand terabytes;
            # observed: a 3.3 TB allocation request). Never clamp below the
            # probe side's own capacity: a 1x join must always fit.
            ceiling = max(
                _MAX_DERIVED_JOIN_CAPACITY, round_up_pow2(max(base, 8))
            )
            out_capacity = min(
                round_up_pow2(max(int(base * expansion_factor), 8)),
                ceiling,
            )
        self.out_capacity = out_capacity

    def children(self):
        return [self.probe, self.build]

    def with_new_children(self, children):
        return HashJoinExec(
            children[0], children[1], self.probe_keys, self.build_keys,
            self.join_type, self.residual, self.out_capacity, self.num_slots,
            self.mark_name, self.expansion_factor, self.null_aware,
        )

    def schema(self):
        if self.join_type in ("semi", "anti"):
            return self.probe.schema()
        if self.join_type == "mark":
            return Schema(
                list(self.probe.schema().fields)
                + [Field(self.mark_name, DataType.BOOL, False)]
            )
        left = list(self.probe.schema().fields)
        right = [
            Field(f.name, f.dtype, True if self.join_type == "left" else f.nullable)
            for f in self.build.schema().fields
        ]
        return Schema(left + right)

    def output_capacity(self):
        if self.join_type in ("semi", "anti", "mark"):
            return self.probe.output_capacity()
        return self.out_capacity

    def _execute(self, ctx: ExecContext) -> Table:
        probe = self.probe.execute(ctx)
        build = self.build.execute(ctx)
        probe, build = _unify_key_dictionaries(
            probe, build, self.probe_keys, self.build_keys
        )
        # shared validity-lane layout: union of both sides' nullability
        lane_plan = []
        for pk, bk in zip(self.probe_keys, self.build_keys):
            lane_plan.append(
                probe.column(pk).validity is not None
                or build.column(bk).validity is not None
            )
        bs = build_join_table(build, self.build_keys, self.num_slots, lane_plan)

        if self.residual is None:
            out, overflow = hash_join(
                probe, bs, self.probe_keys, self.join_type, self.out_capacity
            )
            ctx.record_overflow(self, overflow)
            if self.join_type == "anti" and self.null_aware:
                out = self._null_aware_anti(probe, bs, out)
            if self.join_type == "mark":
                out = out.rename({"__mark": self.mark_name})
            return out

        # Residual path: expand pairs (inner), filter, then fold back.
        pidx = Column(
            jnp.arange(probe.capacity, dtype=DataType.INT64.np_dtype),
            None, DataType.INT64,
        )
        probe2 = probe.with_column(_PROBE_IDX, pidx)
        pairs, overflow = hash_join(
            probe2, bs, self.probe_keys, "inner", self.out_capacity
        )
        ctx.record_overflow(self, overflow)
        v = self.residual.evaluate(pairs)
        ok = v.data.astype(jnp.bool_) & v.valid_mask() & pairs.row_mask()

        if self.join_type == "inner":
            out = pairs.compact(ok)
            names = [n for n in out.names if n != _PROBE_IDX]
            return out.select(names)

        # semi/anti/mark: scatter pair verdicts back onto probe rows
        pair_pidx = pairs.column(_PROBE_IDX).data.astype(jnp.int32)
        match = jnp.zeros(probe.capacity, dtype=jnp.bool_)
        match = match.at[jnp.where(ok, pair_pidx, probe.capacity)].set(
            True, mode="drop"
        )
        live = probe.row_mask()
        if self.join_type == "semi":
            return probe.compact(match)
        if self.join_type == "anti":
            return probe.compact(live & ~match)
        if self.join_type == "mark":
            return probe.with_column(
                self.mark_name, Column(match, None, DataType.BOOL)
            )
        raise NotImplementedError(
            f"join type {self.join_type} with residual predicate"
        )

    def _null_aware_anti(self, probe: Table, bs, anti_result: Table) -> Table:
        """NOT IN: any NULL in the subquery empties the result; NULL probe
        keys are excluded (three-valued logic makes them UNKNOWN)."""
        keep = ~bs.has_null_key
        probe_null = jnp.zeros(anti_result.capacity, dtype=jnp.bool_)
        for k in self.probe_keys:
            v = anti_result.column(k).validity
            if v is not None:
                probe_null = probe_null | ~v
        mask = anti_result.row_mask() & ~probe_null & keep
        return anti_result.compact(mask)

    def display(self):
        ks = ", ".join(
            f"{p}={b}" for p, b in zip(self.probe_keys, self.build_keys)
        )
        res = f" residual={self.residual.display()}" if self.residual else ""
        return (
            f"HashJoin {self.join_type} on [{ks}]{res} "
            f"out_cap={self.out_capacity}"
        )


def _unify_key_dictionaries(probe: Table, build: Table, probe_keys, build_keys):
    """String join keys are dictionary codes; codes from different
    dictionaries are not comparable. Remap both sides onto a sorted union
    dictionary (host-side LUT over static metadata + device gather), the
    analogue of Arrow dictionary unification before a DataFusion hash join."""
    from datafusion_distributed_tpu.ops.table import Dictionary
    import numpy as np

    for pk, bk in zip(probe_keys, build_keys):
        pc = probe.column(pk)
        bc = build.column(bk)
        if pc.dictionary is None and bc.dictionary is None:
            continue
        if pc.dictionary == bc.dictionary:
            continue
        if pc.dictionary is None or bc.dictionary is None:
            raise ValueError(
                f"string join key {pk}/{bk} missing a dictionary"
            )
        union_vals = np.unique(
            np.concatenate([pc.dictionary.values, bc.dictionary.values]).astype(str)
        )
        unified = Dictionary(union_vals.astype(object))

        def remap(col, table, name):
            old = col.dictionary.values.astype(str)
            lut = np.searchsorted(union_vals, old).astype(np.int32)
            lut_dev = jnp.asarray(lut) if len(lut) else jnp.zeros(1, jnp.int32)
            codes = lut_dev[jnp.clip(col.data, 0, max(len(lut) - 1, 0))]
            from datafusion_distributed_tpu.ops.table import Column

            return table.with_column(
                name, Column(codes, col.validity, col.dtype, unified)
            )

        probe = remap(pc, probe, pk)
        build = remap(bc, build, bk)
    return probe, build


_MW_ORIG = "__mw_orig"


@dataclass(frozen=True)
class MultiwayJoinStep:
    """Parameters of one probe step of a fused multiway join — exactly the
    knobs of the binary HashJoinExec the step replaced, so fusion is
    reversible (``to_binary_chain``) without re-deriving capacities and the
    fused plan sizes its tables byte-identically to the chain it fused."""

    probe_keys: tuple
    build_keys: tuple
    join_type: str
    out_capacity: int
    num_slots: int
    residual: Optional[PhysicalExpr] = None
    mark_name: str = "__mark"
    expansion_factor: float = 1.0
    null_aware: bool = False

    @classmethod
    def from_join(cls, j: "HashJoinExec") -> "MultiwayJoinStep":
        return cls(
            probe_keys=tuple(j.probe_keys),
            build_keys=tuple(j.build_keys),
            join_type=j.join_type,
            out_capacity=int(j.out_capacity),
            num_slots=int(j.num_slots),
            residual=j.residual,
            mark_name=j.mark_name,
            expansion_factor=float(j.expansion_factor),
            null_aware=bool(j.null_aware),
        )


class MultiwayHashJoinExec(ExecutionPlan):
    """A fused chain of >= 2 hash joins executed as ONE stage. Children are
    ``[probe, build_1 .. build_K]``; ``steps[k]`` joins the running probe
    stream against ``build_k``. The planner's fusion pass
    (planner/distributed._multiway_fusion_pass) only builds this node when
    every step's probe keys come from the BASE probe stream, which is what
    lets the intermediate shuffles be deleted (re-hashing the same keys to
    the same task count is an identity re-partition) and lets the cascaded
    pallas kernel resolve all K probes in one grid pass.

    Execution is exact by construction: the reference path IS the original
    binary chain (``to_binary_chain``), rebuilt with the captured per-step
    capacities; the cascaded kernel path (DFTPU_PALLAS=1 + static
    eligibility) replaces only the per-step probe loops, feeding their
    resolved slots into the same expansion kernel via
    ``hash_join(precomputed=...)``.
    """

    def __init__(self, probe: ExecutionPlan, builds: Sequence[ExecutionPlan],
                 steps: Sequence[MultiwayJoinStep]):
        super().__init__()
        if len(builds) != len(steps) or len(steps) < 2:
            raise ValueError(
                f"multiway join needs >= 2 steps with one build each; got "
                f"{len(steps)} steps / {len(builds)} builds"
            )
        self.probe = probe
        self.builds = list(builds)
        self.steps = list(steps)
        self._chain_cache: Optional[HashJoinExec] = None

    def children(self):
        return [self.probe] + list(self.builds)

    def with_new_children(self, children):
        return MultiwayHashJoinExec(children[0], list(children[1:]),
                                    self.steps)

    def to_binary_chain(self, rederive: bool = False) -> HashJoinExec:
        """The equivalent binary HashJoinExec chain. ``rederive=True`` drops
        the captured capacities so the chain re-sizes from its (measured)
        children — the bailout path when build estimates lied."""
        cur = self.probe
        for build, s in zip(self.builds, self.steps):
            cur = HashJoinExec(
                cur, build, list(s.probe_keys), list(s.build_keys),
                s.join_type, residual=s.residual,
                out_capacity=None if rederive else s.out_capacity,
                num_slots=None if rederive else s.num_slots,
                mark_name=s.mark_name,
                expansion_factor=s.expansion_factor,
                null_aware=s.null_aware,
            )
        return cur

    def _chain(self) -> HashJoinExec:
        if self._chain_cache is None:
            self._chain_cache = self.to_binary_chain()
        return self._chain_cache

    def schema(self):
        return self._chain().schema()

    def output_capacity(self):
        return self._chain().output_capacity()

    def cascade_eligible(self) -> bool:
        """Static (schema-only) eligibility for the cascaded pallas probe:
        inner-only steps, no residual/null-aware modes, every step's probe
        keys on the BASE probe stream, no string (dictionary) keys, and
        every table within one VMEM partition. Anything else takes the
        reference chain path."""
        import numpy as np

        from datafusion_distributed_tpu import precision
        from datafusion_distributed_tpu.ops import pallas_hash

        if not pallas_hash.use_pallas_hash():
            return False
        if np.dtype(precision.LANE_INT).itemsize != 4:
            return False
        base = self.probe.schema()
        base_names = set(base.names)
        for s, b in zip(self.steps, self.builds):
            if (s.join_type != "inner" or s.residual is not None
                    or s.null_aware):
                return False
            if s.num_slots > pallas_hash._MAX_VMEM_SLOTS:
                return False
            if not set(s.probe_keys) <= base_names:
                return False
            bschema = b.schema()
            for kn in s.probe_keys:
                if base.field(kn).dtype == DataType.STRING:
                    return False
            for kn in s.build_keys:
                if bschema.field(kn).dtype == DataType.STRING:
                    return False
        return True

    def _execute(self, ctx: ExecContext) -> Table:
        if self.cascade_eligible():
            return self._execute_cascade(ctx)
        return self._chain()._execute(ctx)

    def _execute_cascade(self, ctx: ExecContext) -> Table:
        import jax
        import numpy as np

        from datafusion_distributed_tpu.ops import pallas_hash
        from datafusion_distributed_tpu.ops.hash import hash_columns
        from datafusion_distributed_tpu.ops.join import _fold_keys

        probe_t = self.probe.execute(ctx)
        builds_t = [b.execute(ctx) for b in self.builds]

        sides = []
        for s, bt in zip(self.steps, builds_t):
            lane_plan = [
                probe_t.column(pk).validity is not None
                or bt.column(bk).validity is not None
                for pk, bk in zip(s.probe_keys, s.build_keys)
            ]
            sides.append(build_join_table(
                bt, list(s.build_keys), s.num_slots, lane_plan
            ))

        live0 = probe_t.row_mask()
        n = probe_t.capacity
        lmax = max(bs.raw_slot_keys.shape[1] for bs in sides)
        keys_list, slot0_list, active_list = [], [], []
        tkeys_parts, used_parts, table_slots = [], [], []
        for s, bs in zip(self.steps, sides):
            cols = [probe_t.column(k).data for k in s.probe_keys]
            valids = [probe_t.column(k).validity for k in s.probe_keys]
            km = _fold_keys(cols, valids, bs.lane_plan).astype(jnp.int32)
            if km.shape[1] < lmax:
                km = jnp.pad(km, ((0, 0), (0, lmax - km.shape[1])))
            hk = bs.slot_used.shape[0]
            h0 = hash_columns(list(cols), list(valids))
            slot0 = (h0 & np.uint32(hk - 1)).astype(jnp.int32)
            has_null = jnp.zeros(n, dtype=jnp.bool_)
            for v in valids:
                if v is not None:
                    has_null = has_null | ~v
            keys_list.append(km)
            slot0_list.append(slot0)
            active_list.append(live0 & ~has_null)
            tk = bs.raw_slot_keys.astype(jnp.int32)
            if tk.shape[1] < lmax:
                tk = jnp.pad(tk, ((0, 0), (0, lmax - tk.shape[1])))
            tkeys_parts.append(tk)
            used_parts.append(bs.slot_used.astype(jnp.int32))
            table_slots.append(hk)

        found, over = pallas_hash.pallas_multiway_probe(
            jnp.stack(keys_list, axis=1),
            jnp.stack(slot0_list, axis=1),
            jnp.stack(active_list, axis=1),
            jnp.concatenate(tkeys_parts, axis=0),
            jnp.concatenate(used_parts, axis=0),
            tuple(table_slots),
            interpret=jax.default_backend() != "tpu",
        )

        # hidden original-row index threads the one-shot probe results
        # through the per-step expansions (dead/padded rows carry garbage
        # slots that hash_join re-masks against its own row_mask)
        cur = probe_t.with_column(
            _MW_ORIG,
            Column(jnp.arange(n, dtype=jnp.int32), None, DataType.INT32),
        )
        for k, (s, bs) in enumerate(zip(self.steps, sides)):
            orig = jnp.clip(
                cur.column(_MW_ORIG).data.astype(jnp.int32), 0, n - 1
            )
            pre = found[:, k][orig]
            cur, overflow = hash_join(
                cur, bs, list(s.probe_keys), "inner", s.out_capacity,
                precomputed=(pre, over[k]),
            )
            ctx.record_overflow(self, overflow)
        names = [nm for nm in cur.names if nm != _MW_ORIG]
        return cur.select(names)

    def display(self):
        parts = []
        for s in self.steps:
            ks = ", ".join(
                f"{p}={b}" for p, b in zip(s.probe_keys, s.build_keys)
            )
            parts.append(f"{s.join_type}[{ks}]")
        return (
            f"MultiwayHashJoin {' -> '.join(parts)} "
            f"out_cap={self.output_capacity()}"
        )


class CrossJoinExec(ExecutionPlan):
    """Cartesian product (TPC-H never needs one after predicate extraction,
    but DataFusion exposes CrossJoinExec so parity requires it)."""

    def __init__(self, left: ExecutionPlan, right: ExecutionPlan,
                 out_capacity: Optional[int] = None):
        super().__init__()
        self.left = left
        self.right = right
        self.out_capacity = out_capacity or min(
            round_up_pow2(left.output_capacity() * right.output_capacity()),
            1 << 22,
        )

    def children(self):
        return [self.left, self.right]

    def with_new_children(self, children):
        return CrossJoinExec(children[0], children[1], self.out_capacity)

    def schema(self):
        return Schema(
            list(self.left.schema().fields) + list(self.right.schema().fields)
        )

    def output_capacity(self):
        return self.out_capacity

    def _execute(self, ctx: ExecContext) -> Table:
        l = self.left.execute(ctx)
        r = self.right.execute(ctx)
        cap = self.out_capacity
        # Division-based overflow test: l*r > cap iff l > cap // r. Avoids
        # a 64-bit product (unavailable in tpu precision mode).
        rn = jnp.maximum(r.num_rows, 1)
        overflow = (r.num_rows > 0) & (l.num_rows > cap // rn)
        ctx.record_overflow(self, overflow)
        # product fits int32 whenever overflow is False (cap is int32-sized)
        total = jnp.where(overflow, cap, l.num_rows * r.num_rows).astype(jnp.int32)
        j = jnp.arange(cap, dtype=jnp.int32)
        li = jnp.clip(j // jnp.maximum(r.num_rows, 1), 0, l.capacity - 1)
        ri = jnp.clip(j % jnp.maximum(r.num_rows, 1), 0, r.capacity - 1)
        cols: dict[str, Column] = {}
        for name, col in zip(l.names, l.columns):
            cols[name] = col.gather(li)
        for name, col in zip(r.names, r.columns):
            cols[name] = col.gather(ri)
        return Table(tuple(cols.keys()), tuple(cols.values()), total)

    def display(self):
        return f"CrossJoin out_cap={self.out_capacity}"


class UnionExec(ExecutionPlan):
    """UNION ALL: concatenation of same-schema children."""

    def __init__(self, children_: Sequence[ExecutionPlan]):
        super().__init__()
        self._children = list(children_)

    def children(self):
        return list(self._children)

    def with_new_children(self, children):
        return UnionExec(children)

    def schema(self):
        return self._children[0].schema()

    def output_capacity(self):
        return sum(c.output_capacity() for c in self._children)

    def _execute(self, ctx: ExecContext) -> Table:
        tables = [c.execute(ctx) for c in self._children]
        first = tables[0]
        # align column names to the first child's
        aligned = [tables[0]]
        for t in tables[1:]:
            aligned.append(
                Table(first.names, t.columns, t.num_rows)
            )
        return concat_tables(aligned, capacity=self.output_capacity())

    def display(self):
        return f"Union children={len(self._children)}"
