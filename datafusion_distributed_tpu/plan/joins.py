"""Physical join + union operators.

The reference uses DataFusion's HashJoinExec/NestedLoopJoinExec/CrossJoinExec
and wraps their build sides in BroadcastExec when distributing
(`/root/reference/src/distributed_planner/insert_broadcast.rs`). Here the
join kernel is ops/join.py's vectorized build/probe/expand; this module is the
plan-tree layer: key materialization, residual predicates, mark/semi/anti
modes, and capacity policy.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from datafusion_distributed_tpu.ops.join import build_join_table, hash_join
from datafusion_distributed_tpu.ops.table import (
    Column,
    Table,
    concat_tables,
    round_up_pow2,
)
from datafusion_distributed_tpu.plan.expressions import PhysicalExpr
from datafusion_distributed_tpu.plan.physical import ExecContext, ExecutionPlan
from datafusion_distributed_tpu.schema import DataType, Field, Schema

_PROBE_IDX = "__probe_idx"


_MAX_DERIVED_JOIN_CAPACITY = 1 << 25


class HashJoinExec(ExecutionPlan):
    """Hash join. probe = left child (preserved side), build = right child.

    join_type: inner | left | semi | anti | mark.
    Keys are column names (the planner materializes key expressions into
    columns below the join). `residual` is an extra predicate over the
    combined schema, used for non-equi correlated EXISTS (TPC-H q21 shape).
    """

    def __init__(
        self,
        probe: ExecutionPlan,
        build: ExecutionPlan,
        probe_keys: Sequence[str],
        build_keys: Sequence[str],
        join_type: str,
        residual: Optional[PhysicalExpr] = None,
        out_capacity: Optional[int] = None,
        num_slots: Optional[int] = None,
        mark_name: str = "__mark",
        expansion_factor: float = 1.0,
        null_aware: bool = False,
    ):
        super().__init__()
        self.probe = probe
        self.build = build
        # NOT IN semantics: a NULL anywhere in the subquery result means no
        # probe row passes, and NULL probe keys never pass.
        self.null_aware = null_aware
        self.probe_keys = list(probe_keys)
        self.build_keys = list(build_keys)
        self.join_type = join_type
        self.residual = residual
        self.mark_name = mark_name
        self.expansion_factor = expansion_factor
        self.num_slots = num_slots or min(
            round_up_pow2(2 * max(build.output_capacity(), 8)), 1 << 21
        )
        if out_capacity is None:
            base = probe.output_capacity()
            # hard ceiling on the EXPANSION (chained joins multiply
            # capacities and the overflow retry quadruples expansion
            # factors — unbounded, the product can demand terabytes;
            # observed: a 3.3 TB allocation request). Never clamp below the
            # probe side's own capacity: a 1x join must always fit.
            ceiling = max(
                _MAX_DERIVED_JOIN_CAPACITY, round_up_pow2(max(base, 8))
            )
            out_capacity = min(
                round_up_pow2(max(int(base * expansion_factor), 8)),
                ceiling,
            )
        self.out_capacity = out_capacity

    def children(self):
        return [self.probe, self.build]

    def with_new_children(self, children):
        return HashJoinExec(
            children[0], children[1], self.probe_keys, self.build_keys,
            self.join_type, self.residual, self.out_capacity, self.num_slots,
            self.mark_name, self.expansion_factor, self.null_aware,
        )

    def schema(self):
        if self.join_type in ("semi", "anti"):
            return self.probe.schema()
        if self.join_type == "mark":
            return Schema(
                list(self.probe.schema().fields)
                + [Field(self.mark_name, DataType.BOOL, False)]
            )
        left = list(self.probe.schema().fields)
        right = [
            Field(f.name, f.dtype, True if self.join_type == "left" else f.nullable)
            for f in self.build.schema().fields
        ]
        return Schema(left + right)

    def output_capacity(self):
        if self.join_type in ("semi", "anti", "mark"):
            return self.probe.output_capacity()
        return self.out_capacity

    def _execute(self, ctx: ExecContext) -> Table:
        probe = self.probe.execute(ctx)
        build = self.build.execute(ctx)
        probe, build = _unify_key_dictionaries(
            probe, build, self.probe_keys, self.build_keys
        )
        # shared validity-lane layout: union of both sides' nullability
        lane_plan = []
        for pk, bk in zip(self.probe_keys, self.build_keys):
            lane_plan.append(
                probe.column(pk).validity is not None
                or build.column(bk).validity is not None
            )
        bs = build_join_table(build, self.build_keys, self.num_slots, lane_plan)

        if self.residual is None:
            out, overflow = hash_join(
                probe, bs, self.probe_keys, self.join_type, self.out_capacity
            )
            ctx.record_overflow(self, overflow)
            if self.join_type == "anti" and self.null_aware:
                out = self._null_aware_anti(probe, bs, out)
            if self.join_type == "mark":
                out = out.rename({"__mark": self.mark_name})
            return out

        # Residual path: expand pairs (inner), filter, then fold back.
        pidx = Column(
            jnp.arange(probe.capacity, dtype=DataType.INT64.np_dtype),
            None, DataType.INT64,
        )
        probe2 = probe.with_column(_PROBE_IDX, pidx)
        pairs, overflow = hash_join(
            probe2, bs, self.probe_keys, "inner", self.out_capacity
        )
        ctx.record_overflow(self, overflow)
        v = self.residual.evaluate(pairs)
        ok = v.data.astype(jnp.bool_) & v.valid_mask() & pairs.row_mask()

        if self.join_type == "inner":
            out = pairs.compact(ok)
            names = [n for n in out.names if n != _PROBE_IDX]
            return out.select(names)

        # semi/anti/mark: scatter pair verdicts back onto probe rows
        pair_pidx = pairs.column(_PROBE_IDX).data.astype(jnp.int32)
        match = jnp.zeros(probe.capacity, dtype=jnp.bool_)
        match = match.at[jnp.where(ok, pair_pidx, probe.capacity)].set(
            True, mode="drop"
        )
        live = probe.row_mask()
        if self.join_type == "semi":
            return probe.compact(match)
        if self.join_type == "anti":
            return probe.compact(live & ~match)
        if self.join_type == "mark":
            return probe.with_column(
                self.mark_name, Column(match, None, DataType.BOOL)
            )
        raise NotImplementedError(
            f"join type {self.join_type} with residual predicate"
        )

    def _null_aware_anti(self, probe: Table, bs, anti_result: Table) -> Table:
        """NOT IN: any NULL in the subquery empties the result; NULL probe
        keys are excluded (three-valued logic makes them UNKNOWN)."""
        keep = ~bs.has_null_key
        probe_null = jnp.zeros(anti_result.capacity, dtype=jnp.bool_)
        for k in self.probe_keys:
            v = anti_result.column(k).validity
            if v is not None:
                probe_null = probe_null | ~v
        mask = anti_result.row_mask() & ~probe_null & keep
        return anti_result.compact(mask)

    def display(self):
        ks = ", ".join(
            f"{p}={b}" for p, b in zip(self.probe_keys, self.build_keys)
        )
        res = f" residual={self.residual.display()}" if self.residual else ""
        return (
            f"HashJoin {self.join_type} on [{ks}]{res} "
            f"out_cap={self.out_capacity}"
        )


def _unify_key_dictionaries(probe: Table, build: Table, probe_keys, build_keys):
    """String join keys are dictionary codes; codes from different
    dictionaries are not comparable. Remap both sides onto a sorted union
    dictionary (host-side LUT over static metadata + device gather), the
    analogue of Arrow dictionary unification before a DataFusion hash join."""
    from datafusion_distributed_tpu.ops.table import Dictionary
    import numpy as np

    for pk, bk in zip(probe_keys, build_keys):
        pc = probe.column(pk)
        bc = build.column(bk)
        if pc.dictionary is None and bc.dictionary is None:
            continue
        if pc.dictionary == bc.dictionary:
            continue
        if pc.dictionary is None or bc.dictionary is None:
            raise ValueError(
                f"string join key {pk}/{bk} missing a dictionary"
            )
        union_vals = np.unique(
            np.concatenate([pc.dictionary.values, bc.dictionary.values]).astype(str)
        )
        unified = Dictionary(union_vals.astype(object))

        def remap(col, table, name):
            old = col.dictionary.values.astype(str)
            lut = np.searchsorted(union_vals, old).astype(np.int32)
            lut_dev = jnp.asarray(lut) if len(lut) else jnp.zeros(1, jnp.int32)
            codes = lut_dev[jnp.clip(col.data, 0, max(len(lut) - 1, 0))]
            from datafusion_distributed_tpu.ops.table import Column

            return table.with_column(
                name, Column(codes, col.validity, col.dtype, unified)
            )

        probe = remap(pc, probe, pk)
        build = remap(bc, build, bk)
    return probe, build


class CrossJoinExec(ExecutionPlan):
    """Cartesian product (TPC-H never needs one after predicate extraction,
    but DataFusion exposes CrossJoinExec so parity requires it)."""

    def __init__(self, left: ExecutionPlan, right: ExecutionPlan,
                 out_capacity: Optional[int] = None):
        super().__init__()
        self.left = left
        self.right = right
        self.out_capacity = out_capacity or min(
            round_up_pow2(left.output_capacity() * right.output_capacity()),
            1 << 22,
        )

    def children(self):
        return [self.left, self.right]

    def with_new_children(self, children):
        return CrossJoinExec(children[0], children[1], self.out_capacity)

    def schema(self):
        return Schema(
            list(self.left.schema().fields) + list(self.right.schema().fields)
        )

    def output_capacity(self):
        return self.out_capacity

    def _execute(self, ctx: ExecContext) -> Table:
        l = self.left.execute(ctx)
        r = self.right.execute(ctx)
        cap = self.out_capacity
        # Division-based overflow test: l*r > cap iff l > cap // r. Avoids
        # a 64-bit product (unavailable in tpu precision mode).
        rn = jnp.maximum(r.num_rows, 1)
        overflow = (r.num_rows > 0) & (l.num_rows > cap // rn)
        ctx.record_overflow(self, overflow)
        # product fits int32 whenever overflow is False (cap is int32-sized)
        total = jnp.where(overflow, cap, l.num_rows * r.num_rows).astype(jnp.int32)
        j = jnp.arange(cap, dtype=jnp.int32)
        li = jnp.clip(j // jnp.maximum(r.num_rows, 1), 0, l.capacity - 1)
        ri = jnp.clip(j % jnp.maximum(r.num_rows, 1), 0, r.capacity - 1)
        cols: dict[str, Column] = {}
        for name, col in zip(l.names, l.columns):
            cols[name] = col.gather(li)
        for name, col in zip(r.names, r.columns):
            cols[name] = col.gather(ri)
        return Table(tuple(cols.keys()), tuple(cols.values()), total)

    def display(self):
        return f"CrossJoin out_cap={self.out_capacity}"


class UnionExec(ExecutionPlan):
    """UNION ALL: concatenation of same-schema children."""

    def __init__(self, children_: Sequence[ExecutionPlan]):
        super().__init__()
        self._children = list(children_)

    def children(self):
        return list(self._children)

    def with_new_children(self, children):
        return UnionExec(children)

    def schema(self):
        return self._children[0].schema()

    def output_capacity(self):
        return sum(c.output_capacity() for c in self._children)

    def _execute(self, ctx: ExecContext) -> Table:
        tables = [c.execute(ctx) for c in self._children]
        first = tables[0]
        # align column names to the first child's
        aligned = [tables[0]]
        for t in tables[1:]:
            aligned.append(
                Table(first.names, t.columns, t.num_rows)
            )
        return concat_tables(aligned, capacity=self.output_capacity())

    def display(self):
        return f"Union children={len(self._children)}"
