"""Static plan verifier: reject malformed plans BEFORE trace/compile/dispatch.

The engine leans on invariants that are documented but (until now) never
checked: stage-shared programs assume leaf traversal order is stable across
codec round-trips, fingerprint-keyed caches assume `structural_tokens()`
coverage, and mesh exchanges assume partition counts match the device axis.
Each of those failure modes is "wrong results, no error" — the worst class.
The reference Rust engine gets most of this for free from its type system
(DataFusion's `Schema`/`Partitioning` contracts are checked at plan-build
time); this module is the Python analogue: a multi-pass analyzer over the
physical plan tree emitting structured `Diagnostic` records with stable
``DFTPU0xx`` codes.

Passes (see ``verify_physical_plan``):

  structure   cycle detection — everything else assumes a finite tree
  schema      dtype/column propagation: every node's expectations against
              its children's derived output schemas
  capacity    static overflow analysis: int32 index range, hash-table
              capacity vs NDV estimates, dictionary sizes
  exchange    stage/lattice consistency: partition counts across stage
              boundaries, stage-id stamping, co-shuffled join agreement,
              task-lattice satisfiability, mesh-axis divisibility
  cache       cache-integrity audit: custom nodes without
              `structural_tokens()`, unhoistable literals that defeat
              fingerprint sharing

Severity: ``error`` = the plan would crash or silently produce wrong
results; ``warning`` = the plan runs correctly but degrades (overflow
retries, no compiled-program sharing). ``strict`` mode raises
`PlanVerificationError` on errors; ``warn`` mode converts them to Python
warnings; warnings-severity diagnostics never raise — they surface through
``EXPLAIN VERIFY`` and ``explain_analyze``.

Diagnostic code registry (keep in sync with README "Static plan
verification & lint"):

  DFTPU011  unknown column reference            (schema, error)
  DFTPU012  join key type-class mismatch        (schema, error)
  DFTPU013  union input schema mismatch         (schema, error)
  DFTPU014  schema derivation failed            (schema, error)
  DFTPU015  filter predicate not boolean        (schema, error)
  DFTPU021  hash capacity below NDV estimate    (capacity, warning)
  DFTPU022  capacity exceeds int32 index range  (capacity, error)
  DFTPU023  join slots below build-side bound   (capacity, warning)
  DFTPU024  dictionary exceeds int32 code range (capacity, error)
  DFTPU025  table exceeds pallas partition cap  (capacity, warning)
  DFTPU031  partition count mismatch at boundary(exchange, error)
  DFTPU032  stage id unstamped / duplicated     (exchange, error)
  DFTPU033  plan graph contains a cycle         (structure, error)
  DFTPU034  co-shuffled join sides disagree     (exchange, error)
  DFTPU035  stage width incompatible with mesh  (exchange, error)
  DFTPU036  task lattice unsatisfiable          (exchange, error)
  DFTPU037  non-contiguous stage ids            (exchange, warning)
  DFTPU041  custom node lacks structural_tokens (cache, warning)
  DFTPU042  literal not hoistable               (cache, warning)
  DFTPU043  decoded plan fingerprint mismatch   (cache, error; raised by
            runtime/worker.py as PlanIntegrityError, not emitted here)
  DFTPU044  codec round-trip fingerprint drift  (cache, error; raised by
            runtime/codec.py under DFTPU_VERIFY_CODEC=1)
"""

from __future__ import annotations

import os
import warnings as _warnings
from dataclasses import dataclass, field
from typing import Any, Optional

from datafusion_distributed_tpu.schema import DataType, Field, Schema

_INT32_MAX = (1 << 31) - 1

# largest hash table the pallas partition-pass kernels accept
# (ops/pallas_hash._MAX_TABLE_SLOTS); mirrored here so the plan layer
# never imports the ops layer at module load
_PALLAS_MAX_TABLE_SLOTS = 1 << 20

#: verification modes, in decreasing strictness
MODES = ("strict", "warn", "off")


@dataclass(frozen=True)
class Diagnostic:
    """One verifier finding, addressed to a plan node."""

    code: str  # "DFTPU0xx"
    severity: str  # "error" | "warning"
    node_id: Optional[int]
    message: str
    #: node display label at emission time (node ids are per-process)
    node: str = ""

    def render(self) -> str:
        loc = f" node={self.node_id}" if self.node_id is not None else ""
        label = f" [{self.node}]" if self.node else ""
        return f"{self.code} {self.severity}{loc}{label}: {self.message}"


@dataclass
class VerifyResult:
    diagnostics: list = field(default_factory=list)

    def errors(self) -> list:
        return [d for d in self.diagnostics if d.severity == "error"]

    def warnings(self) -> list:
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors()

    def codes(self) -> set:
        return {d.code for d in self.diagnostics}

    def by_node(self) -> dict:
        out: dict = {}
        for d in self.diagnostics:
            if d.node_id is not None:
                out.setdefault(d.node_id, []).append(d)
        return out

    def render(self) -> str:
        if not self.diagnostics:
            return "plan verified: no diagnostics"
        lines = [d.render() for d in self.diagnostics]
        lines.append(
            f"{len(self.errors())} error(s), {len(self.warnings())} "
            "warning(s)"
        )
        return "\n".join(lines)


class PlanVerificationError(RuntimeError):
    """A plan failed static verification under ``strict`` mode. Deliberately
    NOT matched by the overflow-retry loops (`"overflow" not in message`):
    re-planning cannot repair a structurally malformed plan."""

    def __init__(self, result: VerifyResult, context: str = ""):
        self.result = result
        where = f" ({context})" if context else ""
        super().__init__(
            f"plan verification failed{where}:\n{result.render()}"
        )


# ---------------------------------------------------------------------------
# mode resolution
# ---------------------------------------------------------------------------


def resolve_verify_mode(options: Optional[dict] = None) -> str:
    """Session option > DFTPU_VERIFY_PLANS env > default ``warn``."""
    mode = None
    if options:
        mode = options.get("verify_plans")
    if mode is None:
        mode = os.environ.get("DFTPU_VERIFY_PLANS")
    if mode is None:
        return "warn"
    mode = str(mode).strip().lower()
    if mode not in MODES:
        raise ValueError(
            f"invalid verify_plans mode {mode!r} (expected one of {MODES})"
        )
    return mode


# ---------------------------------------------------------------------------
# traversal helpers
# ---------------------------------------------------------------------------


def _iter_nodes(plan) -> tuple[list, Optional[Diagnostic]]:
    """Pre-order node list with cycle detection. On a cycle, traversal stops
    at the back-edge and the DFTPU033 diagnostic is returned — the caller
    must not run further passes (they assume a finite tree)."""
    out: list = []
    on_path: set = set()
    visited: set = set()
    cycle: list = []

    def walk(node) -> None:
        if cycle:
            return
        if id(node) in on_path:
            cycle.append(
                Diagnostic(
                    "DFTPU033", "error", getattr(node, "node_id", None),
                    "plan graph contains a cycle (node is its own "
                    "ancestor); traversal/trace would not terminate",
                    node=_label(node),
                )
            )
            return
        if id(node) in visited:  # shared subtree (diamond): audit once
            return
        visited.add(id(node))
        out.append(node)
        on_path.add(id(node))
        try:
            children = node.children()
        except Exception:
            children = []
        for c in children:
            walk(c)
        on_path.discard(id(node))

    walk(plan)
    return out, (cycle[0] if cycle else None)


def _label(node) -> str:
    try:
        return node.display()
    except Exception:
        return type(node).__name__


def _dtype_class(dt: DataType) -> str:
    """Comparability class: values of one class hash/compare consistently
    after the engine's width canonicalization; cross-class keys do not."""
    if dt in (DataType.INT32, DataType.INT64, DataType.DATE32):
        return "int"
    if dt in (DataType.FLOAT32, DataType.FLOAT64):
        return "float"
    if dt is DataType.STRING:
        return "string"
    if dt is DataType.BOOL:
        return "bool"
    return "null"


class _Pass:
    """Shared emit/poison plumbing for one verification pass."""

    def __init__(self, result: VerifyResult):
        self.result = result
        self.poisoned: set = set()  # node ids whose derivation already failed

    def emit(self, code: str, severity: str, node, message: str) -> None:
        self.result.diagnostics.append(
            Diagnostic(code, severity, getattr(node, "node_id", None),
                       message, node=_label(node))
        )


# ---------------------------------------------------------------------------
# schema / dtype propagation pass
# ---------------------------------------------------------------------------


def _schema_pass(nodes: list, p: _Pass) -> dict:
    """Bottom-up schema derivation + per-node consumer expectations.
    Returns node_id -> Schema for downstream passes. A node whose schema
    failed poisons its ancestors (one diagnostic at the failure site, not
    a cascade up the tree)."""
    schemas: dict = {}
    for node in reversed(nodes):  # children precede parents in reversed()
        try:
            children = node.children()
        except Exception:
            children = []
        if any(id(c) in p.poisoned for c in children):
            p.poisoned.add(id(node))
            continue
        try:
            schemas[node.node_id] = node.schema()
        except KeyError as e:
            p.poisoned.add(id(node))
            p.emit("DFTPU011", "error", node,
                   f"unknown column reference while deriving schema: {e}")
            continue
        except Exception as e:
            p.poisoned.add(id(node))
            p.emit("DFTPU014", "error", node,
                   f"schema derivation failed: {type(e).__name__}: {e}")
            continue
        _node_schema_checks(node, children, p)
    return schemas


def _check_names(node, names, child_schema: Schema, what: str,
                 p: _Pass) -> bool:
    ok = True
    for n in names:
        if n not in child_schema:
            p.emit(
                "DFTPU011", "error", node,
                f"{what} {n!r} not in input schema "
                f"{child_schema.names}",
            )
            ok = False
    return ok


def _node_schema_checks(node, children, p: _Pass) -> None:
    kind = type(node).__name__
    if kind == "FilterExec":
        child_schema = children[0].schema()
        try:
            f = node.predicate.output_field(child_schema)
        except KeyError as e:
            p.emit("DFTPU011", "error", node,
                   f"filter predicate references unknown column: {e}")
            return
        except Exception:
            return  # derivation quirks are not this check's business
        if f.dtype not in (DataType.BOOL, DataType.NULL):
            p.emit(
                "DFTPU015", "error", node,
                f"filter predicate evaluates to {f.dtype.value}, not "
                "boolean — rows would be kept by bit-pattern accident",
            )
    elif kind == "ProjectionExec":
        child_schema = children[0].schema()
        for expr, name in node.exprs:
            try:
                expr.output_field(child_schema)
            except KeyError as e:
                p.emit(
                    "DFTPU011", "error", node,
                    f"projection {name!r} references unknown column: {e}",
                )
            except Exception:
                pass
    elif kind == "HashAggregateExec":
        child_schema = children[0].schema()
        _check_names(node, node.group_names, child_schema,
                     "GROUP BY column", p)
        for a in node.aggs:
            if node.mode in ("final", "partial_reduce"):
                continue  # consumes accumulator columns; schema() covered it
            if a.input_name is not None:
                _check_names(node, [a.input_name], child_schema,
                             f"aggregate {a.func} input", p)
    elif kind == "SortExec":
        child_schema = children[0].schema()
        _check_names(node, [k.name for k in node.keys], child_schema,
                     "sort key", p)
    elif kind == "WindowExec":
        child_schema = children[0].schema()
        _check_names(node, node.partition_names, child_schema,
                     "window partition column", p)
        _check_names(node, [k.name for k in node.order_keys], child_schema,
                     "window order key", p)
        for f in node.funcs:
            if f.input_name is not None:
                _check_names(node, [f.input_name], child_schema,
                             f"window {f.func} input", p)
    elif kind == "HashJoinExec":
        probe_schema = node.probe.schema()
        build_schema = node.build.schema()
        ok = _check_names(node, node.probe_keys, probe_schema,
                          "probe join key", p)
        ok = _check_names(node, node.build_keys, build_schema,
                          "build join key", p) and ok
        if ok:
            for pk, bk in zip(node.probe_keys, node.build_keys):
                pc = _dtype_class(probe_schema.field(pk).dtype)
                bc = _dtype_class(build_schema.field(bk).dtype)
                if "null" in (pc, bc) or pc == bc:
                    continue
                p.emit(
                    "DFTPU012", "error", node,
                    f"join key {pk}={bk} compares {pc} to {bc}: hashed "
                    "bit patterns differ per class, rows would silently "
                    "never match",
                )
        if node.residual is not None:
            try:
                node.residual.output_field(probe_schema.join(build_schema))
            except KeyError as e:
                p.emit("DFTPU011", "error", node,
                       f"join residual references unknown column: {e}")
            except Exception:
                pass
    elif kind == "MultiwayHashJoinExec":
        # fold the probe-stream schema step by step, mirroring the binary
        # chain the node lowers to, so every step's keys are checked
        # against the columns actually visible at that step
        running = node.probe.schema()
        for idx, (s, b) in enumerate(zip(node.steps, node.builds)):
            build_schema = b.schema()
            ok = _check_names(node, list(s.probe_keys), running,
                              f"multiway step {idx} probe key", p)
            ok = _check_names(node, list(s.build_keys), build_schema,
                              f"multiway step {idx} build key", p) and ok
            if ok:
                for pk, bk in zip(s.probe_keys, s.build_keys):
                    pc = _dtype_class(running.field(pk).dtype)
                    bc = _dtype_class(build_schema.field(bk).dtype)
                    if "null" in (pc, bc) or pc == bc:
                        continue
                    p.emit(
                        "DFTPU012", "error", node,
                        f"multiway step {idx} key {pk}={bk} compares "
                        f"{pc} to {bc}: hashed bit patterns differ per "
                        "class, rows would silently never match",
                    )
            if s.residual is not None:
                try:
                    s.residual.output_field(running.join(build_schema))
                except KeyError as e:
                    p.emit(
                        "DFTPU011", "error", node,
                        f"multiway step {idx} residual references "
                        f"unknown column: {e}",
                    )
                except Exception:
                    pass
            if not ok:
                break
            if s.join_type in ("semi", "anti"):
                continue
            if s.join_type == "mark":
                running = Schema(
                    list(running.fields)
                    + [Field(s.mark_name, DataType.BOOL, False)]
                )
                continue
            running = Schema(
                list(running.fields)
                + [Field(f.name, f.dtype,
                         True if s.join_type == "left" else f.nullable)
                   for f in build_schema.fields]
            )
    elif kind == "UnionExec":
        first = children[0].schema()
        for i, c in enumerate(children[1:], start=1):
            s = c.schema()
            if len(s) != len(first):
                p.emit(
                    "DFTPU013", "error", node,
                    f"union input {i} has {len(s)} columns, input 0 has "
                    f"{len(first)}",
                )
                continue
            for fa, fb in zip(first.fields, s.fields):
                ca, cb = _dtype_class(fa.dtype), _dtype_class(fb.dtype)
                if "null" in (ca, cb) or ca == cb:
                    continue
                p.emit(
                    "DFTPU013", "error", node,
                    f"union input {i} column {fb.name!r} is {cb}, input 0 "
                    f"column {fa.name!r} is {ca}",
                )
    elif kind in ("ShuffleExchangeExec",):
        _check_names(node, node.key_names, children[0].schema(),
                     "shuffle key", p)
    elif kind in ("RangeShuffleExchangeExec",):
        _check_names(node, [k.name for k in node.sort_keys],
                     children[0].schema(), "range-shuffle sort key", p)
    elif kind == "MemoryScanExec":
        for t in node.tasks:
            if tuple(t.names) != tuple(node.schema().names):
                p.emit(
                    "DFTPU011", "error", node,
                    f"scan table columns {list(t.names)} do not match "
                    f"declared schema {node.schema().names}",
                )
                break


# ---------------------------------------------------------------------------
# capacity / overflow pass
# ---------------------------------------------------------------------------


def _capacity_pass(nodes: list, p: _Pass) -> None:
    for node in reversed(nodes):
        try:
            children = node.children()
        except Exception:
            children = []
        if any(id(c) in p.poisoned for c in children):
            p.poisoned.add(id(node))
            continue
        try:
            cap = int(node.output_capacity())
        except Exception:
            # schema pass already attributed derivation failures
            p.poisoned.add(id(node))
            continue
        if cap > _INT32_MAX:
            p.emit(
                "DFTPU022", "error", node,
                f"padded output capacity {cap} exceeds the int32 index "
                "range; row indices/gather offsets would wrap",
            )
        kind = type(node).__name__
        if kind == "HashAggregateExec" and node.group_names and (
            node.mode in ("single", "partial")
        ):
            est = getattr(node, "est_rows", None)
            if est is not None and node.num_slots < est:
                p.emit(
                    "DFTPU021", "warning", node,
                    f"hash table capacity {node.num_slots} below the "
                    f"estimated {int(est)} distinct groups: the claim "
                    "loop will overflow and force a re-plan retry",
                )
            if (getattr(node, "global_agg_selected", False)
                    and node.num_slots > _PALLAS_MAX_TABLE_SLOTS):
                p.emit(
                    "DFTPU025", "warning", node,
                    f"global-hash aggregate table of {node.num_slots} "
                    f"slots exceeds the pallas partition budget "
                    f"({_PALLAS_MAX_TABLE_SLOTS}): the kernel degrades "
                    "to the XLA scatter path (correct but unaccelerated)",
                )
        elif kind == "MultiwayHashJoinExec":
            for idx, (s, b) in enumerate(zip(node.steps, node.builds)):
                try:
                    build_bound = int(b.output_capacity())
                except Exception:
                    build_bound = 0
                est = getattr(b, "est_rows", None)
                bound = int(est) if est is not None else build_bound
                if s.num_slots < bound:
                    p.emit(
                        "DFTPU023", "warning", node,
                        f"multiway step {idx} hash table has "
                        f"{s.num_slots} slots for a build side bounded "
                        f"by {bound} rows (load factor > 1): guaranteed "
                        "overflow retry at full occupancy",
                    )
                if s.num_slots > _PALLAS_MAX_TABLE_SLOTS:
                    p.emit(
                        "DFTPU025", "warning", node,
                        f"multiway step {idx} table of {s.num_slots} "
                        f"slots exceeds the pallas partition budget "
                        f"({_PALLAS_MAX_TABLE_SLOTS}): the cascaded "
                        "probe kernel is ineligible and the stage takes "
                        "the binary reference chain",
                    )
        elif kind == "HashJoinExec":
            try:
                build_bound = int(node.build.output_capacity())
            except Exception:
                build_bound = 0
            est = getattr(node.build, "est_rows", None)
            bound = int(est) if est is not None else build_bound
            if node.num_slots < bound:
                p.emit(
                    "DFTPU023", "warning", node,
                    f"join hash table has {node.num_slots} slots for a "
                    f"build side bounded by {bound} rows (load factor "
                    "> 1): guaranteed overflow retry at full occupancy",
                )
        _dictionary_checks(node, p)


def _dictionary_checks(node, p: _Pass) -> None:
    dicts: dict = {}
    kind = type(node).__name__
    if kind == "MemoryScanExec":
        for t in node.tasks:
            for name, col in zip(t.names, t.columns):
                if col.dictionary is not None:
                    dicts[name] = len(col.dictionary.values)
    elif kind == "ParquetScanExec" and getattr(node, "dictionaries", None):
        dicts = {
            name: len(d.values) for name, d in node.dictionaries.items()
        }
    for name, size in dicts.items():
        if size > _INT32_MAX:
            p.emit(
                "DFTPU024", "error", node,
                f"dictionary for column {name!r} has {size} entries — "
                "int32 codes cannot address it",
            )


# ---------------------------------------------------------------------------
# exchange / lattice consistency pass
# ---------------------------------------------------------------------------


def _is_exchange(node) -> bool:
    return bool(getattr(node, "is_exchange", False))


def _producer_count(ex) -> int:
    """How many producer tasks feed exchange ``ex`` (the width of the stage
    directly below it). Coalesce's num_tasks IS the producer count; for the
    other exchanges num_tasks is the consumer count and `producer_tasks`
    (stamped by the lattice) overrides when the sides differ."""
    pt = getattr(ex, "producer_tasks", None)
    if pt is not None:
        return int(pt)
    return int(ex.num_tasks)


def _consumer_width(ex) -> Optional[int]:
    """Task count of the stage CONSUMING ``ex``'s output, when the output
    is partitioned (None = replicated output; any consumer width is fine)."""
    kind = type(ex).__name__
    if kind in ("ShuffleExchangeExec", "RangeShuffleExchangeExec",
                "PartitionReplicatedExec"):
        return int(ex.num_tasks)
    if kind == "CoalesceExchangeExec":
        m = int(getattr(ex, "num_consumers", 1))
        return m if m > 1 else None  # N:1 output is replicated
    if kind == "BroadcastExchangeExec":
        return None  # replicated on every consumer task
    return None


def _inner_boundaries(node) -> list:
    """Nearest exchange descendants of ``node``'s stage (descent stops at
    each boundary: deeper exchanges belong to deeper stages)."""
    out: list = []
    try:
        children = node.children()
    except Exception:
        children = []
    for c in children:
        if _is_exchange(c):
            out.append(c)
        else:
            out.extend(_inner_boundaries(c))
    return out


def _stage_members(ex) -> list:
    """Non-exchange nodes of the stage produced below boundary ``ex``."""
    out: list = []

    def walk(n) -> None:
        out.append(n)
        try:
            children = n.children()
        except Exception:
            children = []
        for c in children:
            if not _is_exchange(c):
                walk(c)

    for c in ex.children():
        if not _is_exchange(c):
            walk(c)
    return out


def _exchange_pass(nodes: list, p: _Pass,
                   mesh_axis_size: Optional[int]) -> None:
    exchanges = [n for n in nodes if _is_exchange(n)]
    if not exchanges:
        return
    # stage-id stamping: every multi-task boundary carries a unique id
    seen_ids: dict = {}
    for ex in exchanges:
        sid = getattr(ex, "stage_id", None)
        if sid is None:
            p.emit(
                "DFTPU032", "error", ex,
                "exchange has no stage id (plan was not run through "
                "prepare/distribute_plan); the runtime addresses tasks "
                "by (query, stage, task) and would collide on stage 0",
            )
        elif sid in seen_ids:
            p.emit(
                "DFTPU032", "error", ex,
                f"stage id {sid} is also used by "
                f"[{_label(seen_ids[sid])}]: task keys of the two stages "
                "would collide",
            )
        else:
            seen_ids[sid] = ex
    # non-contiguous ids: evidence of a detached/hand-edited stage
    ids = sorted(seen_ids)
    if ids and ids != list(range(ids[0], ids[0] + len(ids))):
        p.emit(
            "DFTPU037", "warning", exchanges[0],
            f"stage ids {ids} are not contiguous — a stage may have been "
            "dropped or spliced in by hand",
        )
    for ex in exchanges:
        if id(ex) in p.poisoned:
            continue
        t_prod = _producer_count(ex)
        # partition counts must agree across the boundary: each nearest
        # inner boundary's consumer width IS this boundary's producer width
        child = ex.children()[0]
        inners = [child] if _is_exchange(child) else _inner_boundaries(child)
        for inner in inners:
            w = _consumer_width(inner)
            if w is not None and w != t_prod:
                p.emit(
                    "DFTPU031", "error", ex,
                    f"boundary expects {t_prod} producer task(s) but the "
                    f"feeding boundary [{_label(inner)}] partitions its "
                    f"output {w}-way; partitions beyond the smaller count "
                    "would be silently dropped",
                )
        # task-lattice satisfiability within the producer stage
        for m in _stage_members(ex):
            kind = type(m).__name__
            if kind == "MemoryScanExec":
                if not m.replicated and not m.pinned and (
                    len(m.tasks) > max(t_prod, 1)
                ):
                    p.emit(
                        "DFTPU036", "error", ex,
                        f"scan [{_label(m)}] holds {len(m.tasks)} task "
                        f"slices but the stage runs {t_prod} task(s): "
                        "trailing slices would never be read",
                    )
            elif kind == "ParquetScanExec":
                if len(m.file_groups) > max(t_prod, 1):
                    p.emit(
                        "DFTPU036", "error", ex,
                        f"scan [{_label(m)}] holds {len(m.file_groups)} "
                        f"file groups but the stage runs {t_prod} "
                        "task(s): trailing groups would never be read",
                    )
            elif kind == "IsolatedArmExec":
                if m.assigned_task >= max(t_prod, 1):
                    p.emit(
                        "DFTPU036", "error", ex,
                        f"isolated arm assigned to task "
                        f"{m.assigned_task} of a {t_prod}-task stage: "
                        "the arm would never execute (rows silently "
                        "missing)",
                    )
        if mesh_axis_size is not None and ex.num_tasks != mesh_axis_size:
            p.emit(
                "DFTPU035", "error", ex,
                f"stage width {ex.num_tasks} != mesh axis width "
                f"{mesh_axis_size}: in-mesh collectives (all_to_all/"
                "all_gather) address tasks by device index and would "
                "mis-route or abort",
            )
    # co-shuffled join sides must agree on one consumer count
    for node in nodes:
        kind = type(node).__name__
        if kind == "HashJoinExec":
            sides = [c for c in node.children()
                     if type(c).__name__ == "ShuffleExchangeExec"]
            if len(sides) == 2 and sides[0].num_tasks != sides[1].num_tasks:
                p.emit(
                    "DFTPU034", "error", node,
                    f"co-shuffled join sides disagree on task count "
                    f"({sides[0].num_tasks} vs {sides[1].num_tasks}): "
                    "hash%t co-partitioning breaks and matching rows land "
                    "on different tasks",
                )
        elif kind == "MultiwayHashJoinExec":
            sides = [c for c in node.children()
                     if type(c).__name__ == "ShuffleExchangeExec"]
            widths = sorted({s.num_tasks for s in sides})
            if len(widths) > 1:
                p.emit(
                    "DFTPU034", "error", node,
                    f"co-shuffled multiway join sides disagree on task "
                    f"count ({widths}): every deleted intermediate "
                    "exchange assumed one hash%t co-partitioning, so "
                    "matching rows land on different tasks",
                )


# ---------------------------------------------------------------------------
# cache-integrity audit pass
# ---------------------------------------------------------------------------


def _cache_pass(nodes: list, p: _Pass) -> None:
    from datafusion_distributed_tpu.plan.fingerprint import _PLAN_ATTRS

    for node in nodes:
        name = type(node).__name__
        if name not in _PLAN_ATTRS and not callable(
            getattr(node, "structural_tokens", None)
        ):
            p.emit(
                "DFTPU041", "warning", node,
                f"custom node {name} lacks structural_tokens(): the plan "
                "has no structural fingerprint, so every compiled-program "
                "cache falls back to identity keying (no cross-query "
                "sharing, no stage-share across workers)",
            )
        _unhoistable_literal_check(node, p)


def _unhoistable_literal_check(node, p: _Pass) -> None:
    """Warn on literals that defeat fingerprint sharing: numeric comparison
    literals hoist into runtime parameters (template variants share one
    executable), but string comparisons, LIKE patterns and IN lists stay
    baked — each distinct value traces and compiles its own program."""
    from datafusion_distributed_tpu.plan import expressions as pe

    kind = type(node).__name__
    if kind == "FilterExec":
        exprs = [node.predicate]
    elif kind == "ProjectionExec":
        exprs = [e for e, _ in node.exprs]
    else:
        return
    baked: list = []

    def walk(e, under_cmp: bool) -> None:
        if isinstance(e, pe.Literal):
            if under_cmp and e.value is not None and (
                e.dtype is DataType.STRING
            ):
                baked.append(f"string literal {e.value!r}")
            return
        if isinstance(e, pe.Like):
            baked.append(f"LIKE pattern {e.pattern!r}")
            walk(e.child, False)
            return
        if isinstance(e, pe.InList):
            baked.append(f"IN list of {len(e.values)} value(s)")
            walk(e.child, False)
            return
        if isinstance(e, pe.BinaryOp):
            child_cmp = e.op in pe._CMP_OPS or (
                under_cmp and e.op in pe._ARITH_OPS
            )
            walk(e.left, child_cmp)
            walk(e.right, child_cmp)
            return
        for attr in ("left", "right", "child", "otherwise"):
            sub = getattr(e, attr, None)
            if isinstance(sub, pe.PhysicalExpr):
                walk(sub, False)
        for attr in ("args", "branches"):
            subs = getattr(e, attr, None) or ()
            for sub in subs:
                if isinstance(sub, tuple):
                    for s in sub:
                        if isinstance(s, pe.PhysicalExpr):
                            walk(s, False)
                elif isinstance(sub, pe.PhysicalExpr):
                    walk(sub, False)

    for e in exprs:
        walk(e, False)
    if baked:
        shown = "; ".join(baked[:3])
        more = f" (+{len(baked) - 3} more)" if len(baked) > 3 else ""
        p.emit(
            "DFTPU042", "warning", node,
            f"literal not hoistable: {shown}{more} — query variants "
            "differing only in these values will not share compiled "
            "programs",
        )


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def verify_physical_plan(
    plan,
    mesh_axis_size: Optional[int] = None,
    include_cache_audit: bool = True,
) -> VerifyResult:
    """Run every static pass over a physical plan (single-node or staged).

    ``mesh_axis_size``: when the plan will run as one SPMD program over a
    device mesh, the axis width — enables the stage-width/mesh checks.
    ``include_cache_audit=False`` skips the warning-severity cache pass
    (the worker's post-decode verification uses this: the coordinator
    already audited the full plan)."""
    result = VerifyResult()
    nodes, cycle = _iter_nodes(plan)
    if cycle is not None:
        result.diagnostics.append(cycle)
        return result  # every later pass assumes a finite tree
    p = _Pass(result)
    _schema_pass(nodes, p)
    _capacity_pass(nodes, p)
    _exchange_pass(nodes, p, mesh_axis_size)
    if include_cache_audit:
        _cache_pass(nodes, p)
    return result


_VERIFIED_ATTR = "_dftpu_verified"


def enforce_verification(
    plan,
    options: Optional[dict] = None,
    mode: Optional[str] = None,
    mesh_axis_size: Optional[int] = None,
    context: str = "",
) -> Optional[VerifyResult]:
    """Verify ``plan`` under the resolved mode and act on the outcome:
    ``strict`` raises PlanVerificationError on error-severity diagnostics,
    ``warn`` emits a Python warning instead, ``off`` skips entirely.
    Results are memoized on the plan object (plans are immutable after
    planning/decoding; rebuilt trees re-verify), so the retry loops'
    repeated submissions of one plan verify once."""
    mode = mode or resolve_verify_mode(options)
    if mode == "off":
        return None
    memo = getattr(plan, _VERIFIED_ATTR, None)
    if memo is not None and memo[0] == mesh_axis_size:
        result = memo[1]
    else:
        result = verify_physical_plan(plan, mesh_axis_size=mesh_axis_size)
        try:
            setattr(plan, _VERIFIED_ATTR, (mesh_axis_size, result))
        except AttributeError:
            pass
    if result.errors():
        if mode == "strict":
            raise PlanVerificationError(result, context=context)
        _warnings.warn(
            f"plan verification found errors{f' ({context})' if context else ''}"
            f" (verify_plans=warn):\n{result.render()}",
            RuntimeWarning,
            stacklevel=2,
        )
    return result


def diag_suffix(diags) -> str:
    """Per-node-line diagnostic rendering ('  !CODE severity: message'
    per diagnostic) shared by EXPLAIN VERIFY and explain_analyze."""
    return "".join(
        f"  !{d.code} {d.severity}: {d.message}" for d in diags
    )


def render_verified_tree(plan, result: VerifyResult) -> str:
    """Plan tree with per-node diagnostics stitched into each line — the
    EXPLAIN VERIFY display (and the shape explain_analyze reuses)."""
    by_node = result.by_node()
    lines: list = []

    def walk(node, indent: int) -> None:
        suffix = diag_suffix(by_node.get(node.node_id, ()))
        lines.append("  " * indent + _label(node) + suffix)
        try:
            children = node.children()
        except Exception:
            children = []
        for c in children:
            walk(c, indent + 1)

    walk(plan, 0)
    tail = (
        "verification: clean" if not result.diagnostics else
        f"verification: {len(result.errors())} error(s), "
        f"{len(result.warnings())} warning(s)"
    )
    lines.append(tail)
    return "\n".join(lines)
