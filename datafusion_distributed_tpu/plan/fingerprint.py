"""Structural plan fingerprints + literal hoisting (prepared statements).

Compiled-program caches used to key on plan *object identity*
(``plan.node_id``), so a fresh submission of an identical query — a new
``ctx.sql()`` call, a worker's freshly decoded task copy, a dashboard's
templated refresh — paid the full trace + XLA compile again. At serving
scale compile time dwarfs execution, and repeated/templated queries are the
dominant workload (the reference re-executes tasks against the cached plan
in ``TaskData`` for the same reason).

This module provides the two pieces that turn those caches content-
addressed:

1. **Structural fingerprint** (`plan_fingerprint`): a canonical traversal
   hash over node kind, leaf schemas, expressions, aggregate specs,
   capacities and the task lattice — explicitly *excluding* ``node_id``,
   ``stage_id``, table-store ids, worker URLs, dictionaries and leaf data.
   Two plans with equal fingerprints trace byte-identical XLA programs
   given the same input pytree (dictionaries and shapes ride the program
   *inputs*, so drift there degrades to a jit retrace, never to a wrong
   binding). Anything the fingerprint cannot prove structural about — a
   user extension node without `structural_tokens()` — returns ``None``
   and callers fall back to object-identity keying.

2. **Literal hoisting** (`prepare_plan`): numeric comparison literals in
   filter predicates and projection expressions are lifted out of the
   traced program into a runtime parameter vector per dtype class (one
   int64 vector, one float64 vector). TPC-H-style templates that differ
   only in constants then share ONE executable — the prepared-statement
   path. String/LIKE/IN literals stay baked: their evaluation does
   host-side dictionary work at trace time, so they must remain static
   (and correctly produce distinct fingerprints).

Knobs: ``DFTPU_LITERAL_HOIST=0`` disables hoisting, ``DFTPU_PLAN_CACHE``
sizes the compiled-program LRU in plan/physical.py; both also accept
session scope via ``SET distributed.literal_hoisting`` /
``SET distributed.plan_cache_size``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu.plan import expressions as pe
from datafusion_distributed_tpu.schema import DataType, Schema


class Unfingerprintable(Exception):
    """A node/value the canonicalizer cannot prove structural."""


# ---------------------------------------------------------------------------
# Hoisting configuration
# ---------------------------------------------------------------------------

_HOIST_OVERRIDE: Optional[bool] = None


def set_literal_hoisting(enabled) -> None:
    """Session-scoped override (SET distributed.literal_hoisting)."""
    global _HOIST_OVERRIDE
    if isinstance(enabled, str):
        enabled = enabled.strip().lower() not in ("0", "false", "off", "")
    _HOIST_OVERRIDE = bool(enabled)


def hoist_enabled() -> bool:
    if _HOIST_OVERRIDE is not None:
        return _HOIST_OVERRIDE
    return os.environ.get("DFTPU_LITERAL_HOIST", "1") != "0"


# dtype classes for the parameter vectors: every hoistable dtype maps to one
# of two carrier vectors. The carrier round-trips exactly: int64 holds every
# int32/date32 value; float64 holds every python float, and a float64 ->
# float32 downcast equals the direct python-float -> float32 parse the baked
# literal would have done.
_INT_CLASS = "i"
_FLOAT_CLASS = "f"
_HOISTABLE = {
    DataType.INT32: _INT_CLASS,
    DataType.INT64: _INT_CLASS,
    DataType.DATE32: _INT_CLASS,
    DataType.FLOAT32: _FLOAT_CLASS,
    DataType.FLOAT64: _FLOAT_CLASS,
}

# Trace-time parameter context: `execute_plan`/`execute_on_mesh` bind the
# traced parameter vectors here while tracing runs, and HoistedLiteral
# reads them from inside expression evaluation (expressions only receive
# the table, so the vectors travel out-of-band). Thread-local because
# worker threads trace stage programs concurrently.
_PARAM_TLS = threading.local()


def _param_stack() -> list:
    stack = getattr(_PARAM_TLS, "stack", None)
    if stack is None:
        stack = _PARAM_TLS.stack = []
    return stack


class bound_params:
    """Context manager binding (int_vec, float_vec) for the current trace."""

    def __init__(self, params):
        self.params = params

    def __enter__(self):
        _param_stack().append(self.params)
        return self

    def __exit__(self, *exc):
        _param_stack().pop()
        return False


@dataclass
class HoistedLiteral(pe.PhysicalExpr):
    """A literal lifted into the runtime parameter vector.

    ``klass``/``index`` address the slot; ``value`` is the *current* plan's
    constant (used to build the parameter vector, never baked into the
    trace — and therefore excluded from the fingerprint)."""

    klass: str
    index: int
    dtype: DataType
    value: Any

    def evaluate(self, table) -> pe.ExprValue:
        stack = _param_stack()
        if not stack:
            # executed outside a parameter-carrying program (defensive):
            # fall back to baking the constant, semantics identical
            lit = pe.Literal(self.value, self.dtype)
            return lit.evaluate(table)
        ints, floats = stack[-1]
        vec = ints if self.klass == _INT_CLASS else floats
        val = vec[self.index].astype(self.dtype.np_dtype)
        data = jnp.broadcast_to(val, (table.capacity,))
        return pe.ExprValue(data, None, self.dtype)

    def output_field(self, schema):
        # mirrors Literal.output_field so hoisted/unhoisted plans derive
        # identical schemas (None values are never hoisted)
        from datafusion_distributed_tpu.schema import Field

        return Field(str(self.value), self.dtype, nullable=False)

    def display(self) -> str:
        return f"${self.klass}{self.index}={self.value!r}"


class _HoistCollector:
    def __init__(self) -> None:
        self.ints: list = []
        self.floats: list = []

    def slot(self, dtype: DataType, value) -> HoistedLiteral:
        klass = _HOISTABLE[dtype]
        vec = self.ints if klass == _INT_CLASS else self.floats
        idx = len(vec)
        vec.append(value)
        return HoistedLiteral(klass, idx, dtype, value)

    @property
    def count(self) -> int:
        return len(self.ints) + len(self.floats)


def _hoist_expr(e: pe.PhysicalExpr, col: _HoistCollector,
                under_cmp: bool = False) -> pe.PhysicalExpr:
    """Rebuild ``e`` with hoistable literals replaced by HoistedLiteral.

    Hoistable = a numeric/date Literal (value not None) inside a comparison
    operand: a direct child of a comparison BinaryOp, or nested under
    arithmetic that feeds one (``l_shipdate < date '1994-01-01' + 90``).
    String literals never hoist — BinaryOp._compare resolves them against
    the column dictionary host-side at trace time (and the DATE32-vs-string
    coercion path dispatches on ``isinstance(..., Literal)``)."""
    if isinstance(e, pe.Literal):
        if (under_cmp and e.value is not None and e.dtype in _HOISTABLE):
            return col.slot(e.dtype, e.value)
        return e
    if isinstance(e, pe.BinaryOp):
        child_cmp = e.op in pe._CMP_OPS or (under_cmp and e.op in pe._ARITH_OPS)
        l = _hoist_expr(e.left, col, child_cmp)
        r = _hoist_expr(e.right, col, child_cmp)
        if l is e.left and r is e.right:
            return e
        return pe.BinaryOp(e.op, l, r)
    if isinstance(e, pe.BooleanOp):
        l = _hoist_expr(e.left, col, False)
        r = _hoist_expr(e.right, col, False)
        if l is e.left and r is e.right:
            return e
        return pe.BooleanOp(e.op, l, r)
    if isinstance(e, pe.Not):
        c = _hoist_expr(e.child, col, False)
        return e if c is e.child else pe.Not(c)
    if isinstance(e, pe.Alias):
        c = _hoist_expr(e.child, col, False)
        return e if c is e.child else pe.Alias(c, e.name)
    if isinstance(e, pe.Case):
        branches = tuple(
            (_hoist_expr(c, col, False), _hoist_expr(v, col, False))
            for c, v in e.branches
        )
        otherwise = (
            _hoist_expr(e.otherwise, col, False) if e.otherwise else None
        )
        if (
            all(b[0] is o[0] and b[1] is o[1]
                for b, o in zip(branches, e.branches))
            and otherwise is e.otherwise
        ):
            return e
        return pe.Case(branches, otherwise)
    # every other expression kind (Cast, Coalesce, Like, InList, string
    # functions, subqueries...) keeps its literals baked: their evaluation
    # either does trace-time host work on the value or is not a comparison
    return e


def _hoist_plan(plan, col: _HoistCollector):
    """Rebuild the plan with hoisted filter/projection expressions; nodes
    without hoistable literals are reused as-is (leaves always are, so
    leaf traversal order — the cross-copy input binding — is preserved).
    Rebuilt nodes KEEP the original's node_id: metrics and
    explain_analyze address nodes by id, and the 1:1 rewrite preserves
    uniqueness within the tree."""
    from datafusion_distributed_tpu.plan.physical import (
        FilterExec,
        ProjectionExec,
    )

    kids = [_hoist_plan(c, col) for c in plan.children()]
    changed = any(k is not c for k, c in zip(kids, plan.children()))
    n = None
    if isinstance(plan, FilterExec):
        pred = _hoist_expr(plan.predicate, col, False)
        if pred is not plan.predicate or changed:
            n = FilterExec(pred, kids[0])
            n.est_rows, n.est_selectivity = plan.est_rows, plan.est_selectivity
    elif isinstance(plan, ProjectionExec):
        exprs = [(_hoist_expr(e, col, False), name) for e, name in plan.exprs]
        if changed or any(h is not e for (h, _), (e, _) in
                          zip(exprs, plan.exprs)):
            n = ProjectionExec(exprs, kids[0])
            n.est_rows, n.est_selectivity = plan.est_rows, plan.est_selectivity
    elif changed:
        n = plan.with_new_children(kids)
    if n is None:
        return plan
    if n is not plan:
        n.node_id = plan.node_id
    return n


# ---------------------------------------------------------------------------
# Canonicalization
# ---------------------------------------------------------------------------


def _canon_schema(s: Schema) -> tuple:
    return ("schema",) + tuple(
        (f.name, f.dtype.value, bool(f.nullable)) for f in s.fields
    )


def _canon_value(v) -> Any:
    """Canonical token tree for expression/plan attribute values."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        return ("float", repr(v))
    if isinstance(v, DataType):
        return ("dtype", v.value)
    if isinstance(v, Schema):
        return _canon_schema(v)
    if isinstance(v, HoistedLiteral):
        # the whole point: the VALUE is excluded — only the slot shape is
        # structural, so literal-only variants share a fingerprint
        return ("hlit", v.klass, v.index, v.dtype.value)
    if type(v).__name__ == "ScalarSubqueryExpr":
        resolved = getattr(v, "resolved", None)
        if resolved is not None:
            value, dtype = resolved
            return ("subqlit", _canon_value(value), dtype.value)
        logical = getattr(v, "logical", None)
        if logical is not None:
            return ("subq", _canon_logical(logical))
        raise Unfingerprintable("unresolved scalar subquery")
    if isinstance(v, pe.PhysicalExpr):
        if dataclasses.is_dataclass(v):
            return (type(v).__name__,) + tuple(
                _canon_value(getattr(v, f.name))
                for f in dataclasses.fields(v)
            )
        raise Unfingerprintable(f"expression {type(v).__name__}")
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        # AggSpec, SortKey, WindowFunc, logical helper dataclasses...
        return (type(v).__name__,) + tuple(
            _canon_value(getattr(v, f.name)) for f in dataclasses.fields(v)
        )
    if isinstance(v, (list, tuple)):
        return ("seq",) + tuple(_canon_value(x) for x in v)
    if isinstance(v, dict):
        return ("map",) + tuple(
            (k, _canon_value(v[k])) for k in sorted(v)
        )
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return ("float", repr(float(v)))
    raise Unfingerprintable(f"value of type {type(v).__name__}")


# Per-class structural attribute extractors, dispatched by class NAME so
# this module needs no imports from exchanges/joins/peer (avoiding import
# cycles). Everything identity-like is deliberately absent: node_id,
# stage_id, est_* stats, table-store ids, worker URLs, file paths,
# dictionaries, and leaf table DATA — those either ride the program inputs
# (shape/dict drift degrades to a jit retrace) or are host-side load
# concerns that never enter the traced computation.
_PLAN_ATTRS: dict = {
    "MemoryScanExec": lambda n: (
        len(n.tasks), tuple(int(t.capacity) for t in n.tasks),
        _canon_schema(n._schema), bool(n.pinned), bool(n.replicated),
    ),
    "ParquetScanExec": lambda n: (
        len(n.file_groups), _canon_schema(n._schema), int(n.capacity),
        tuple(n.projection) if n.projection else None,
    ),
    "FilterExec": lambda n: (_canon_value(n.predicate),),
    "ProjectionExec": lambda n: (
        tuple((_canon_value(e), name) for e, name in n.exprs),
    ),
    "HashAggregateExec": lambda n: (
        n.mode, tuple(n.group_names), _canon_value(n.aggs),
        int(n.num_slots), int(n.out_capacity),
    ),
    # bail-out form of a pushed-down partial aggregate (plan/physical.py,
    # runtime/adaptivity.py): per-row singleton states, no table sizing
    "PartialPassthroughExec": lambda n: (
        tuple(n.group_names), _canon_value(n.aggs),
    ),
    "SortExec": lambda n: (
        _canon_value(n.keys), n.fetch,
    ),
    "LimitExec": lambda n: (int(n.fetch), int(n.skip)),
    "CoalescePartitionsExec": lambda n: (),
    "HashJoinExec": lambda n: (
        n.join_type, tuple(n.probe_keys), tuple(n.build_keys),
        _canon_value(n.residual), int(n.out_capacity), int(n.num_slots),
        n.mark_name, bool(n.null_aware),
    ),
    "MultiwayHashJoinExec": lambda n: (
        tuple(
            (s.join_type, tuple(s.probe_keys), tuple(s.build_keys),
             _canon_value(s.residual), int(s.out_capacity),
             int(s.num_slots), s.mark_name, bool(s.null_aware))
            for s in n.steps
        ),
    ),
    "CrossJoinExec": lambda n: (int(n.out_capacity),),
    "UnionExec": lambda n: (),
    "WindowExec": lambda n: (
        _canon_value(n.funcs), tuple(n.partition_names),
        _canon_value(n.order_keys), _canon_schema(Schema(n.out_fields)),
    ),
    "ShuffleExchangeExec": lambda n: (
        tuple(n.key_names), int(n.num_tasks), int(n.per_dest_capacity),
        n.producer_tasks, n.consumer_fetch,
    ),
    "RangeShuffleExchangeExec": lambda n: (
        _canon_value(n.sort_keys), int(n.num_tasks),
        int(n.per_dest_capacity), n.producer_tasks, n.consumer_fetch,
    ),
    "CoalesceExchangeExec": lambda n: (
        int(n.num_tasks), int(getattr(n, "num_consumers", 1)),
        n.producer_tasks, n.consumer_fetch,
    ),
    "BroadcastExchangeExec": lambda n: (
        int(n.num_tasks), n.producer_tasks, n.consumer_fetch,
    ),
    "PartitionReplicatedExec": lambda n: (
        int(n.num_tasks), n.producer_tasks, n.consumer_fetch,
    ),
    "IsolatedArmExec": lambda n: (int(n.assigned_task),),
    # stateless metric pass-through (planner/adaptive.py)
    "SamplerExec": lambda n: (),
    # feed-fed leaf: the feed id is a data location (like table-store ids),
    # not structure — the drained units enter as program inputs
    "WorkUnitScanExec": lambda n: (
        _canon_schema(n._schema), int(n.capacity),
    ),
    "PeerShuffleScanExec": lambda n: (
        len(n.pulls_per_task),
        tuple(len(s) for s in n.pulls_per_task),
        tuple(n.key_names), int(n.num_partitions),
        int(n.per_dest_capacity), _canon_schema(n._schema),
        bool(n.replicated), n.pinned_task, bool(n.pull_all),
        int(n.capacity_hint),
    ),
}


def _canon_plan(plan) -> tuple:
    name = type(plan).__name__
    attrs = _PLAN_ATTRS.get(name)
    if attrs is None:
        # extension hook: a custom node may declare its own structural
        # identity; without one we cannot prove what its trace depends on
        tokens = getattr(plan, "structural_tokens", None)
        if callable(tokens):
            return (name, _canon_value(tokens()),
                    tuple(_canon_plan(c) for c in plan.children()))
        raise Unfingerprintable(f"plan node {name}")
    return (name, attrs(plan), tuple(_canon_plan(c) for c in plan.children()))


def _canon_logical(plan) -> tuple:
    """Generic canonical form for LogicalPlan trees (all dataclasses whose
    fields are exprs / nested plans / schemas / scalars)."""
    from datafusion_distributed_tpu.sql.lplan import LogicalPlan

    if not isinstance(plan, LogicalPlan):
        raise Unfingerprintable(f"logical node {type(plan).__name__}")
    if not dataclasses.is_dataclass(plan):
        raise Unfingerprintable(f"logical node {type(plan).__name__}")
    parts = []
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if isinstance(v, LogicalPlan):
            parts.append(_canon_logical(v))
        elif isinstance(v, (list, tuple)):
            parts.append(tuple(
                _canon_logical(x) if isinstance(x, LogicalPlan)
                else _canon_value(x)
                for x in v
            ))
        else:
            parts.append(_canon_value(v))
    return (type(plan).__name__,) + tuple(parts)


def _digest(tokens) -> str:
    return hashlib.blake2b(
        repr(tokens).encode("utf-8"), digest_size=16
    ).hexdigest()


def plan_fingerprint(plan) -> Optional[str]:
    """Structural fingerprint of a physical plan, or None when a node
    cannot be canonicalized (callers fall back to identity keying).
    Deliberately failure-proof: a canonicalization bug must degrade to the
    legacy cache key, never fail the query."""
    try:
        return _digest(_canon_plan(plan))
    except Exception:
        return None


def logical_fingerprint(plan) -> Optional[str]:
    """Structural fingerprint of a LOGICAL plan — keys SessionContext's
    physical-plan cache so ``ctx.sql(same_text)`` from distinct submissions
    reuses the planned physical tree. None -> per-DataFrame fallback."""
    try:
        return _digest(_canon_logical(plan))
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Prepared plans
# ---------------------------------------------------------------------------


@dataclass
class PreparedPlan:
    """Execution-ready form of a plan: possibly literal-hoisted, with its
    structural fingerprint and the parameter values the hoist extracted."""

    plan: Any
    fingerprint: Optional[str]
    int_params: tuple
    float_params: tuple

    def param_arrays(self):
        """(int64 vec, float64 vec) host arrays — jit inputs. jax's x32
        canonicalization narrows them exactly like the baked literals the
        hoist replaced (DataType.np_dtype goes through the same precision
        policy)."""
        return (
            np.asarray(self.int_params, dtype=np.int64),
            np.asarray(self.float_params, dtype=np.float64),
        )


_PREP_ATTR = "_dftpu_prepared"


def prepare_plan(plan) -> PreparedPlan:
    """Hoist + fingerprint ``plan``, memoized on the plan object (plans are
    treated as immutable after planning/decoding; rebuilt trees are new
    objects and re-prepare)."""
    prep = getattr(plan, _PREP_ATTR, None)
    if prep is not None:
        return prep
    hoisted_plan, ints, floats = plan, (), ()
    if hoist_enabled():
        col = _HoistCollector()
        try:
            hoisted_plan = _hoist_plan(plan, col)
        except Exception:
            # e.g. a custom node above a hoistable filter without
            # with_new_children — hoisting is an optimization, never a
            # reason to fail the query
            hoisted_plan = plan
        else:
            if col.count:
                ints, floats = tuple(col.ints), tuple(col.floats)
            else:
                hoisted_plan = plan
    fp = plan_fingerprint(hoisted_plan)
    if fp is None:
        # no content address -> no cross-plan sharing; execute the ORIGINAL
        # plan so the legacy identity-keyed path stays parameter-free
        prep = PreparedPlan(plan, None, (), ())
    else:
        prep = PreparedPlan(hoisted_plan, fp, ints, floats)
    try:
        setattr(plan, _PREP_ATTR, prep)
    except AttributeError:
        pass
    return prep


def result_cache_key(plan, extra=()) -> Optional[tuple]:
    """Whole-result cache key for a STAGED plan (runtime/
    result_cache.py): (post-hoist structural fingerprint, hoisted
    int/float literal vectors) + ``extra`` (the session appends its
    PlannerConfig snapshot, catalog generation, and task profile).
    Literal variants of one template share the structural fingerprint
    and differ only in the parameter vectors — each variant keys its
    own entry with its own result. None when the plan has no content
    address (Unfingerprintable nodes): such plans are never cached."""
    prep = prepare_plan(plan)
    if prep.fingerprint is None:
        return None
    return ("rc", prep.fingerprint, prep.int_params,
            prep.float_params) + tuple(extra)
