"""Exchange operators: the stage-boundary nodes of the distributed plan.

These are the TPU-native counterparts of the reference's three
`NetworkBoundary` implementations (`/root/reference/src/execution_plans/`):

    ShuffleExchangeExec   <- NetworkShuffleExec   (hash N:M re-shard)
    CoalesceExchangeExec  <- NetworkCoalesceExec  (N -> 1 concat)
    BroadcastExchangeExec <- NetworkBroadcastExec (replicate to all)

A boundary splits the plan into stages (producer below, consumer above).
Under the mesh executor the whole staged tree traces into one SPMD program —
`execute` simply emits the collective. The boundary duality of the reference
(Pending/Ready; `network_shuffle.rs` Stage::Local vs Stage::Remote) shows up
here as: the same node can run in-mesh (collective) or across meshes via the
host runtime (runtime/), which materializes producer output and re-feeds
consumers — that path is the DCN/multi-host fallback.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from datafusion_distributed_tpu.ops.table import Column, Table, round_up_pow2
from datafusion_distributed_tpu.parallel.exchange import (
    broadcast_exchange,
    coalesce_exchange,
    group_coalesce_exchange,
    shuffle_exchange,
)
from datafusion_distributed_tpu.plan.physical import ExecContext, ExecutionPlan


class ExchangeExec(ExecutionPlan):
    """Common base: a stage boundary with a producer child."""

    is_exchange = True

    def __init__(self, child: ExecutionPlan, num_tasks: int):
        super().__init__()
        self.child = child
        self.num_tasks = num_tasks
        # stamped by the prepare pass (stage ids mirror the reference's
        # (query_id, stage_num) TaskKey addressing)
        self.stage_id: Optional[int] = None
        # producer-stage task count when it differs from the consumer side
        # (stamped by the task-count lattice; None = uniform num_tasks).
        # Coalesce's num_tasks already IS the producer count.
        self.producer_tasks: Optional[int] = None
        # downstream LIMIT's fetch+skip (stamped by the planner's limit
        # rule): the streaming data plane stops pulling producer chunks
        # once this many rows arrived (host tier only; in-mesh collectives
        # are single-program and already bounded by the local limit)
        self.consumer_fetch: Optional[int] = None
        # planner-predicted bytes crossing this boundary (stamped by the
        # partial-aggregate push-down from sampled NDV statistics; the
        # coordinator records predicted-vs-measured through the telemetry
        # registry). Never a compile-cache or fingerprint input — it
        # annotates the plan, it does not shape the trace.
        self.predicted_exchange_bytes: Optional[int] = None

    def children(self):
        return [self.child]

    def schema(self):
        return self.child.schema()

    def execute(self, ctx: ExecContext):
        """Memoized: an exchange's collective runs exactly once per traced
        program (see ExecContext.exchange_cache)."""
        cached = ctx.exchange_cache.get(self.node_id)
        if cached is not None:
            return cached
        out = super().execute(ctx)
        ctx.exchange_cache[self.node_id] = out
        return out

    def _require_axis(self, ctx: ExecContext) -> str:
        axis = ctx.config.get("mesh_axis")
        if axis is None:
            raise RuntimeError(
                f"{type(self).__name__} executed outside a mesh; use the "
                "distributed executor (runtime/) or a shard_map context"
            )
        return axis


class ShuffleExchangeExec(ExchangeExec):
    """Hash shuffle: rows re-shard across tasks by key hash."""

    def __init__(
        self,
        child: ExecutionPlan,
        key_names: Sequence[str],
        num_tasks: int,
        per_dest_capacity: int,
    ):
        super().__init__(child, num_tasks)
        self.key_names = list(key_names)
        # sizing policy lives in planner/distributed.py _mk_shuffle (driven
        # by DistributedConfig.shuffle_skew_factor and the overflow retry)
        self.per_dest_capacity = per_dest_capacity

    def with_new_children(self, children):
        n = ShuffleExchangeExec(
            children[0], self.key_names, self.num_tasks, self.per_dest_capacity
        )
        n.stage_id = self.stage_id
        n.producer_tasks = self.producer_tasks
        n.consumer_fetch = self.consumer_fetch
        n.predicted_exchange_bytes = self.predicted_exchange_bytes
        return n

    def output_capacity(self):
        # a consumer task receives <= per_dest_capacity from EACH producer
        # task (mesh tier: producers == the axis width == num_tasks)
        t_prod = (self.producer_tasks if self.producer_tasks is not None
                  else self.num_tasks)
        return t_prod * self.per_dest_capacity

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        out, overflow = shuffle_exchange(
            t, self.key_names, self._require_axis(ctx), self.num_tasks,
            self.per_dest_capacity,
        )
        ctx.record_overflow(self, overflow)
        return out

    def display(self):
        return (
            f"ShuffleExchange keys=[{', '.join(self.key_names)}] "
            f"tasks={self.num_tasks} per_dest_cap={self.per_dest_capacity}"
        )


class RangeShuffleExchangeExec(ExchangeExec):
    """Range shuffle on a composite SORT key (distributed sample sort):
    after this exchange, task i's rows all order before task i+1's, so a
    LOCAL sort per task followed by an order-preserving coalesce yields
    the global sort order. Replaces the coalesce-then-global-sort plan for
    unlimited ORDER BY: the old shape made every device gather and re-sort
    the full T*C dataset; this one sorts T-way in parallel and never
    re-sorts after the gather. (The reference leans on single-node
    SortPreservingMergeExec above a coalesce, `inject_network_boundaries.rs`
    sort case — a merge is the streaming-CPU analogue of the same idea.)
    """

    def __init__(
        self,
        child: ExecutionPlan,
        sort_keys,  # list[ops.sort.SortKey]
        num_tasks: int,
        per_dest_capacity: int,
    ):
        super().__init__(child, num_tasks)
        self.sort_keys = list(sort_keys)
        self.per_dest_capacity = per_dest_capacity

    def with_new_children(self, children):
        n = RangeShuffleExchangeExec(
            children[0], self.sort_keys, self.num_tasks,
            self.per_dest_capacity,
        )
        n.stage_id = self.stage_id
        n.producer_tasks = self.producer_tasks
        n.consumer_fetch = self.consumer_fetch
        return n

    def output_capacity(self):
        t_prod = (self.producer_tasks if self.producer_tasks is not None
                  else self.num_tasks)
        return t_prod * self.per_dest_capacity

    def _execute(self, ctx: ExecContext) -> Table:
        from datafusion_distributed_tpu.parallel.exchange import (
            range_shuffle_exchange,
        )

        t = self.child.execute(ctx)
        out, overflow = range_shuffle_exchange(
            t, self.sort_keys, self._require_axis(ctx), self.num_tasks,
            self.per_dest_capacity,
        )
        ctx.record_overflow(self, overflow)
        return out

    def display(self):
        keys = ", ".join(
            f"{k.name}{'' if k.ascending else ' DESC'}" for k in self.sort_keys
        )
        return (
            f"RangeShuffleExchange keys=[{keys}] tasks={self.num_tasks} "
            f"per_dest_cap={self.per_dest_capacity}"
        )


class PartitionReplicatedExec(ExchangeExec):
    """REPLICATED -> PARTITIONED: every task keeps the row-index slice
    ``row % num_tasks == task`` of its (identical) copy. No communication —
    the inverse of a broadcast, used when a replicated subtree feeds a
    partition-wise consumer (e.g. a UNION arm)."""

    def with_new_children(self, children):
        n = PartitionReplicatedExec(children[0], self.num_tasks)
        n.stage_id = self.stage_id
        n.producer_tasks = self.producer_tasks
        n.consumer_fetch = self.consumer_fetch
        return n

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        import jax

        t = self.child.execute(ctx)
        axis = self._require_axis(ctx)
        me = jax.lax.axis_index(axis)
        idx = jnp.arange(t.capacity, dtype=jnp.int32)
        keep = t.row_mask() & ((idx % self.num_tasks) == me)
        return t.compact(keep)

    def display(self):
        return f"PartitionReplicated tasks={self.num_tasks}"


class CoalesceExchangeExec(ExchangeExec):
    """Producer tasks' rows coalesced for the consumer stage.

    ``num_consumers == 1`` (default): gathered into one logical table,
    replicated on every task (the consumer stage is the SPMD root).
    ``num_consumers = M > 1``: true N:M — consumer task j holds the
    contiguous producer group [j*g, (j+1)*g), g = div_ceil(N, M) (the
    reference's `network_coalesce.rs` arithmetic); memory per task is
    g*C instead of N*C."""

    def __init__(self, child: ExecutionPlan, num_tasks: int,
                 num_consumers: int = 1):
        super().__init__(child, num_tasks)
        self.num_consumers = num_consumers

    def with_new_children(self, children):
        n = CoalesceExchangeExec(
            children[0], self.num_tasks, self.num_consumers
        )
        n.stage_id = self.stage_id
        n.producer_tasks = self.producer_tasks
        n.consumer_fetch = self.consumer_fetch
        return n

    def output_capacity(self):
        if self.num_consumers > 1:
            g = -(-self.num_tasks // self.num_consumers)
            return self.child.output_capacity() * g
        return self.child.output_capacity() * self.num_tasks

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        axis = self._require_axis(ctx)
        if self.num_consumers > 1:
            return group_coalesce_exchange(
                t, axis, self.num_tasks, self.num_consumers
            )
        return coalesce_exchange(t, axis, self.num_tasks)

    def display(self):
        m = (f" consumers={self.num_consumers}"
             if self.num_consumers > 1 else "")
        return f"CoalesceExchange tasks={self.num_tasks}{m}"


class IsolatedArmExec(ExecutionPlan):
    """One UNION arm assigned to a single task — the TPU-native analogue of
    the reference's ChildrenIsolatorUnionExec child->task assignment
    (`children_isolator_union.rs:39-100`). A replicated arm would otherwise
    be computed identically on EVERY task and deduplicated after the fact
    (x T wasted compute); isolation computes it exactly once:

    - mesh tier: `lax.cond(axis_index == assigned, run_arm, empty)` — SPMD
      control flow diverges per device, the arm's FLOPs execute on one chip
      (arms contain no collectives by construction: exchanges end stages)
    - host tier: task specialization ships the arm only to its assigned
      worker (other tasks get an empty scan), mirroring the reference's
      task-specialized plan stripping (`query_coordinator.rs:346-382`)
    """

    def __init__(self, child: ExecutionPlan, assigned_task: int):
        super().__init__()
        self.child = child
        self.assigned_task = assigned_task

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return IsolatedArmExec(children[0], self.assigned_task)

    def schema(self):
        return self.child.schema()

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        import jax

        axis = ctx.config.get("mesh_axis")
        if axis is None:
            # host tier: static task index (specialization usually removed
            # this node already; this is the in-process fallback)
            if ctx.task.task_count > 1 and (
                ctx.task.task_index != self.assigned_task
            ):
                return self._empty_like(ctx)
            return self.child.execute(ctx)
        me = jax.lax.axis_index(axis)

        from datafusion_distributed_tpu.ops.table import (
            pin_dictionary_caches,
        )

        with pin_dictionary_caches():
            return self._execute_mesh_arm(ctx, me)

    def _execute_mesh_arm(self, ctx: ExecContext, me) -> Table:
        """Probe + lax.cond traces, with the dictionary memo caches pinned
        for the duration: both traces must observe the SAME Dictionary
        objects or their pytree metadata diverges (ops/table.py)."""
        import jax

        # Exchanges inside the arm contain COLLECTIVES, which every task
        # must execute unconditionally (a collective inside one lax.cond
        # branch deadlocks/aborts). Pre-execute them into the shared cache
        # with the REAL context (their overflow flags propagate normally);
        # the conditioned part is then only the arm's post-exchange local
        # compute — which is exactly the duplicated-work segment isolation
        # exists to eliminate.
        for ex in self.child.collect(
            lambda n: getattr(n, "is_exchange", False)
        ):
            ex.execute(ctx)

        # Probe the arm under a throwaway context (sharing the exchange
        # cache): its outputs are used for SHAPES/DTYPES only, so XLA
        # dead-code-eliminates the probe's compute; its overflow/metric
        # lists tell us the side-channel structure the cond branches must
        # return explicitly (tracers may not escape a branch via ctx lists).
        probe_ctx = ExecContext(
            task=ctx.task, inputs=ctx.inputs, config=ctx.config,
            exchange_cache=ctx.exchange_cache,
        )
        probe = self.child.execute(probe_ctx)
        flag_names = [name for name, _ in probe_ctx.overflow_flags]
        metric_keys = [(nid, name) for nid, name, _ in probe_ctx.metrics]
        metric_dtypes = [v.dtype for _, _, v in probe_ctx.metrics]

        def run_arm(_):
            c2 = ExecContext(
                task=ctx.task, inputs=ctx.inputs, config=ctx.config,
                exchange_cache=ctx.exchange_cache,
            )
            t = self.child.execute(c2)
            return (
                t,
                tuple(f for _, f in c2.overflow_flags),
                tuple(v for _, _, v in c2.metrics),
            )

        def empty_arm(_):
            cols = tuple(
                Column(
                    jnp.zeros(c.data.shape, c.data.dtype),
                    jnp.zeros(c.validity.shape, jnp.bool_)
                    if c.validity is not None else None,
                    c.dtype,
                    c.dictionary,
                )
                for c in probe.columns
            )
            t = Table(probe.names, cols, jnp.zeros((), dtype=jnp.int32))
            return (
                t,
                tuple(jnp.zeros((), jnp.bool_) for _ in flag_names),
                tuple(jnp.zeros((), d) for d in metric_dtypes),
            )

        out, flags, metrics = jax.lax.cond(
            me == self.assigned_task, run_arm, empty_arm, None
        )
        for name, f in zip(flag_names, flags):
            ctx.overflow_flags.append((name, f))
        for (nid, name), v in zip(metric_keys, metrics):
            ctx.metrics.append((nid, name, v))
        return out

    def _empty_like(self, ctx: ExecContext) -> Table:
        probe_ctx = ExecContext(
            task=ctx.task, inputs=ctx.inputs, config=ctx.config
        )
        t = self.child.execute(probe_ctx)
        return Table(t.names, t.columns, jnp.zeros((), dtype=jnp.int32))

    def display(self):
        return f"IsolatedArm task={self.assigned_task}"


def assign_arms_to_tasks(weights: Sequence[float], num_tasks: int) -> list:
    """Weighted child->task assignment (greedy LPT): heaviest arm first to
    the least-loaded task. Covers the reference's tasks <, =, > children
    cases (`children_isolator_union.rs:39-83`): with fewer arms than tasks
    some tasks receive none; with more, tasks receive several."""
    loads = [0.0] * num_tasks
    assignment = [0] * len(weights)
    for i in sorted(range(len(weights)), key=lambda i: -weights[i]):
        task = min(range(num_tasks), key=lambda t: loads[t])
        assignment[i] = task
        loads[task] += weights[i]
    return assignment


class BroadcastExchangeExec(ExchangeExec):
    """Replicate rows to every task (broadcast-join build sides)."""

    def with_new_children(self, children):
        n = BroadcastExchangeExec(children[0], self.num_tasks)
        n.stage_id = self.stage_id
        n.producer_tasks = self.producer_tasks
        n.consumer_fetch = self.consumer_fetch
        return n

    def output_capacity(self):
        return self.child.output_capacity() * self.num_tasks

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        return broadcast_exchange(t, self._require_axis(ctx), self.num_tasks)

    def display(self):
        return f"BroadcastExchange tasks={self.num_tasks}"
