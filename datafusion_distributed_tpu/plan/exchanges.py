"""Exchange operators: the stage-boundary nodes of the distributed plan.

These are the TPU-native counterparts of the reference's three
`NetworkBoundary` implementations (`/root/reference/src/execution_plans/`):

    ShuffleExchangeExec   <- NetworkShuffleExec   (hash N:M re-shard)
    CoalesceExchangeExec  <- NetworkCoalesceExec  (N -> 1 concat)
    BroadcastExchangeExec <- NetworkBroadcastExec (replicate to all)

A boundary splits the plan into stages (producer below, consumer above).
Under the mesh executor the whole staged tree traces into one SPMD program —
`execute` simply emits the collective. The boundary duality of the reference
(Pending/Ready; `network_shuffle.rs` Stage::Local vs Stage::Remote) shows up
here as: the same node can run in-mesh (collective) or across meshes via the
host runtime (runtime/), which materializes producer output and re-feeds
consumers — that path is the DCN/multi-host fallback.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from datafusion_distributed_tpu.ops.table import Table, round_up_pow2
from datafusion_distributed_tpu.parallel.exchange import (
    broadcast_exchange,
    coalesce_exchange,
    shuffle_exchange,
)
from datafusion_distributed_tpu.plan.physical import ExecContext, ExecutionPlan


class ExchangeExec(ExecutionPlan):
    """Common base: a stage boundary with a producer child."""

    is_exchange = True

    def __init__(self, child: ExecutionPlan, num_tasks: int):
        super().__init__()
        self.child = child
        self.num_tasks = num_tasks
        # stamped by the prepare pass (stage ids mirror the reference's
        # (query_id, stage_num) TaskKey addressing)
        self.stage_id: Optional[int] = None

    def children(self):
        return [self.child]

    def schema(self):
        return self.child.schema()

    def _require_axis(self, ctx: ExecContext) -> str:
        axis = ctx.config.get("mesh_axis")
        if axis is None:
            raise RuntimeError(
                f"{type(self).__name__} executed outside a mesh; use the "
                "distributed executor (runtime/) or a shard_map context"
            )
        return axis


class ShuffleExchangeExec(ExchangeExec):
    """Hash shuffle: rows re-shard across tasks by key hash."""

    def __init__(
        self,
        child: ExecutionPlan,
        key_names: Sequence[str],
        num_tasks: int,
        per_dest_capacity: int,
    ):
        super().__init__(child, num_tasks)
        self.key_names = list(key_names)
        # sizing policy lives in planner/distributed.py _mk_shuffle (driven
        # by DistributedConfig.shuffle_skew_factor and the overflow retry)
        self.per_dest_capacity = per_dest_capacity

    def with_new_children(self, children):
        n = ShuffleExchangeExec(
            children[0], self.key_names, self.num_tasks, self.per_dest_capacity
        )
        n.stage_id = self.stage_id
        return n

    def output_capacity(self):
        return self.num_tasks * self.per_dest_capacity

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        out, overflow = shuffle_exchange(
            t, self.key_names, self._require_axis(ctx), self.num_tasks,
            self.per_dest_capacity,
        )
        ctx.record_overflow(self, overflow)
        return out

    def display(self):
        return (
            f"ShuffleExchange keys=[{', '.join(self.key_names)}] "
            f"tasks={self.num_tasks} per_dest_cap={self.per_dest_capacity}"
        )


class PartitionReplicatedExec(ExchangeExec):
    """REPLICATED -> PARTITIONED: every task keeps the row-index slice
    ``row % num_tasks == task`` of its (identical) copy. No communication —
    the inverse of a broadcast, used when a replicated subtree feeds a
    partition-wise consumer (e.g. a UNION arm)."""

    def with_new_children(self, children):
        n = PartitionReplicatedExec(children[0], self.num_tasks)
        n.stage_id = self.stage_id
        return n

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        import jax

        t = self.child.execute(ctx)
        axis = self._require_axis(ctx)
        me = jax.lax.axis_index(axis)
        idx = jnp.arange(t.capacity, dtype=jnp.int32)
        keep = t.row_mask() & ((idx % self.num_tasks) == me)
        return t.compact(keep)

    def display(self):
        return f"PartitionReplicated tasks={self.num_tasks}"


class CoalesceExchangeExec(ExchangeExec):
    """All tasks' rows gathered into one logical table (replicated)."""

    def with_new_children(self, children):
        n = CoalesceExchangeExec(children[0], self.num_tasks)
        n.stage_id = self.stage_id
        return n

    def output_capacity(self):
        return self.child.output_capacity() * self.num_tasks

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        return coalesce_exchange(t, self._require_axis(ctx), self.num_tasks)

    def display(self):
        return f"CoalesceExchange tasks={self.num_tasks}"


class BroadcastExchangeExec(ExchangeExec):
    """Replicate rows to every task (broadcast-join build sides)."""

    def with_new_children(self, children):
        n = BroadcastExchangeExec(children[0], self.num_tasks)
        n.stage_id = self.stage_id
        return n

    def output_capacity(self):
        return self.child.output_capacity() * self.num_tasks

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        return broadcast_exchange(t, self._require_axis(ctx), self.num_tasks)

    def display(self):
        return f"BroadcastExchange tasks={self.num_tasks}"
