// Native host data plane for the cross-host (DCN) runtime tier.
//
// The reference's entire engine is native (Rust); in the TPU design the
// device compute path is XLA-compiled (native by construction), and THIS
// library covers the host-side hot loops of the coordinator/worker runtime:
// the shuffle regroup between stages (hash + bucket CSR build) that the
// reference performs in its RepartitionExec/Flight encode pipeline.
//
// The hash MUST be bit-identical to ops/hash.py (murmur3 fmix32 mixing over
// folded uint32 lanes) so rows co-locate whether a shuffle ran on-device
// (lax.all_to_all inside the mesh) or host-side (this code, across meshes).
//
// Build: g++ -O3 -shared -fPIC (see native/build.py). Bound via ctypes.

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

// fold an int64 payload to a uint32 lane: hi ^ lo (matches
// ops/hash.py fold_to_u32 for int64/float64-bitcast columns)
inline uint32_t fold64(int64_t v) {
    uint64_t u = static_cast<uint64_t>(v);
    return static_cast<uint32_t>(u ^ (u >> 32));
}

}  // namespace

extern "C" {

// Combined hash of multiple key columns.
//   cols:   ncols pointers to int64 payload arrays [n]
//           (callers pre-normalize: int64/date/int32 cast to int64;
//            float64 bitcast to int64; float32 bits zero-extended)
//   kinds:  per column: 0 = fold hi^lo (64-bit payloads),
//                       1 = low 32 bits used directly (32-bit payloads)
//   valids: ncols pointers to uint8 validity arrays [n] (or nullptr)
//   out:    uint32 hash per row
void dftpu_hash_rows(const int64_t* const* cols, const int32_t* kinds,
                     const uint8_t* const* valids, int32_t ncols, int64_t n,
                     uint32_t* out) {
    for (int64_t i = 0; i < n; ++i) out[i] = 0x9E3779B9u;
    for (int32_t c = 0; c < ncols; ++c) {
        const int64_t* col = cols[c];
        const uint8_t* valid = valids[c];
        const uint32_t mult = 0x01000193u + 2u * static_cast<uint32_t>(c);
        const int32_t kind = kinds[c];
        for (int64_t i = 0; i < n; ++i) {
            uint32_t lane = kind == 0
                                ? fold64(col[i])
                                : static_cast<uint32_t>(col[i]);
            if (valid != nullptr && valid[i] == 0) lane = 0xDEADBEEFu;
            out[i] = (out[i] ^ fmix32(lane)) * mult;
        }
    }
    for (int64_t i = 0; i < n; ++i) out[i] = fmix32(out[i]);
}

// Destinations + per-bucket counts for a hash shuffle. Dead rows get
// dest = -1 and are not counted.
void dftpu_shuffle_dest(const uint32_t* hash, const uint8_t* live, int64_t n,
                        int32_t parts, int32_t* dest, int64_t* counts) {
    for (int32_t p = 0; p < parts; ++p) counts[p] = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (live != nullptr && live[i] == 0) {
            dest[i] = -1;
            continue;
        }
        int32_t d = static_cast<int32_t>(hash[i] % static_cast<uint32_t>(parts));
        dest[i] = d;
        counts[d] += 1;
    }
}

// CSR of row indices grouped by destination: offsets[parts+1], indices[live].
void dftpu_bucket_indices(const int32_t* dest, int64_t n, int32_t parts,
                          const int64_t* counts, int64_t* offsets,
                          int64_t* indices) {
    offsets[0] = 0;
    for (int32_t p = 0; p < parts; ++p) offsets[p + 1] = offsets[p] + counts[p];
    // cursor per bucket
    int64_t* cursor = new int64_t[parts];
    for (int32_t p = 0; p < parts; ++p) cursor[p] = offsets[p];
    for (int64_t i = 0; i < n; ++i) {
        int32_t d = dest[i];
        if (d < 0) continue;
        indices[cursor[d]++] = i;
    }
    delete[] cursor;
}

int32_t dftpu_version() { return 1; }

}  // extern "C"
