"""ctypes bindings to the native host data plane (see src/dftpu.cpp).

Compiled lazily with g++ on first use; falls back cleanly (callers check
`available()`) when no toolchain exists. The hash here is bit-identical to
ops/hash.py so host-side and in-mesh shuffles co-locate keys identically.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from datafusion_distributed_tpu.schema import DataType

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "dftpu.cpp")
_SO = os.path.join(_HERE, "libdftpu.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


_HASH_FILE = _SO + ".sha256"


def _src_hash() -> str:
    import hashlib

    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO],
            check=True,
            capture_output=True,
            timeout=120,
        )
        with open(_HASH_FILE, "w") as f:
            f.write(_src_hash())
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # staleness by content hash, not mtime: a checked-out tree can't be
        # trusted to have meaningful mtimes, and a stale binary would break
        # the bit-identical-hash guarantee with the device kernel
        current = None
        if os.path.exists(_HASH_FILE):
            with open(_HASH_FILE) as f:
                current = f.read().strip()
        needs_build = not os.path.exists(_SO) or current != _src_hash()
        if needs_build and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.dftpu_hash_rows.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            np.ctypeslib.ndpointer(np.int32),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_int32,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.uint32),
        ]
        lib.dftpu_shuffle_dest.argtypes = [
            np.ctypeslib.ndpointer(np.uint32),
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int32),
            np.ctypeslib.ndpointer(np.int64),
        ]
        lib.dftpu_bucket_indices.argtypes = [
            np.ctypeslib.ndpointer(np.int32),
            ctypes.c_int64,
            ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int64),
            np.ctypeslib.ndpointer(np.int64),
            np.ctypeslib.ndpointer(np.int64),
        ]
        lib.dftpu_version.restype = ctypes.c_int32
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def hash_rows(cols: list[np.ndarray], valids: list[Optional[np.ndarray]],
              dtypes: list[DataType]) -> np.ndarray:
    """Combined uint32 hash, bit-identical to ops.hash.hash_columns."""
    lib = _load()
    assert lib is not None
    n = len(cols[0])
    payloads = []
    kinds = np.zeros(len(cols), dtype=np.int32)
    for i, (c, dt) in enumerate(zip(cols, dtypes)):
        # Dispatch on the ACTUAL array dtype, not the logical DataType: in
        # tpu precision mode logical INT64/FLOAT64 columns are stored as
        # int32/float32 on device, and parity means hashing those exact bits.
        adt = np.asarray(c).dtype
        if adt == np.int64:
            payloads.append(np.ascontiguousarray(c, dtype=np.int64))
            kinds[i] = 0
        elif adt == np.float64:
            payloads.append(
                np.ascontiguousarray(c, dtype=np.float64).view(np.int64)
            )
            kinds[i] = 0
        elif adt == np.float32:
            bits = np.ascontiguousarray(c, dtype=np.float32).view(np.uint32)
            payloads.append(bits.astype(np.int64))
            kinds[i] = 1
        else:  # int32 / date32 / bool / dict codes: astype(uint32) semantics
            u = np.ascontiguousarray(c).astype(np.int64)
            payloads.append(u & np.int64(0xFFFFFFFF))
            kinds[i] = 1
    col_ptrs = (ctypes.c_void_p * len(cols))(
        *[p.ctypes.data_as(ctypes.c_void_p) for p in payloads]
    )
    vbufs = []
    vptrs = (ctypes.c_void_p * len(cols))()
    for i, v in enumerate(valids):
        if v is None:
            vptrs[i] = None
        else:
            vb = np.ascontiguousarray(v, dtype=np.uint8)
            vbufs.append(vb)
            vptrs[i] = vb.ctypes.data_as(ctypes.c_void_p).value
    out = np.empty(n, dtype=np.uint32)
    lib.dftpu_hash_rows(col_ptrs, kinds, vptrs, len(cols), n, out)
    return out


def shuffle_buckets(
    hash_: np.ndarray, live: Optional[np.ndarray], parts: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """-> (offsets[parts+1], indices[sum(counts)], counts[parts]): CSR of row
    indices per destination bucket."""
    lib = _load()
    assert lib is not None
    n = len(hash_)
    dest = np.empty(n, dtype=np.int32)
    counts = np.empty(parts, dtype=np.int64)
    live_ptr = None
    if live is not None:
        live8 = np.ascontiguousarray(live, dtype=np.uint8)
        live_ptr = live8.ctypes.data_as(ctypes.c_void_p)
    lib.dftpu_shuffle_dest(
        np.ascontiguousarray(hash_, dtype=np.uint32), live_ptr, n, parts,
        dest, counts,
    )
    offsets = np.empty(parts + 1, dtype=np.int64)
    indices = np.empty(int(counts.sum()), dtype=np.int64)
    lib.dftpu_bucket_indices(dest, n, parts, counts, offsets, indices)
    return offsets, indices, counts
