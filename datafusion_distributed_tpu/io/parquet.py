"""Host-side Parquet/Arrow -> device Table loading.

The reference's scan path is DataFusion's `DataSourceExec` over Parquet
(SURVEY.md L0) with per-task file-group slicing
(`/root/reference/src/distributed_planner/task_estimator.rs:235-300`). On TPU
the decode stays on the host (pyarrow), and the upload pads each batch to a
static capacity; string columns are dictionary-encoded against a per-dataset
unified dictionary so device-side codes are comparable across files and tasks.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from datafusion_distributed_tpu.ops.table import (
    Column,
    Dictionary,
    Table,
    round_up_pow2,
)
from datafusion_distributed_tpu.schema import DataType, Field, Schema


def _arrow_type_to_dtype(t) -> DataType:
    import pyarrow as pa

    if pa.types.is_int8(t) or pa.types.is_int16(t) or pa.types.is_int32(t):
        return DataType.INT32
    if pa.types.is_int64(t) or pa.types.is_uint32(t) or pa.types.is_uint64(t):
        return DataType.INT64
    if pa.types.is_uint8(t) or pa.types.is_uint16(t):
        return DataType.INT32
    if pa.types.is_float32(t):
        return DataType.FLOAT32
    if pa.types.is_float64(t):
        return DataType.FLOAT64
    if pa.types.is_decimal(t):
        return DataType.FLOAT64
    if pa.types.is_boolean(t):
        return DataType.BOOL
    if pa.types.is_date(t):
        return DataType.DATE32
    if pa.types.is_timestamp(t):
        return DataType.INT64
    if pa.types.is_string(t) or pa.types.is_large_string(t) or (
        pa.types.is_dictionary(t)
    ):
        return DataType.STRING
    raise NotImplementedError(f"unsupported arrow type: {t}")


def schema_from_arrow(arrow_schema) -> Schema:
    return Schema(
        [
            Field(f.name, _arrow_type_to_dtype(f.type), nullable=f.nullable)
            for f in arrow_schema
        ]
    )


def arrow_to_host_columns(
    arrow_table,
    dictionaries: Optional[dict[str, Dictionary]] = None,
) -> tuple[dict[str, np.ndarray], dict[str, np.ndarray], dict[str, Dictionary], Schema]:
    """Arrow table -> (host data arrays, validity arrays, dictionaries, schema).

    String columns become int32 code arrays. If ``dictionaries`` supplies a
    Dictionary for a column, codes are produced against it (values missing
    from the dictionary become -1/null); otherwise a fresh sorted dictionary
    is built from the column's values.
    """
    import pyarrow as pa
    import pyarrow.compute as pc

    schema = schema_from_arrow(arrow_table.schema)
    meta = arrow_table.schema.metadata or {}
    if b"dftpu_logical" in meta:
        # wire payloads carry their LOGICAL dtypes (runtime/codec.py): the
        # physical arrow width reflects the sender's precision mode, not
        # the column's logical type
        import json as _json

        logical = _json.loads(meta[b"dftpu_logical"].decode())
        schema = Schema([
            Field(f.name, DataType(logical.get(f.name, f.dtype.value)),
                  f.nullable)
            for f in schema.fields
        ])
    data: dict[str, np.ndarray] = {}
    validity: dict[str, np.ndarray] = {}
    dicts: dict[str, Dictionary] = {}
    for f in schema.fields:
        col = arrow_table.column(f.name)
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        null_mask = np.asarray(col.is_valid())
        if f.dtype == DataType.STRING:
            provided0 = dictionaries.get(f.name) if dictionaries else None
            if pa.types.is_dictionary(col.type) and provided0 is None:
                # wire fast path: a dictionary array arriving from
                # encode_table carries a GC'd, SORTED dictionary — adopt it
                # and its codes directly instead of decoding + re-uniquing
                # (the receive half of the reference's dictionary handling,
                # `impl_execute_task.rs:184-201` DictionaryHandling::Resend)
                dvals = np.asarray(
                    col.dictionary.to_numpy(zero_copy_only=False),
                    dtype=object,
                )
                sv = dvals.astype(str)
                # STRICTLY ascending == sorted AND duplicate-free: a
                # user-supplied dictionary array with repeated values must
                # fall through to the canonicalizing decode+re-unique path
                # (duplicate entries would give equal strings distinct
                # codes, splitting their groups)
                if len(sv) < 2 or bool(np.all(sv[:-1] < sv[1:])):
                    import pyarrow.compute as pc

                    idx = col.indices
                    if not null_mask.all():
                        idx = pc.fill_null(idx, 0)
                    codes = np.asarray(
                        idx.to_numpy(zero_copy_only=False)
                    ).astype(np.int32)
                    codes = np.where(null_mask, codes, 0).astype(np.int32)
                    data[f.name] = codes
                    dicts[f.name] = Dictionary(dvals)
                    validity[f.name] = null_mask
                    continue
            if pa.types.is_dictionary(col.type):
                col = col.cast(pa.string())
            values = np.asarray(col.to_numpy(zero_copy_only=False), dtype=object)
            strs = np.where(null_mask, values, "").astype(str)
            provided = dictionaries.get(f.name) if dictionaries else None
            if provided is not None:
                d = provided
            else:
                d = Dictionary(np.unique(strs[null_mask]).astype(object))
            # Vectorized encode: a sorted dictionary admits searchsorted with
            # an equality check for absent values; unsorted (caller-provided)
            # dictionaries fall back to the exact hash-map path.
            if len(d.values) == 0:
                codes = np.full(len(strs), -1, dtype=np.int32)
            elif d.is_sorted():
                sorted_vals = d.values.astype(str)
                pos = np.searchsorted(sorted_vals, strs)
                pos_c = np.clip(pos, 0, len(sorted_vals) - 1).astype(np.int32)
                found = sorted_vals[pos_c] == strs
                codes = np.where(found, pos_c, -1).astype(np.int32)
            else:
                idx = d.index()
                codes = np.asarray(
                    [idx.get(v, -1) for v in strs], dtype=np.int32
                )
            null_mask = null_mask & (codes >= 0)
            codes = np.where(codes < 0, 0, codes)
            data[f.name] = codes
            dicts[f.name] = d
        elif f.dtype == DataType.DATE32:
            arr = col.cast(pa.date32()).to_numpy(zero_copy_only=False)
            days = arr.astype("datetime64[D]").astype(np.int64).astype(np.int32)
            days = np.where(null_mask, days, 0).astype(np.int32)
            data[f.name] = days
        elif f.dtype == DataType.BOOL:
            arr = col.to_numpy(zero_copy_only=False)
            arr = np.asarray(arr, dtype=object)
            arr = np.where(null_mask, arr, False)
            data[f.name] = arr.astype(np.bool_)
        else:
            # Fill nulls inside Arrow first: pyarrow's to_numpy converts
            # nullable int columns through float64, which silently rounds
            # int64 values above 2^53 — fatal for join keys. fill_null keeps
            # the column in its native width. Timestamps flow through int64
            # epoch values (cast), dates already handled above. Real (valid)
            # NaN payloads in float columns are preserved as-is.
            if pa.types.is_timestamp(col.type):
                col = col.cast(pa.int64())
            elif pa.types.is_decimal(col.type):
                col = col.cast(pa.float64())
            if not null_mask.all():
                col = pc.fill_null(col, 0)
            arr = col.to_numpy(zero_copy_only=False)
            # Keep the column's native (wide) width here: Column.from_numpy
            # owns the narrowing and range-checks it loudly in tpu precision
            # mode. An astype here would wrap int64 join keys / timestamps
            # silently before the guard could see the wide dtype.
            if np.issubdtype(np.asarray(arr).dtype, np.integer):
                data[f.name] = np.asarray(arr)
            else:
                data[f.name] = np.asarray(arr).astype(
                    f.dtype.logical_np_dtype
                )
        validity[f.name] = null_mask
    return data, validity, dicts, schema


def read_parquet(
    paths: str | Sequence[str],
    columns: Optional[Sequence[str]] = None,
    capacity: Optional[int] = None,
    dictionaries: Optional[dict[str, Dictionary]] = None,
) -> Table:
    """Read parquet file(s) into a single padded device Table."""
    import pyarrow.parquet as pq
    import pyarrow as pa

    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    tables = [pq.read_table(p, columns=list(columns) if columns else None) for p in paths]
    arrow_table = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
    return arrow_to_table(arrow_table, capacity=capacity, dictionaries=dictionaries)


def arrow_to_table(
    arrow_table,
    capacity: Optional[int] = None,
    dictionaries: Optional[dict[str, Dictionary]] = None,
) -> Table:
    data, validity, dicts, schema = arrow_to_host_columns(arrow_table, dictionaries)
    n = arrow_table.num_rows
    cap = capacity or round_up_pow2(max(n, 1))
    return Table.from_numpy(
        data, schema, capacity=cap, validity=validity, dictionaries=dicts
    )


def table_to_arrow(table: Table, dictionary_gc: bool = False,
                   logical_metadata: bool = False):
    """Device Table -> Arrow table (host materialization).

    Default shape decodes strings to plain arrays (pandas-friendly). The
    WIRE shape (``dictionary_gc=True``) instead ships string columns as
    dictionary arrays whose dictionaries are garbage-collected to only the
    values the live rows reference — the reference's dictionary/view-array
    GC before Flight encode (`impl_execute_task.rs:244-274`): a slice
    referencing 10 of a 100k-value dictionary ships 10 values, and
    repeated strings ship as int32 codes. The GC'd subset of a sorted
    dictionary stays sorted, so the receiver adopts it directly
    (arrow_to_host_columns fast path). ``logical_metadata=True`` attaches
    the columns' LOGICAL dtypes as schema metadata: physical arrow widths
    narrow in tpu precision mode (FLOAT64 logical -> f32 device data), and
    a consumer inferring dtypes from the wire would otherwise disagree
    with a same-worker bypass pull of the identical table."""
    import pyarrow as pa

    n = int(table.num_rows)
    arrays = []
    names = []
    for name, col in zip(table.names, table.columns):
        vals = np.asarray(col.data[:n])
        mask = None
        if col.validity is not None:
            mask = ~np.asarray(col.validity[:n])
        if col.dtype == DataType.STRING and dictionary_gc:
            assert col.dictionary is not None
            codes = vals.astype(np.int64)
            valid = np.ones(n, dtype=bool) if mask is None else ~mask
            live = valid & (codes >= 0) & (
                codes < len(col.dictionary.values)
            )
            used = np.unique(codes[live])
            subset = col.dictionary.values[used]
            fill = used[0] if len(used) else 0
            new_codes = np.searchsorted(
                used, np.where(live, codes, fill)
            ).astype(np.int32)
            arrays.append(pa.DictionaryArray.from_arrays(
                pa.array(new_codes, mask=~live),
                pa.array(subset.tolist(), type=pa.string()),
            ))
        elif col.dtype == DataType.STRING:
            assert col.dictionary is not None
            decoded = col.dictionary.decode(vals)
            if mask is not None:
                decoded = decoded.copy()
                decoded[mask] = None
            arrays.append(pa.array(decoded.tolist(), type=pa.string()))
        elif col.dtype == DataType.DATE32:
            arr = pa.array(vals.astype(np.int32), type=pa.int32(), mask=mask)
            arrays.append(arr.cast(pa.date32()))
        else:
            arrays.append(pa.array(vals, mask=mask))
        names.append(name)
    out = pa.table(dict(zip(names, arrays)))
    if logical_metadata:
        import json as _json

        out = out.replace_schema_metadata({
            b"dftpu_logical": _json.dumps({
                name: col.dtype.value
                for name, col in zip(table.names, table.columns)
            }).encode()
        })
    return out
