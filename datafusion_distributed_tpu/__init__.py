"""datafusion_distributed_tpu — a TPU-native distributed columnar query engine.

A ground-up JAX/XLA/Pallas re-design of the capability set of
`datafusion-contrib/datafusion-distributed` (reference at /root/reference):
stage-split distributed query execution, with per-stage columnar compute
compiled by XLA onto TPU and shuffle/broadcast exchanges expressed as mesh
collectives instead of gRPC/Arrow-Flight streams.

Layering (mirrors SURVEY.md §1, re-expressed TPU-first):
- ops/       columnar substrate + compute kernels (the DataFusion-L0 analogue)
- plan/      physical plan IR + expression IR
- planner/   distributed planning passes (boundary injection, task counts, …)
- parallel/  mesh + exchange collectives (shuffle/broadcast/coalesce)
- runtime/   coordinator/worker task runtime
- sql/       SQL frontend (parser -> logical plan -> physical plan)
- io/        host-side Parquet/Arrow <-> device Table
- models/    benchmark workloads (TPC-H, ClickBench) and data generators
"""

import os as _os

import jax as _jax

# A query engine needs real 64-bit integers (join keys at SF>=100 exceed
# int32) and float64 accumulation for result parity with the CPU reference.
_jax.config.update("jax_enable_x64", True)

# Honor JAX_PLATFORMS when a platform plugin force-selected itself at
# registration time (the environment's TPU-tunnel plugin sets
# jax_platforms="axon,cpu", shadowing the env var). Only correct the
# plugin's forced value — never clobber a platform the embedding program
# already chose explicitly via jax.config.update (e.g. tests pinning cpu).
_env_platforms = _os.environ.get("JAX_PLATFORMS")
if _env_platforms:
    try:
        _current = _jax.config.jax_platforms
    except AttributeError:  # pragma: no cover - config name change guard
        _current = None
    if (
        _current is not None
        and _current != _env_platforms
        and "axon" in str(_current)
    ):
        _jax.config.update("jax_platforms", _env_platforms)

from datafusion_distributed_tpu.schema import DataType, Field, Schema  # noqa: E402
from datafusion_distributed_tpu.ops.table import (  # noqa: E402
    Column,
    Dictionary,
    Table,
)

__version__ = "0.1.0"

__all__ = [
    "DataType",
    "Field",
    "Schema",
    "Column",
    "Dictionary",
    "Table",
]
