"""datafusion_distributed_tpu — a TPU-native distributed columnar query engine.

A ground-up JAX/XLA/Pallas re-design of the capability set of
`datafusion-contrib/datafusion-distributed` (reference at /root/reference):
stage-split distributed query execution, with per-stage columnar compute
compiled by XLA onto TPU and shuffle/broadcast exchanges expressed as mesh
collectives instead of gRPC/Arrow-Flight streams.

Layering (mirrors SURVEY.md §1, re-expressed TPU-first):
- ops/       columnar substrate + compute kernels (the DataFusion-L0 analogue)
- plan/      physical plan IR + expression IR
- planner/   distributed planning passes (boundary injection, task counts, …)
- parallel/  mesh + exchange collectives (shuffle/broadcast/coalesce)
- runtime/   coordinator/worker task runtime
- sql/       SQL frontend (parser -> logical plan -> physical plan)
- io/        host-side Parquet/Arrow <-> device Table
- data/      benchmark datasets (TPC-H/TPC-DS/ClickBench generators)
"""

import os as _os

import jax as _jax

# Runtime lock-order / race harness (runtime/lockcheck.py): installed
# FIRST under DFTPU_LOCK_CHECK=1, before any submodule import, so every
# lock the package creates — module-level, class-level and per-instance —
# is wrapped. Observed acquisition order is asserted against the static
# graph (tools/check_concurrency.py); see README "Concurrency model".
if _os.environ.get("DFTPU_LOCK_CHECK", "0") not in ("", "0"):
    from datafusion_distributed_tpu.runtime import lockcheck as _lockcheck

    _lockcheck.install()

# Runtime resource-leak harness (runtime/leakcheck.py): the dynamic half
# of the resource model enforced statically by
# tools/check_resource_lifecycle.py. Installed before submodule imports
# so every tracked acquisition (store entries, spill slots, shm tokens,
# stream pullers, checkpoint slices) is witnessed; see README "Resource
# lifecycle".
if _os.environ.get("DFTPU_LEAK_CHECK", "0") not in ("", "0"):
    from datafusion_distributed_tpu.runtime import leakcheck as _leakcheck

    _leakcheck.install()

# Precision policy: 32-bit TPU-native compute by default; DFTPU_PRECISION=x64
# restores exact f64/i64 (see precision.py for the full rationale).
from datafusion_distributed_tpu import precision  # noqa: F401

# Persistent XLA compilation cache (opt-in via DFTPU_COMPILE_CACHE=<dir>):
# 22 distinct TPC-H programs cost 20-40 s each to compile cold over the TPU
# tunnel; caching them across runs is the difference between a bench run
# fitting its budget or not. Opt-in only: XLA:CPU AOT cache entries embed
# host machine features and reloading them on a different (virtual) host
# risks SIGILL, so tests never want this.
_cache_dir = _os.environ.get("DFTPU_COMPILE_CACHE")
if _cache_dir and _cache_dir != "0":
    try:
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # pragma: no cover - older jax config name guard
        pass
    # jax's persistent cache hard-codes a platform allowlist
    # (compilation_cache.py: supported_platforms = ["tpu","gpu","cpu","neuron"])
    # and silently disables itself for the TPU-tunnel plugin's "axon"
    # platform — which is why four rounds of TPU bench runs never populated
    # the cache despite the plugin's executables serializing fine (verified:
    # runtime_executable().serialize() returns bytes on axon). The allowlist
    # is a local inside the once-per-process check, so the only seam is the
    # check's memoization globals: pre-answer "yes" before any backend
    # initializes. Guarded three ways (advisor round 5): opt-in only
    # (DFTPU_COMPILE_CACHE set), applied only when the axon plugin is the
    # selected platform (cpu/tpu are already on the allowlist and need no
    # override), and only when the memoization globals still have the
    # known bool shape — a jax upgrade that reshapes them (the probe) or
    # renames them (the hasattr-equivalent isinstance check) degrades to
    # jax's stock behavior instead of corrupting private state.
    try:
        _effective_platforms = _os.environ.get("JAX_PLATFORMS") or ""
        if not _effective_platforms:
            try:
                _effective_platforms = str(_jax.config.jax_platforms or "")
            except AttributeError:
                _effective_platforms = ""
        if "axon" in _effective_platforms:
            from jax._src import compilation_cache as _cc

            if isinstance(getattr(_cc, "_cache_checked", None), bool) and (
                isinstance(getattr(_cc, "_cache_used", None), bool)
            ):
                _cc._cache_checked = True
                _cc._cache_used = True
    except Exception:  # pragma: no cover - private-API drift guard
        pass

    # Cache-WRITE budget (DFTPU_COMPILE_CACHE_WRITES=<n>, opt-in): this
    # image's XLA:CPU corrupts its heap after a few hundred in-process
    # compiles and the persistent-cache WRITE serializer is a known crash
    # site (root-caused in run_tests.sh round 5). Long-lived processes that
    # opt into the persistent cache can therefore stop persisting NEW
    # entries after a budget: early entries still land, already-cached
    # programs load without aging the writer, and each restart caches the
    # next slice — converging over a few runs. Lives here (next to the
    # DFTPU_COMPILE_CACHE handling) so EVERY long-lived process is
    # protected, not just benchmarks/sweep_sf.py.
    _write_budget_raw = _os.environ.get("DFTPU_COMPILE_CACHE_WRITES")
    if _write_budget_raw is not None and _write_budget_raw != "":
        try:
            _write_budget = int(_write_budget_raw)
        except ValueError:
            _write_budget = None  # malformed: leave the writer unguarded
        # 0 means "persist NOTHING" (full protection from the crash-prone
        # write serializer), not "no guard" — reads still hit a pre-warmed
        # cache either way
        if _write_budget is not None and _write_budget >= 0:
            try:
                from jax._src import compilation_cache as _cc_wb

                _orig_put = _cc_wb.put_executable_and_time
                _writes = [0]

                def _budgeted_put(*a, **kw):
                    _writes[0] += 1
                    if _writes[0] > _write_budget:
                        return None
                    return _orig_put(*a, **kw)

                _cc_wb.put_executable_and_time = _budgeted_put
            except Exception:  # pragma: no cover - private API drift
                pass

# Honor JAX_PLATFORMS when a platform plugin force-selected itself at
# registration time (the environment's TPU-tunnel plugin sets
# jax_platforms="axon,cpu", shadowing the env var). Only correct the
# plugin's forced value — never clobber a platform the embedding program
# already chose explicitly via jax.config.update (e.g. tests pinning cpu).
_env_platforms = _os.environ.get("JAX_PLATFORMS")
if _env_platforms:
    try:
        _current = _jax.config.jax_platforms
    except AttributeError:  # pragma: no cover - config name change guard
        _current = None
    if (
        _current is not None
        and _current != _env_platforms
        and "axon" in str(_current)
    ):
        _jax.config.update("jax_platforms", _env_platforms)

from datafusion_distributed_tpu.schema import DataType, Field, Schema  # noqa: E402
from datafusion_distributed_tpu.ops.table import (  # noqa: E402
    Column,
    Dictionary,
    Table,
)

def clear_compile_caches() -> None:
    """Drop every compiled-program cache this package (and jax) holds.

    Long multi-query processes accumulate compiled executables — jax's jit
    caches plus this package's program caches — until the address space
    exhausts (observed: 32-128 MiB allocation failures after ~2 h of SF0.5
    queries). Call between queries in long-lived batch processes; later
    queries recompile, reloading from the persistent compile cache when one
    is configured."""
    from datafusion_distributed_tpu.plan import physical as _phys
    from datafusion_distributed_tpu.runtime import (
        mesh_executor as _me,
        worker as _w,
    )

    _phys._COMPILE_CACHE.clear()
    with _w.Worker._stage_compiles_lock:
        _w.Worker._stage_compiles.clear()
    _me._MESH_COMPILE_CACHE.clear()
    _jax.clear_caches()


__version__ = "0.1.0"

__all__ = [
    "DataType",
    "Field",
    "Schema",
    "Column",
    "Dictionary",
    "Table",
]
