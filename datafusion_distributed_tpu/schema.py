"""Logical/physical schema types for the TPU-native columnar engine.

Capability parity target: Apache DataFusion's Arrow schema layer as used by the
reference (`/root/reference/src/` builds on `datafusion = 54`, which brings the
Arrow type system). We support the subset of Arrow types that TPC-H / TPC-DS /
ClickBench need, mapped onto TPU-friendly fixed-width device representations:

- integers/floats  -> same-width jnp arrays
- BOOL             -> bool_
- DATE32           -> int32 days since epoch
- DECIMAL(p, s)    -> float64 (device) [exactness note: result parity harness
                      compares with per-type tolerances, mirroring the float
                      comparison in the reference's
                      `tests/common/property_based.rs`]
- STRING / UTF8    -> dictionary codes (int32) on device + host-side np.ndarray
                      of Python strings, sorted so code order == lexicographic
                      order (enables ORDER BY / min / max on codes directly).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

import numpy as np


class DataType(enum.Enum):
    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    BOOL = "bool"
    DATE32 = "date32"  # days since unix epoch, int32 storage
    STRING = "string"  # dictionary-encoded: int32 codes + host dictionary
    NULL = "null"  # untyped SQL NULL literal; promotes to any peer type

    @property
    def np_dtype(self) -> np.dtype:
        """Device storage dtype: the logical width narrowed per the active
        precision mode (INT64->int32, FLOAT64->float32 in tpu mode; see
        precision.py)."""
        from datafusion_distributed_tpu import precision

        return precision.narrow_np_dtype(_NP_DTYPES[self])

    @property
    def logical_np_dtype(self) -> np.dtype:
        """The mode-independent logical dtype (host/IO width)."""
        return np.dtype(_NP_DTYPES[self])

    @property
    def is_numeric(self) -> bool:
        return self in (
            DataType.INT32,
            DataType.INT64,
            DataType.FLOAT32,
            DataType.FLOAT64,
        )

    @property
    def is_integer(self) -> bool:
        return self in (DataType.INT32, DataType.INT64, DataType.DATE32)

    @property
    def is_float(self) -> bool:
        return self in (DataType.FLOAT32, DataType.FLOAT64)


_NP_DTYPES = {
    DataType.NULL: np.int32,  # placeholder storage; validity is all-false
    DataType.INT32: np.int32,
    DataType.INT64: np.int64,
    DataType.FLOAT32: np.float32,
    DataType.FLOAT64: np.float64,
    DataType.BOOL: np.bool_,
    DataType.DATE32: np.int32,
    DataType.STRING: np.int32,  # device representation: dictionary codes
}


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    nullable: bool = True

    def rename(self, name: str) -> "Field":
        return Field(name, self.dtype, self.nullable)


@dataclass(frozen=True)
class Schema:
    fields: tuple[Field, ...]

    def __init__(self, fields) -> None:
        object.__setattr__(self, "fields", tuple(fields))

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(f"no field named {name!r}; have {self.names}")

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(f"no field named {name!r}; have {self.names}")

    def __contains__(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def __len__(self) -> int:
        return len(self.fields)

    def select(self, names) -> "Schema":
        return Schema([self.field(n) for n in names])

    def join(self, other: "Schema") -> "Schema":
        return Schema(self.fields + other.fields)

    def __repr__(self) -> str:
        inner = ", ".join(f"{f.name}: {f.dtype.value}" for f in self.fields)
        return f"Schema[{inner}]"
