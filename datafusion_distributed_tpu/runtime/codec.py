"""Plan (de)serialization codec.

The reference ships task-specialized plan subtrees to workers as protobuf
(`DistributedCodec`, `/root/reference/src/protobuf/distributed_codec.rs`, with
user-codec composition). Here plans serialize to JSON-able dicts; bulk data
never rides inside the plan — scan leaves serialize as *table references*
into a shipment store (in-process: shared by reference, the
LocalWorkerConnection zero-copy bypass analogue; cross-host: Arrow IPC bytes
via `encode_table`/`decode_table`).

User extension nodes register via `register_codec` (the user-codec registry
analogue, `src/protobuf/user_codec.rs`).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Callable, Optional

from datafusion_distributed_tpu.runtime import leakcheck as _leakcheck
from datafusion_distributed_tpu.ops.aggregate import AggSpec
from datafusion_distributed_tpu.ops.sort import SortKey
from datafusion_distributed_tpu.ops.table import Table
from datafusion_distributed_tpu.plan import expressions as pe
from datafusion_distributed_tpu.plan.exchanges import (
    BroadcastExchangeExec,
    CoalesceExchangeExec,
    IsolatedArmExec,
    PartitionReplicatedExec,
    ShuffleExchangeExec,
)
from datafusion_distributed_tpu.plan.joins import (
    CrossJoinExec,
    HashJoinExec,
    MultiwayHashJoinExec,
    MultiwayJoinStep,
    UnionExec,
)
from datafusion_distributed_tpu.plan.physical import (
    CoalescePartitionsExec,
    ExecutionPlan,
    FilterExec,
    HashAggregateExec,
    LimitExec,
    MemoryScanExec,
    ParquetScanExec,
    PartialPassthroughExec,
    ProjectionExec,
    SortExec,
)
from datafusion_distributed_tpu.schema import DataType, Field, Schema


class CodecError(ValueError):
    pass


_USER_CODECS: dict[str, tuple[Callable, Callable]] = {}


def register_codec(kind: str, encode: Callable, decode: Callable) -> None:
    """Register (encode(node, ctx) -> dict, decode(obj, ctx) -> node) for a
    custom ExecutionPlan type."""
    _USER_CODECS[kind] = (encode, decode)


def _table_nbytes(table) -> int:
    from datafusion_distributed_tpu.runtime.tracing import table_nbytes

    return table_nbytes(table)


def _spill_event(name: str, tid: str, nbytes: int) -> None:
    """Structured spill/refault trace event (runtime/eventlog.py) —
    best-effort: observability must never fail the staging path."""
    try:
        from datafusion_distributed_tpu.runtime.eventlog import log_event

        log_event(name, table_id=tid, nbytes=int(nbytes))
    except Exception:
        pass


class _EntryMeta:
    """Accounting record of one store entry. ``base`` is None for an entry
    that OWNS its buffers (counted once in the store's byte total) and the
    owning entry's id for a view/alias (shares buffers, counted zero);
    ``refs`` counts the aliases of an owning entry. ``spilled`` holds the
    entry's on-disk SpillSlot while its buffers live in the host spill
    segment (runtime/spill.py) instead of memory; ``owner_query`` is the
    query id staging attribution captured at insert (the serving tier's
    estimate-vs-measured loop reads per-query peaks from it)."""

    __slots__ = ("nbytes", "base", "refs", "spilled", "owner_query")

    def __init__(self, nbytes: int, base: Optional[str] = None,
                 owner_query: Optional[str] = None):
        self.nbytes = int(nbytes)
        self.base = base
        self.refs = 0
        self.spilled = None
        self.owner_query = owner_query


class _SpilledSentinel:
    """Placeholder value a spilled entry's table id maps to: the entry is
    LIVE (it still counts as staged, releases normally, leaks if leaked)
    but its buffers are on disk until `get` refaults them."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<spilled>"


_SPILLED = _SpilledSentinel()

#: staging-attribution context (thread-local): while set, owned bytes
#: inserted into ANY TableStore on this thread are attributed to the
#: query id — the coordinator wraps dispatch encodes, the worker wraps
#: decode + partition staging. Per-query peaks close the serving tier's
#: estimate-vs-measured admission loop.
_staging_attr = threading.local()


class staging_attribution:
    """``with staging_attribution(query_id): ...`` — attribute owned-byte
    inserts on this thread to ``query_id`` (None = unattributed)."""

    __slots__ = ("qid", "prev")

    def __init__(self, qid: Optional[str]):
        self.qid = qid
        self.prev = None

    def __enter__(self):
        self.prev = getattr(_staging_attr, "qid", None)
        _staging_attr.qid = self.qid
        return self

    def __exit__(self, *exc):
        _staging_attr.qid = self.prev


def _current_attribution() -> Optional[str]:
    return getattr(_staging_attr, "qid", None)


class _TableDict(dict):
    """tid -> Table mapping of a TableStore. Legacy call sites mutate it
    directly (`store.tables[tid] = t` on the wire receive path,
    `.clear()` on cluster teardown), so the mapping itself routes every
    mutation through the store's byte accounting — the two can never
    disagree."""

    __slots__ = ("_store",)

    def __init__(self, store: "TableStore"):
        super().__init__()
        self._store = store

    def __setitem__(self, tid, table):
        with self._store._lock:
            self._store._release_locked(tid)
            self._store._insert_locked(tid, table)

    def __delitem__(self, tid):
        with self._store._lock:
            if not dict.__contains__(self, tid):
                raise KeyError(tid)
            self._store._release_locked(tid)

    def pop(self, tid, *default):
        with self._store._lock:
            if dict.__contains__(self, tid):
                val = dict.__getitem__(self, tid)
                self._store._release_locked(tid)
                return val
        if default:
            return default[0]
        raise KeyError(tid)

    def clear(self):
        with self._store._lock:
            for tid in list(dict.keys(self)):
                self._store._release_locked(tid)

    def update(self, *args, **kwargs):
        # route through __setitem__ so every inserted entry is accounted
        for k, v in dict(*args, **kwargs).items():
            self[k] = v

    def __ior__(self, other):
        self.update(other)
        return self

    def setdefault(self, tid, default=None):
        with self._store._lock:
            if dict.__contains__(self, tid):
                return dict.__getitem__(self, tid)
            self._store._insert_locked(tid, default)
            return default

    def popitem(self):
        with self._store._lock:
            tid = next(reversed(self), None)
            if tid is None:
                raise KeyError("popitem(): dictionary is empty")
            val = dict.__getitem__(self, tid)
            self._store._release_locked(tid)
            return tid, val


class TableStore:
    """Shipment store: table id -> staged Table — the buffer-owning,
    byte-accounted heart of the zero-copy data plane.

    In-process peers share entries by reference; cross-host transport
    serializes them with encode_table. Callers release shipped entries when
    their task completes (drop-driven cleanup, like the reference's
    partition-drop accounting).

    Zero-copy semantics:

    - ``put`` DEDUPLICATES by table identity: staging the same Table object
      again (broadcast fan-out — one entry per consumer task; retry
      re-ships of unchanged slices) registers an alias that shares the
      buffers and counts ZERO additional bytes. Releasing the owning entry
      while aliases remain promotes an alias (refcounted release, never a
      copy).
    - ``put_view``/``get_slice`` expose row-range VIEWS of a staged entry
      (numpy views of the same buffers via ops.table.slice_view) so
      per-destination slices and chunk streams reference one staged buffer.
    - Thread-safe: serving-tier threads and stage-DAG fan-out threads
      mutate one worker store concurrently; every mutation (including the
      legacy direct `tables[tid] = t` writes) runs under one lock.
    - Byte-accounted: ``nbytes()``/``stats()`` report live owned bytes,
      entry/view counts and the high-water mark — the observability
      service's actual-staged-bytes surface, and the recorded entry sizes
      (`entry_nbytes`) are what dispatch encode spans attribute, so store
      accounting and trace bytes can never disagree.
    - Budget-ENFORCED: when ``budget_bytes`` is set (constructor,
      `set_budget`, the `DFTPU_WORKER_MEM_BUDGET` env, or the
      `distributed.worker_memory_budget_bytes` knob shipped with task
      configs), staging past the budget spills the coldest unreferenced
      owned entries to a host-disk segment (runtime/spill.py) and `get`
      refaults them transparently — byte-exact, original capacity
      preserved. Entries pinned by views/aliases are unspillable (their
      buffers are shared); `under_pressure()` reports residency still
      over budget after spilling, which is what the stream planes'
      producer backpressure keys on. Spill/refault file I/O always runs
      OUTSIDE the store lock (DFTPU205)."""

    def __init__(self, budget_bytes: Optional[int] = None) -> None:
        self._lock = threading.RLock()
        # every mutation of `tables` routes through _TableDict, which
        # takes this store's lock itself — the guarded fields below are
        # the accounting the _locked helpers keep in sync with it
        self.tables: _TableDict = _TableDict(self)
        self._meta: dict[str, _EntryMeta] = {}  # guarded-by: _lock
        self._by_identity: dict[int, str] = {}  # guarded-by: _lock
        self._owned_nbytes = 0  # guarded-by: _lock
        self.peak_nbytes = 0  # guarded-by: _lock
        self.put_count = 0  # guarded-by: _lock
        self.dedup_hits = 0  # guarded-by: _lock
        # -- enforced memory budget (0 = unlimited) --------------------------
        if budget_bytes is None:
            import os

            try:
                budget_bytes = int(float(
                    os.environ.get("DFTPU_WORKER_MEM_BUDGET", "0")
                ))
            except (TypeError, ValueError):
                budget_bytes = 0
        self.budget_bytes = max(int(budget_bytes or 0), 0)  # guarded-by: _lock
        self._spill = None  # SpillManager, lazy  # guarded-by: _lock
        self._spilling: set = set()  # tids mid-spill  # guarded-by: _lock
        self.spilled_nbytes = 0  # live bytes in the segment  # guarded-by: _lock
        self.spill_count = 0  # guarded-by: _lock
        self.refault_count = 0  # guarded-by: _lock
        # -- per-query staging attribution (logical demand, spill-blind) ----
        self._query_bytes: dict[str, int] = {}  # guarded-by: _lock; per-query: bounded 512
        self._query_peak: dict[str, int] = {}  # guarded-by: _lock; per-query: bounded 512

    # -- accounting core (callers hold self._lock) ---------------------------
    def _insert_locked(self, tid: str, table: Table,
                       base: Optional[str] = None,
                       nbytes: Optional[int] = None) -> str:
        meta = _EntryMeta(
            _table_nbytes(table) if nbytes is None else nbytes, base=base,
            owner_query=_current_attribution(),
        )
        dict.__setitem__(self.tables, tid, table)
        self._meta[tid] = meta
        if _leakcheck.enabled():
            _leakcheck.note_acquire(
                "store-entry", (id(self), tid),
                query_id=meta.owner_query,
                tag="view" if base is not None else "owner",
            )
        if base is None:
            self._by_identity[id(table)] = tid
            self._owned_nbytes += meta.nbytes
            self.peak_nbytes = max(self.peak_nbytes, self._owned_nbytes)
            self._attr_add_locked(meta)
        else:
            b = self._meta.get(base)
            if b is not None:
                b.refs += 1
        return tid

    def _attr_add_locked(self, meta: _EntryMeta) -> None:
        """Charge an OWNING insert's logical bytes to its query (spill-
        blind: attribution measures staging DEMAND, which is what the
        admission re-cost loop needs, not residency). Bounded: a
        long-lived worker sheds the oldest query's attribution instead
        of growing per-query dicts forever (sweep_query_attribution is
        the cooperative path)."""
        qid = meta.owner_query
        if not qid or not meta.nbytes:
            return
        cur = self._query_bytes.get(qid, 0) + meta.nbytes
        self._query_bytes[qid] = cur
        if cur > self._query_peak.get(qid, 0):
            self._query_peak[qid] = cur
        while len(self._query_peak) > 512:
            old = next(iter(self._query_peak))
            self._query_peak.pop(old, None)
            self._query_bytes.pop(old, None)

    def _attr_sub_locked(self, meta: _EntryMeta) -> None:
        qid = meta.owner_query
        if not qid or not meta.nbytes:
            return
        cur = self._query_bytes.get(qid)
        if cur is not None:
            self._query_bytes[qid] = max(cur - meta.nbytes, 0)

    def _release_locked(self, tid: str) -> None:
        meta = self._meta.pop(tid, None)
        table = None
        if dict.__contains__(self.tables, tid):
            table = dict.__getitem__(self.tables, tid)
            dict.__delitem__(self.tables, tid)
        if meta is None:
            return
        if _leakcheck.enabled():
            _leakcheck.note_release("store-entry", (id(self), tid))
        if meta.base is not None:
            b = self._meta.get(meta.base)
            if b is not None:
                b.refs = max(b.refs - 1, 0)
            return
        if meta.spilled is not None:
            # spilled owner: its bytes live in the segment, not the
            # resident total — release the disk slot instead (unlink,
            # idempotent, O(1): not a registered blocking call). A view
            # registered against it in put_view's unlocked window still
            # promotes below: the view holds its own pre-spill buffers.
            self.spilled_nbytes -= meta.nbytes
            self._attr_sub_locked(meta)
            if self._spill is not None:
                self._spill.release(meta.spilled)
        else:
            self._owned_nbytes -= meta.nbytes
            self._attr_sub_locked(meta)
            if table is not None and self._by_identity.get(id(table)) == tid:
                del self._by_identity[id(table)]
        if meta.refs > 0:
            # views/aliases still reference the buffers: promote the first
            # one to owner so shared staged bytes stay accounted until the
            # LAST reference drops (refcounted release, not a copy). A
            # promoted slice-view accounts its own logical bytes — a
            # deliberate undercount of the full base buffer it pins.
            heir = next(
                (t2 for t2, m2 in self._meta.items() if m2.base == tid),
                None,
            )
            if heir is not None:
                hm = self._meta[heir]
                hm.base = None
                hm.refs = 0
                for m2 in self._meta.values():
                    if m2 is not hm and m2.base == tid:
                        m2.base = heir
                        hm.refs += 1
                ht = dict.__getitem__(self.tables, heir)
                self._by_identity.setdefault(id(ht), heir)
                self._owned_nbytes += hm.nbytes
                self.peak_nbytes = max(
                    self.peak_nbytes, self._owned_nbytes
                )
                self._attr_add_locked(hm)

    def _canonical(self, tid: str) -> str:
        m = self._meta.get(tid)
        while m is not None and m.base is not None:
            tid = m.base
            m = self._meta.get(tid)
        return tid

    # -- public surface ------------------------------------------------------
    def put(self, table: Table) -> str:  # acquires: store-entry (managed)
        tid = uuid.uuid4().hex
        with self._lock:
            self.put_count += 1
            canon = self._by_identity.get(id(table))
            if canon is not None and dict.get(self.tables, canon) is table:
                # identity dedup: the SAME staged object (broadcast
                # fan-out, retry re-ship) becomes a zero-byte alias
                self.dedup_hits += 1
                self._insert_locked(tid, table, base=canon,
                                    nbytes=self._meta[canon].nbytes)
            else:
                self._insert_locked(tid, table)
        self.enforce_budget()
        return tid

    def put_as(self, tid: str, table: Table) -> str:  # acquires: store-entry (managed)
        """Stage under a caller-chosen id (the wire receive path — the
        shipping side minted the id and the plan references it — and the
        checkpoint store's accounted staging surface)."""
        self.tables[tid] = table
        self.enforce_budget()
        return tid

    def put_view(self, base_tid: str, table: Optional[Table] = None,  # acquires: store-entry (managed)
                 lo: int = 0, count: Optional[int] = None) -> str:
        """Register a zero-copy VIEW of an existing entry as its own id:
        shares the base buffers (zero owned bytes; the base stays pinned by
        refcount until the last view drops). ``table`` may be a view the
        caller already built over the entry's buffers; otherwise rows
        [lo, lo+count) are sliced here via `get_slice`. The base resolves
        BEFORE the lock is taken: a spilled base refaults in `get`, whose
        file I/O must never run under the store lock (DFTPU205)."""
        if table is None:
            base_table = self.get(base_tid)  # refaults a spilled base
            if count is None:
                count = int(base_table.num_rows) - lo
            table = self.get_slice(base_tid, lo, count)
        with self._lock:
            canon = self._canonical(base_tid)
            if canon not in self._meta:
                raise CodecError(
                    f"table {base_tid} not in shipment store"
                )
            # the base may have (re-)spilled inside the unlocked window
            # above: registering the view is still correct — the view
            # holds its own (pre-spill) buffers, and the spilled-owner
            # release path promotes surviving views exactly like the
            # resident path, so nothing leaks accounting either way
            tid = uuid.uuid4().hex
            self.put_count += 1
            self._insert_locked(tid, table, base=canon)
        return tid

    def get(self, tid: str) -> Table:
        with self._lock:
            if not dict.__contains__(self.tables, tid):
                raise CodecError(f"table {tid} not in shipment store")
            val = dict.__getitem__(self.tables, tid)
            m = self._meta.get(tid)
            if m is not None:
                # LRU touch: budget victim selection walks _meta in
                # order, so a re-read entry moves to the hot end
                self._meta[tid] = self._meta.pop(tid)
            if val is not _SPILLED or m is None:
                return val
            slot = m.spilled
        return self._refault(tid, slot)

    def _refault(self, tid: str, slot) -> Table:
        """Restore a spilled entry's buffers from the segment (file read
        OUTSIDE the lock) and re-install them; a raced second refault or
        a raced release both resolve to one consistent winner."""
        from datafusion_distributed_tpu.runtime.spill import SpillError

        try:
            table = self._spill_manager().read_spill(slot)
        except SpillError:
            # a raced WINNER may have refaulted + released (unlinked)
            # the slot between our locked read and this open: re-check
            # under the lock and serve the winner's resident table — the
            # entry is live and recoverable, never an error. A vanished
            # ENTRY (raced remove) keeps the not-in-store contract.
            with self._lock:
                m = self._meta.get(tid)
                if m is None:
                    raise CodecError(
                        f"table {tid} not in shipment store"
                    )
                if dict.__contains__(self.tables, tid):
                    cur = dict.__getitem__(self.tables, tid)
                    if cur is not _SPILLED:
                        return cur
                new_slot = m.spilled
            if new_slot is not None and new_slot is not slot:
                # re-spilled under a fresh slot mid-race: read that one
                return self._refault(tid, new_slot)
            raise
        release_slot = None
        with self._lock:
            m = self._meta.get(tid)
            if m is None or m.spilled is not slot:
                # released (return the content that was live at call
                # time) or already refaulted by a sibling (serve theirs)
                if m is not None and dict.__contains__(self.tables, tid):
                    cur = dict.__getitem__(self.tables, tid)
                    if cur is not _SPILLED:
                        table = cur
            else:
                dict.__setitem__(self.tables, tid, table)
                m.spilled = None
                self._owned_nbytes += m.nbytes
                self.peak_nbytes = max(self.peak_nbytes, self._owned_nbytes)
                self.spilled_nbytes -= m.nbytes
                self.refault_count += 1
                self._by_identity.setdefault(id(table), tid)
                release_slot = slot
        if release_slot is not None:
            self._spill.release(release_slot)
            _spill_event("store_refault", tid,
                         self.entry_nbytes(tid))
            # the refault may push residency back over budget: rebalance
            # by spilling colder entries (never this one — it is now the
            # hottest by LRU order)
            self.enforce_budget()
        return table

    # -- enforced memory budget ---------------------------------------------
    def _spill_manager(self):
        with self._lock:
            if self._spill is None:
                from datafusion_distributed_tpu.runtime.spill import (
                    SpillManager,
                )

                self._spill = SpillManager()
            return self._spill

    def set_budget(self, budget_bytes) -> None:
        """Set/replace the enforced byte budget (0/None = unlimited) and
        rebalance immediately — the chaos `kind="oom"` collapse path."""
        try:
            b = max(int(float(budget_bytes or 0)), 0)
        except (TypeError, ValueError):
            return
        with self._lock:
            self.budget_bytes = b
        self.enforce_budget()

    def under_pressure(self) -> bool:
        """Residency still over budget AFTER spilling (every remaining
        entry is pinned by refs or mid-spill): the producer-backpressure
        signal the stream planes consult."""
        with self._lock:
            return bool(self.budget_bytes) and (
                self._owned_nbytes > self.budget_bytes
            )

    def enforce_budget(self) -> int:
        """Spill coldest unreferenced owned entries until resident owned
        bytes fit the budget; -> bytes spilled. Victims are chosen under
        the lock; the file WRITE runs outside it (DFTPU205), then the
        entry swaps to the spilled sentinel if it is still live and
        unchanged. No-op without a budget. A disk failure degrades to an
        unenforced budget — never a failed staging."""
        from datafusion_distributed_tpu.runtime.spill import SpillError

        spilled_total = 0
        while True:
            with self._lock:
                if not self.budget_bytes or (
                    self._owned_nbytes <= self.budget_bytes
                ):
                    break
                victim = next(
                    (t for t, m in self._meta.items()
                     if m.base is None and m.spilled is None
                     and m.refs == 0 and t not in self._spilling
                     and dict.get(self.tables, t) is not None),
                    None,
                )
                if victim is None:
                    break  # everything left is pinned: backpressure takes over
                self._spilling.add(victim)
                table = dict.__getitem__(self.tables, victim)
                nbytes = self._meta[victim].nbytes
            try:
                slot = self._spill_manager().write_spill(table, nbytes)
            except SpillError:
                with self._lock:
                    self._spilling.discard(victim)
                break  # disk trouble: leave resident, stop trying
            with self._lock:
                self._spilling.discard(victim)
                m = self._meta.get(victim)
                live = (
                    m is not None and m.base is None
                    and m.spilled is None
                    and dict.get(self.tables, victim) is table
                )
                if not live or m.refs > 0:
                    # released/replaced/aliased while the write ran: the
                    # slot is orphaned — drop it (release is idempotent)
                    release_orphan = slot
                else:
                    release_orphan = None
                    dict.__setitem__(self.tables, victim, _SPILLED)
                    m.spilled = slot
                    self._owned_nbytes -= m.nbytes
                    self.spilled_nbytes += m.nbytes
                    self.spill_count += 1
                    spilled_total += m.nbytes
                    if self._by_identity.get(id(table)) == victim:
                        del self._by_identity[id(table)]
            if release_orphan is not None:
                self._spill.release(release_orphan)
            else:
                _spill_event("store_spill", victim, nbytes)
        return spilled_total

    def reset_peak(self) -> int:
        """Reset the high-water mark to the CURRENT residency and return
        the previous peak — per-phase peaks for bench arms (the lifetime
        peak was monotone and made them unmeasurable)."""
        with self._lock:
            prev = self.peak_nbytes
            self.peak_nbytes = self._owned_nbytes
            return prev

    # -- per-query staging attribution ---------------------------------------
    def query_peak_nbytes(self, query_id: str) -> int:
        """Peak logical bytes this query ever had staged here (demand,
        spill-blind) — the measured side of the admission re-cost loop."""
        with self._lock:
            return self._query_peak.get(query_id, 0)

    def query_current_nbytes(self, query_id: str) -> int:
        with self._lock:
            return self._query_bytes.get(query_id, 0)

    def sweep_query_attribution(self, query_id: str) -> int:
        """Drop a resolved query's attribution state; -> its peak."""
        with self._lock:
            self._query_bytes.pop(query_id, None)
            return self._query_peak.pop(query_id, 0)

    def get_slice(self, tid: str, lo: int, count: int) -> Table:
        """Zero-copy row-range view of a staged entry (not registered —
        use `put_view` to give the view its own id/lifetime)."""
        from datafusion_distributed_tpu.ops.table import slice_view

        return slice_view(self.get(tid), lo, count)

    def remove(self, tids) -> None:  # releases: store-entry
        with self._lock:
            for tid in tids:
                self._release_locked(tid)

    # -- accounting surface --------------------------------------------------
    def nbytes(self) -> int:
        """Live owned bytes (shared buffers counted once)."""
        with self._lock:
            return self._owned_nbytes

    def entry_nbytes(self, tid: str) -> int:
        """The recorded logical size of one entry — what a dispatch encode
        span attributes for this table id (always the size recorded at
        put time, so spans and store accounting cannot disagree)."""
        with self._lock:
            m = self._meta.get(tid)
            return m.nbytes if m is not None else 0

    def stats(self) -> dict:
        with self._lock:
            views = sum(
                1 for m in self._meta.values() if m.base is not None
            )
            out = {
                "entries": len(self._meta),
                "nbytes": self._owned_nbytes,
                "views": views,
                "peak_nbytes": self.peak_nbytes,
                "puts": self.put_count,
                "dedup_hits": self.dedup_hits,
                "budget_bytes": self.budget_bytes,
                "spilled_nbytes": self.spilled_nbytes,
                "spills": self.spill_count,
                "refaults": self.refault_count,
            }
            spill = self._spill
        # the spill manager's lock nests AFTER the store lock everywhere
        # else; reading its counters outside ours keeps the static
        # order graph a tree
        if spill is not None:
            ss = spill.stats()
            out["spill_files"] = ss["spill_files"]
            out["spilled_total_bytes"] = ss["spill_bytes"]
            out["refaulted_total_bytes"] = ss["refault_bytes"]
        else:
            out["spill_files"] = 0
            out["spilled_total_bytes"] = 0
            out["refaulted_total_bytes"] = 0
        return out

    def telemetry_families(self) -> list:
        """Typed-registry adapter (runtime/telemetry.py): the staged-byte
        accounting as uniformly named gauges/counters, sampled at
        snapshot time — the `get_metrics` face of the numbers `stats()`
        already keeps (one source of truth, two surfaces)."""
        from datafusion_distributed_tpu.runtime.telemetry import family

        s = self.stats()
        return [
            family("dftpu_store_staged_bytes", "gauge",
                   "Live owned bytes staged in the table store "
                   "(shared buffers counted once).",
                   [({}, s["nbytes"])]),
            family("dftpu_store_entries", "gauge",
                   "Staged entries (owners + views/aliases).",
                   [({}, s["entries"])]),
            family("dftpu_store_views", "gauge",
                   "Zero-copy view/alias entries sharing an owner's "
                   "buffers.", [({}, s["views"])]),
            family("dftpu_store_peak_bytes", "gauge",
                   "High-water mark of owned staged bytes.",
                   [({}, s["peak_nbytes"])]),
            family("dftpu_store_puts", "counter",
                   "Entries ever staged.", [({}, s["puts"])]),
            family("dftpu_store_dedup_hits", "counter",
                   "Identity-dedup hits (zero-byte aliases).",
                   [({}, s["dedup_hits"])]),
            family("dftpu_store_budget_bytes", "gauge",
                   "Enforced worker memory budget (0 = unlimited).",
                   [({}, s["budget_bytes"])]),
            family("dftpu_store_spilled_bytes", "gauge",
                   "Live staged bytes resident in the host spill "
                   "segment instead of memory.",
                   [({}, s["spilled_nbytes"])]),
            family("dftpu_store_spills", "counter",
                   "Entries ever spilled to the host segment.",
                   [({}, s["spills"])]),
            family("dftpu_store_refaults", "counter",
                   "Spilled entries refaulted back on get().",
                   [({}, s["refaults"])]),
            family("dftpu_store_spill_files", "gauge",
                   "Spill files currently on disk (0 once drained — "
                   "the zero-leak gate's file half).",
                   [({}, s["spill_files"])]),
        ]


def collect_table_ids(plan_obj: dict) -> list[str]:
    """All shipment-store ids referenced by an encoded plan."""
    out: list[str] = []

    def walk(o):
        if isinstance(o, dict):
            if o.get("t") == "memscan":
                out.extend(o["tables"])
            for v in o.values():
                walk(v)
        elif isinstance(o, list):
            for v in o:
                walk(v)

    walk(plan_obj)
    return out


# ---------------------------------------------------------------------------
# schema / expressions
# ---------------------------------------------------------------------------


def encode_schema(s: Schema) -> list:
    return [[f.name, f.dtype.value, f.nullable] for f in s.fields]


def decode_schema(obj) -> Schema:
    return Schema([Field(n, DataType(d), bool(nl)) for n, d, nl in obj])


def encode_expr(e: pe.PhysicalExpr) -> dict:
    if isinstance(e, pe.Col):
        return {"t": "col", "name": e.name}
    if isinstance(e, pe.Literal):
        v = e.value
        return {"t": "lit", "value": v, "dtype": e.dtype.value}
    if isinstance(e, pe.BinaryOp):
        return {"t": "bin", "op": e.op, "l": encode_expr(e.left),
                "r": encode_expr(e.right)}
    if isinstance(e, pe.BooleanOp):
        return {"t": "bool", "op": e.op, "l": encode_expr(e.left),
                "r": encode_expr(e.right)}
    if isinstance(e, pe.Not):
        return {"t": "not", "c": encode_expr(e.child)}
    if isinstance(e, pe.IsNull):
        return {"t": "isnull", "c": encode_expr(e.child), "neg": e.negated}
    if isinstance(e, pe.Cast):
        return {"t": "cast", "c": encode_expr(e.child), "to": e.to.value}
    if isinstance(e, pe.Like):
        return {"t": "like", "c": encode_expr(e.child), "p": e.pattern,
                "neg": e.negated}
    if isinstance(e, pe.InList):
        return {"t": "inlist", "c": encode_expr(e.child),
                "values": list(e.values), "neg": e.negated}
    if isinstance(e, pe.Case):
        return {
            "t": "case",
            "branches": [[encode_expr(c), encode_expr(v)] for c, v in e.branches],
            "else": encode_expr(e.otherwise) if e.otherwise else None,
        }
    if isinstance(e, pe.Alias):
        return {"t": "alias", "c": encode_expr(e.child), "name": e.name}
    if isinstance(e, pe.Negate):
        return {"t": "neg", "c": encode_expr(e.child)}
    if isinstance(e, pe.Extract):
        return {"t": "extract", "part": e.part, "c": encode_expr(e.child)}
    if isinstance(e, pe.Substring):
        return {"t": "substr", "c": encode_expr(e.child), "start": e.start,
                "length": e.length}
    if isinstance(e, pe.Coalesce):
        return {"t": "coalesce", "args": [encode_expr(a) for a in e.args]}
    if isinstance(e, pe.Abs):
        return {"t": "abs", "c": encode_expr(e.child)}
    if isinstance(e, pe.Round):
        return {"t": "round", "c": encode_expr(e.child), "digits": e.digits}
    if isinstance(e, pe.StringCase):
        return {"t": "strcase", "c": encode_expr(e.child), "upper": e.upper}
    if isinstance(e, pe.ConcatStrings):
        return {"t": "concat", "args": [encode_expr(a) for a in e.args]}
    if isinstance(e, pe.DateTrunc):
        return {"t": "datetrunc", "unit": e.unit, "c": encode_expr(e.child)}
    if isinstance(e, pe.StrLength):
        return {"t": "strlen", "c": encode_expr(e.child)}
    if isinstance(e, pe.RegexpReplace):
        return {"t": "regexp_replace", "c": encode_expr(e.child),
                "p": e.pattern, "r": e.replacement}
    # a resolved scalar subquery is a constant by the time plans ship
    from datafusion_distributed_tpu.sql.logical import ScalarSubqueryExpr

    if isinstance(e, ScalarSubqueryExpr) and getattr(e, "resolved", None):
        value, dtype = e.resolved
        return {"t": "lit", "value": value, "dtype": dtype.value}
    raise CodecError(f"cannot encode expression {type(e).__name__}")


def decode_expr(o: dict) -> pe.PhysicalExpr:
    t = o["t"]
    if t == "col":
        return pe.Col(o["name"])
    if t == "lit":
        return pe.Literal(o["value"], DataType(o["dtype"]))
    if t == "bin":
        return pe.BinaryOp(o["op"], decode_expr(o["l"]), decode_expr(o["r"]))
    if t == "bool":
        return pe.BooleanOp(o["op"], decode_expr(o["l"]), decode_expr(o["r"]))
    if t == "not":
        return pe.Not(decode_expr(o["c"]))
    if t == "isnull":
        return pe.IsNull(decode_expr(o["c"]), o["neg"])
    if t == "cast":
        return pe.Cast(decode_expr(o["c"]), DataType(o["to"]))
    if t == "like":
        return pe.Like(decode_expr(o["c"]), o["p"], o["neg"])
    if t == "inlist":
        return pe.InList(decode_expr(o["c"]), tuple(o["values"]), o["neg"])
    if t == "case":
        branches = tuple(
            (decode_expr(c), decode_expr(v)) for c, v in o["branches"]
        )
        otherwise = decode_expr(o["else"]) if o["else"] else None
        return pe.Case(branches, otherwise)
    if t == "alias":
        return pe.Alias(decode_expr(o["c"]), o["name"])
    if t == "neg":
        return pe.Negate(decode_expr(o["c"]))
    if t == "extract":
        return pe.Extract(o["part"], decode_expr(o["c"]))
    if t == "substr":
        return pe.Substring(decode_expr(o["c"]), o["start"], o["length"])
    if t == "coalesce":
        return pe.Coalesce(tuple(decode_expr(a) for a in o["args"]))
    if t == "abs":
        return pe.Abs(decode_expr(o["c"]))
    if t == "round":
        return pe.Round(decode_expr(o["c"]), o["digits"])
    if t == "strcase":
        return pe.StringCase(decode_expr(o["c"]), o["upper"])
    if t == "concat":
        return pe.ConcatStrings(tuple(decode_expr(a) for a in o["args"]))
    if t == "datetrunc":
        return pe.DateTrunc(o["unit"], decode_expr(o["c"]))
    if t == "strlen":
        return pe.StrLength(decode_expr(o["c"]))
    if t == "regexp_replace":
        return pe.RegexpReplace(decode_expr(o["c"]), o["p"], o["r"])
    raise CodecError(f"cannot decode expression kind {t!r}")


# ---------------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------------


def encode_plan(p: ExecutionPlan, store: TableStore) -> dict:
    """Encode ``p``, stamping its structural fingerprint (plan/fingerprint)
    into the wire object under ``"_fp"``. Decoders ignore the key; workers
    compare it against the DECODED plan's fingerprint (runtime/worker.py
    post-decode check, diagnostic DFTPU043) so a miscoded/corrupted plan
    becomes a classified fatal error instead of wrong results.

    ``DFTPU_VERIFY_CODEC=1`` additionally round-trips the encoding through
    decode_plan right here and fails fast (DFTPU044) on fingerprint drift —
    the debug-mode assertion for codec changes."""
    from datafusion_distributed_tpu.plan.fingerprint import prepare_plan

    obj = _encode_plan_node(p, store)
    fp = prepare_plan(p).fingerprint
    if fp is not None:
        obj["_fp"] = fp
        import os

        if os.environ.get("DFTPU_VERIFY_CODEC") == "1":
            _verify_codec_roundtrip(p, obj, store, fp)
    return obj


def _verify_codec_roundtrip(p: ExecutionPlan, obj: dict, store: TableStore,
                            fp: str) -> None:
    from datafusion_distributed_tpu.plan.fingerprint import prepare_plan
    from datafusion_distributed_tpu.runtime.errors import PlanIntegrityError

    decoded = decode_plan(obj, store)
    got = prepare_plan(decoded).fingerprint
    if got != fp:
        raise PlanIntegrityError(
            f"DFTPU044: codec round-trip fingerprint drift for "
            f"{type(p).__name__}: encoded plan fingerprints as {fp}, "
            f"decode(encode(plan)) as {got} — the codec dropped or "
            "reordered structural state (DFTPU_VERIFY_CODEC=1)"
        )


def _encode_plan_node(p: ExecutionPlan, store: TableStore) -> dict:
    if isinstance(p, MemoryScanExec):
        return {
            "t": "memscan",
            "tables": [store.put(t) for t in p.tasks],
            "schema": encode_schema(p.schema()),
            "pinned": p.pinned,
            "replicated": p.replicated,
        }
    if isinstance(p, ParquetScanExec):
        return {
            "t": "pqscan",
            "file_groups": p.file_groups,
            "schema": encode_schema(p._schema),
            "capacity": p.capacity,
            "projection": p.projection,
            # shared dictionaries must travel: per-worker rebuilt dictionaries
            # would make codes incomparable across the exchange
            "dictionaries": {
                name: list(d.values)
                for name, d in (p.dictionaries or {}).items()
            } or None,
        }
    if isinstance(p, FilterExec):
        return {"t": "filter", "pred": encode_expr(p.predicate),
                "c": _encode_plan_node(p.child, store)}
    if isinstance(p, ProjectionExec):
        return {
            "t": "project",
            "exprs": [[encode_expr(e), n] for e, n in p.exprs],
            "c": _encode_plan_node(p.child, store),
        }
    if isinstance(p, HashAggregateExec):
        return {
            "t": "agg",
            "mode": p.mode,
            "groups": p.group_names,
            "aggs": [[a.func, a.input_name, a.output_name] for a in p.aggs],
            "slots": p.num_slots,
            "c": _encode_plan_node(p.child, store),
        }
    if isinstance(p, PartialPassthroughExec):
        return {
            "t": "partial_passthrough",
            "groups": p.group_names,
            "aggs": [[a.func, a.input_name, a.output_name] for a in p.aggs],
            "c": _encode_plan_node(p.child, store),
        }
    if isinstance(p, SortExec):
        return {
            "t": "sort",
            "keys": [[k.name, k.ascending, k.nulls_first] for k in p.keys],
            "fetch": p.fetch,
            "c": _encode_plan_node(p.child, store),
        }
    if isinstance(p, LimitExec):
        return {"t": "limit", "fetch": p.fetch, "skip": p.skip,
                "c": _encode_plan_node(p.child, store)}
    if isinstance(p, CoalescePartitionsExec):
        return {"t": "coalesce_parts", "c": _encode_plan_node(p.child, store)}
    if isinstance(p, HashJoinExec):
        return {
            "t": "hashjoin",
            "jt": p.join_type,
            "pk": p.probe_keys,
            "bk": p.build_keys,
            "residual": encode_expr(p.residual) if p.residual else None,
            "out_cap": p.out_capacity,
            "slots": p.num_slots,
            "mark": p.mark_name,
            "null_aware": p.null_aware,
            "probe": _encode_plan_node(p.probe, store),
            "build": _encode_plan_node(p.build, store),
        }
    if isinstance(p, MultiwayHashJoinExec):
        return {
            "t": "mwjoin",
            "steps": [
                {
                    "jt": s.join_type,
                    "pk": list(s.probe_keys),
                    "bk": list(s.build_keys),
                    "residual": (encode_expr(s.residual)
                                 if s.residual else None),
                    "out_cap": s.out_capacity,
                    "slots": s.num_slots,
                    "mark": s.mark_name,
                    "null_aware": s.null_aware,
                }
                for s in p.steps
            ],
            "probe": _encode_plan_node(p.probe, store),
            "builds": [_encode_plan_node(b, store) for b in p.builds],
        }
    if isinstance(p, CrossJoinExec):
        return {"t": "crossjoin", "out_cap": p.out_capacity,
                "l": _encode_plan_node(p.left, store),
                "r": _encode_plan_node(p.right, store)}
    if isinstance(p, UnionExec):
        return {"t": "union",
                "cs": [_encode_plan_node(c, store) for c in p.children()]}
    from datafusion_distributed_tpu.plan.window_exec import WindowExec

    if isinstance(p, WindowExec):
        return {
            "t": "window",
            "funcs": [[f.func, f.input_name, f.output_name, f.frame]
                      for f in p.funcs],
            "partitions": p.partition_names,
            "orders": [[k.name, k.ascending, k.nulls_first]
                       for k in p.order_keys],
            "fields": encode_schema(Schema(p.out_fields)),
            "c": _encode_plan_node(p.child, store),
        }
    from datafusion_distributed_tpu.plan.exchanges import (
        RangeShuffleExchangeExec,
    )

    # exchange boundary state: producer_tasks and consumer_fetch are
    # STRUCTURAL (they enter output_capacity and the plan fingerprint) —
    # dropping them on the wire re-shaped decoded plans silently until the
    # DFTPU043/044 integrity checks made the loss a hard error
    if isinstance(p, RangeShuffleExchangeExec):
        return {
            "t": "range_shuffle",
            "keys": [[k.name, k.ascending, k.nulls_first]
                     for k in p.sort_keys],
            "tasks": p.num_tasks, "per_dest": p.per_dest_capacity,
            "stage": p.stage_id, "prod": p.producer_tasks,
            "cfetch": p.consumer_fetch,
            "c": _encode_plan_node(p.child, store),
        }
    if isinstance(p, ShuffleExchangeExec):
        return {"t": "shuffle", "keys": p.key_names, "tasks": p.num_tasks,
                "per_dest": p.per_dest_capacity, "stage": p.stage_id,
                "prod": p.producer_tasks, "cfetch": p.consumer_fetch,
                "c": _encode_plan_node(p.child, store)}
    if isinstance(p, CoalesceExchangeExec):
        return {"t": "coalesce_ex", "tasks": p.num_tasks, "stage": p.stage_id,
                "consumers": p.num_consumers,
                "prod": p.producer_tasks, "cfetch": p.consumer_fetch,
                "c": _encode_plan_node(p.child, store)}
    if isinstance(p, BroadcastExchangeExec):
        return {"t": "broadcast_ex", "tasks": p.num_tasks, "stage": p.stage_id,
                "prod": p.producer_tasks, "cfetch": p.consumer_fetch,
                "c": _encode_plan_node(p.child, store)}
    if isinstance(p, PartitionReplicatedExec):
        return {"t": "partrep", "tasks": p.num_tasks, "stage": p.stage_id,
                "prod": p.producer_tasks, "cfetch": p.consumer_fetch,
                "c": _encode_plan_node(p.child, store)}
    if isinstance(p, IsolatedArmExec):
        return {"t": "isoarm", "task": p.assigned_task,
                "c": _encode_plan_node(p.child, store)}
    from datafusion_distributed_tpu.runtime.peer import PeerShuffleScanExec

    if isinstance(p, PeerShuffleScanExec):
        return {
            "t": "peerscan",
            "pulls": [
                [[list(key), url, lo, hi] for key, url, lo, hi in specs]
                for specs in p.pulls_per_task
            ],
            "keys": p.key_names,
            "parts": p.num_partitions,
            "per_dest": p.per_dest_capacity,
            "schema": encode_schema(p._schema),
            "dictionaries": {
                name: list(d.values)
                for name, d in (p.dictionaries or {}).items()
            } or None,
            "replicated": p.replicated,
            "pinned_task": p.pinned_task,
            "pull_all": p.pull_all,
            "budget": p.budget_bytes,
            "chunk_rows": p.chunk_rows,
            "cap_hint": p.capacity_hint,
        }
    kind = getattr(p, "codec_kind", None)
    if kind and kind in _USER_CODECS:
        enc, _ = _USER_CODECS[kind]
        return {"t": f"user:{kind}", "body": enc(p, store)}
    raise CodecError(f"cannot encode plan node {type(p).__name__}")


def _restore_exchange_state(n, o: dict):
    n.stage_id = o["stage"]
    n.producer_tasks = o.get("prod")
    n.consumer_fetch = o.get("cfetch")
    return n


def decode_plan(o: dict, store: TableStore) -> ExecutionPlan:
    t = o["t"]
    if t == "memscan":
        tables = [store.get(tid) for tid in o["tables"]]
        return MemoryScanExec(tables, decode_schema(o["schema"]),
                              pinned=o.get("pinned", False),
                              replicated=o.get("replicated", False))
    if t == "pqscan":
        from datafusion_distributed_tpu.ops.table import Dictionary
        import numpy as np

        dicts = None
        if o.get("dictionaries"):
            dicts = {
                name: Dictionary(np.asarray(vals, dtype=object))
                for name, vals in o["dictionaries"].items()
            }
        return ParquetScanExec(
            o["file_groups"], decode_schema(o["schema"]), o["capacity"],
            o["projection"], dicts,
        )
    if t == "filter":
        return FilterExec(decode_expr(o["pred"]), decode_plan(o["c"], store))
    if t == "project":
        return ProjectionExec(
            [(decode_expr(e), n) for e, n in o["exprs"]],
            decode_plan(o["c"], store),
        )
    if t == "agg":
        return HashAggregateExec(
            o["mode"], o["groups"],
            [AggSpec(f, i, n) for f, i, n in o["aggs"]],
            decode_plan(o["c"], store), o["slots"],
        )
    if t == "partial_passthrough":
        return PartialPassthroughExec(
            o["groups"],
            [AggSpec(f, i, n) for f, i, n in o["aggs"]],
            decode_plan(o["c"], store),
        )
    if t == "sort":
        return SortExec(
            [SortKey(n, a, nf) for n, a, nf in o["keys"]],
            decode_plan(o["c"], store), o["fetch"],
        )
    if t == "limit":
        return LimitExec(decode_plan(o["c"], store), o["fetch"], o["skip"])
    if t == "coalesce_parts":
        return CoalescePartitionsExec(decode_plan(o["c"], store))
    if t == "hashjoin":
        return HashJoinExec(
            decode_plan(o["probe"], store), decode_plan(o["build"], store),
            o["pk"], o["bk"], o["jt"],
            residual=decode_expr(o["residual"]) if o["residual"] else None,
            out_capacity=o["out_cap"], num_slots=o["slots"],
            mark_name=o["mark"], null_aware=o["null_aware"],
        )
    if t == "mwjoin":
        return MultiwayHashJoinExec(
            decode_plan(o["probe"], store),
            [decode_plan(b, store) for b in o["builds"]],
            [
                MultiwayJoinStep(
                    probe_keys=tuple(s["pk"]), build_keys=tuple(s["bk"]),
                    join_type=s["jt"], out_capacity=s["out_cap"],
                    num_slots=s["slots"],
                    residual=(decode_expr(s["residual"])
                              if s["residual"] else None),
                    mark_name=s["mark"], null_aware=s["null_aware"],
                )
                for s in o["steps"]
            ],
        )
    if t == "crossjoin":
        return CrossJoinExec(decode_plan(o["l"], store),
                             decode_plan(o["r"], store), o["out_cap"])
    if t == "union":
        return UnionExec([decode_plan(c, store) for c in o["cs"]])
    if t == "window":
        from datafusion_distributed_tpu.ops.window import WindowFunc
        from datafusion_distributed_tpu.plan.window_exec import WindowExec

        return WindowExec(
            decode_plan(o["c"], store),
            [WindowFunc(*args) for args in o["funcs"]],
            o["partitions"],
            [SortKey(n, a, nf) for n, a, nf in o["orders"]],
            list(decode_schema(o["fields"]).fields),
        )
    if t == "range_shuffle":
        from datafusion_distributed_tpu.plan.exchanges import (
            RangeShuffleExchangeExec,
        )

        n = RangeShuffleExchangeExec(
            decode_plan(o["c"], store),
            [SortKey(nm, a, nf) for nm, a, nf in o["keys"]],
            o["tasks"], o["per_dest"],
        )
        return _restore_exchange_state(n, o)
    if t == "shuffle":
        n = ShuffleExchangeExec(decode_plan(o["c"], store), o["keys"],
                                o["tasks"], o["per_dest"])
        return _restore_exchange_state(n, o)
    if t == "coalesce_ex":
        n = CoalesceExchangeExec(decode_plan(o["c"], store), o["tasks"],
                                 o.get("consumers", 1))
        return _restore_exchange_state(n, o)
    if t == "broadcast_ex":
        n = BroadcastExchangeExec(decode_plan(o["c"], store), o["tasks"])
        return _restore_exchange_state(n, o)
    if t == "partrep":
        n = PartitionReplicatedExec(decode_plan(o["c"], store), o["tasks"])
        return _restore_exchange_state(n, o)
    if t == "isoarm":
        return IsolatedArmExec(decode_plan(o["c"], store), o["task"])
    if t == "peerscan":
        from datafusion_distributed_tpu.ops.table import Dictionary
        from datafusion_distributed_tpu.runtime.peer import (
            PeerShuffleScanExec,
        )
        import numpy as np

        dicts = None
        if o.get("dictionaries"):
            dicts = {
                name: Dictionary(np.asarray(vals, dtype=object))
                for name, vals in o["dictionaries"].items()
            }
        return PeerShuffleScanExec(
            [
                [(tuple(key), url, lo, hi) for key, url, lo, hi in specs]
                for specs in o["pulls"]
            ],
            o["keys"], o["parts"], o["per_dest"],
            decode_schema(o["schema"]), dicts,
            replicated=o.get("replicated", False),
            pinned_task=o.get("pinned_task"),
            pull_all=o.get("pull_all", False),
            budget_bytes=o.get("budget", 64 << 20),
            chunk_rows=o.get("chunk_rows", 65536),
            capacity_hint=o.get("cap_hint", 0),
        )
    if t.startswith("user:"):
        kind = t[5:]
        if kind not in _USER_CODECS:
            raise CodecError(f"no codec registered for {kind!r}")
        _, dec = _USER_CODECS[kind]
        return dec(o["body"], store)
    raise CodecError(f"cannot decode plan kind {t!r}")


# ---------------------------------------------------------------------------
# table transport (cross-host payloads)
# ---------------------------------------------------------------------------


def encode_table(table: Table) -> memoryview:
    """Table -> Arrow IPC payload (the Flight data-plane analogue):
    dictionary-GC'd string columns + logical-dtype metadata (the wire shape
    of io/parquet.table_to_arrow). Writes through `pa.BufferOutputStream`
    and returns a memoryview over the resulting Arrow buffer — the old
    `BytesIO` + `getvalue()` shape duplicated the whole payload at peak
    (one copy in the stream, a second in getvalue). Consumers (transport
    framing, compression, len) all speak the buffer protocol."""
    import pyarrow as pa

    from datafusion_distributed_tpu.io.parquet import table_to_arrow

    arrow = table_to_arrow(table, dictionary_gc=True,
                           logical_metadata=True)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, arrow.schema) as w:
        w.write_table(arrow)
    # getvalue() on a BufferOutputStream is zero-copy (an Arrow buffer);
    # the memoryview keeps it alive and exposes the buffer protocol
    return memoryview(sink.getvalue())


def decode_table(data, capacity: Optional[int] = None) -> Table:
    """Arrow IPC payload -> Table. Reads through `pa.BufferReader` (no
    BytesIO staging copy); ``capacity`` passes through to the column build,
    where a buffer that already satisfies it skips the zero-fill + pad copy
    (Column.from_numpy fast path)."""
    import pyarrow as pa

    from datafusion_distributed_tpu.io.parquet import arrow_to_table

    with pa.ipc.open_stream(pa.BufferReader(data)) as r:
        arrow = r.read_all()
    return arrow_to_table(arrow, capacity=capacity)


# ---------------------------------------------------------------------------
# adaptive per-column wire compression (remote hops only)
# ---------------------------------------------------------------------------

#: rows sampled per column when choosing its wire codec — enough to see
#: repetition without paying a full-column unique() on wide exchanges
WIRE_SAMPLE_ROWS = 512
#: payloads under this ship as one plainly-compressed blob: per-column
#: IPC framing has fixed schema overhead that only pays on real payloads
ADAPTIVE_MIN_BYTES = 1 << 12


def choose_column_codec(column, available) -> str:
    """Wire codec for ONE arrow column from sampled statistics — the
    adaptive half of the remote data plane. Dictionary/string columns
    are dominated by repeated values and codes: the strongest available
    codec (zstd) wins. Repetitive columns (sampled unique ratio <= 0.5)
    prefer the cheapest negotiated codec (lz4 beats zstd on speed when
    both ends speak it). High-entropy floats ship raw — compressing
    random mantissas burns CPU to save nothing. ``available`` is the
    NEGOTIATED codec set (both endpoints), not this process's."""
    import pyarrow as pa

    avail = set(available or ())

    def best(*prefs: str) -> str:
        for p in prefs:
            if p in avail:
                return p
        return "none"

    t = column.type
    if pa.types.is_dictionary(t) or pa.types.is_string(t) or (
        pa.types.is_large_string(t)
    ):
        return best("zstd", "lz4")
    sample = column.slice(0, min(len(column), WIRE_SAMPLE_ROWS))
    try:
        ratio = len(sample.unique()) / max(len(sample), 1)
    except pa.ArrowInvalid:
        ratio = 1.0
    if ratio <= 0.5:
        return best("lz4", "zstd")
    if pa.types.is_floating(t):
        return "none"
    return best("zstd", "lz4")


def encode_table_adaptive(table: Table, available) -> tuple[dict, dict]:
    """Table -> per-column Arrow IPC blobs with per-column codec picks;
    -> (blobs {"c<i>": payload}, codecs {"c<i>": codec}). Each column is
    its own single-column IPC stream so the transport's per-blob
    ``comp`` framing (self-describing) carries a MIXED-codec frame; the
    decoder reassembles the columns into one table. Returns ({}, {})
    for a zero-column table — callers fall back to `encode_table`."""
    import pyarrow as pa

    from datafusion_distributed_tpu.io.parquet import table_to_arrow

    arrow = table_to_arrow(table, dictionary_gc=True,
                           logical_metadata=True)
    blobs: dict = {}
    codecs: dict = {}
    for i in range(arrow.num_columns):
        single = arrow.select([i])
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, single.schema) as w:
            w.write_table(single)
        name = f"c{i}"
        blobs[name] = memoryview(sink.getvalue())
        codecs[name] = choose_column_codec(arrow.column(i), available)
    return blobs, codecs


def decode_table_adaptive(blobs: dict, num_cols: int,
                          capacity: Optional[int] = None) -> Table:
    """Reassemble `encode_table_adaptive` blobs into one Table: the
    single-column arrow tables are re-joined and decoded through the
    SAME `arrow_to_table` call as the single-blob path, so both wire
    shapes build byte-identical tables."""
    import pyarrow as pa

    from datafusion_distributed_tpu.io.parquet import arrow_to_table

    if num_cols <= 0:
        raise CodecError("adaptive frame with zero columns")
    parts = []
    for i in range(num_cols):
        with pa.ipc.open_stream(pa.BufferReader(blobs[f"c{i}"])) as r:
            parts.append(r.read_all())
    arrow = parts[0]
    for t in parts[1:]:
        arrow = arrow.append_column(t.schema.field(0), t.column(0))
    return arrow_to_table(arrow, capacity=capacity)
