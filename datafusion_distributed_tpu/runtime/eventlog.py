"""Structured JSON event logging, correlated with traces and metrics.

Before this module the fault paths were asymmetric: every transition
(task_retry, worker_quarantined, hedge_won, checkpoint_saved, ...)
emitted a TRACE event — visible only when `SET distributed.tracing` was
on and only inside that query's bounded trace — and nothing else. The
event log is the always-on half: one `log_event(kind, **fields)` path
carrying the SAME query/stage/task ids as the PR 7 trace spans, so logs,
metrics, and traces correlate on the same ids (find a `task_retry` in
the log, open the query id's trace, read the matching event + the
`dftpu_faults` counter it also bumped).

- Ring-buffered (bounded — a long-lived serving process keeps the last
  ``capacity`` events, with a dropped counter), thread-safe.
- ``DFTPU_EVENT_LOG=path``: every event is ALSO appended to ``path`` as
  one JSON line at log time (operator tailing / post-mortem). `dump()`
  writes the current ring on demand.
- Host-side only: no event-log call may run inside a jax-traced
  function (tools/check_tracer_safety.py rule DFTPU110) and nothing
  here enters a compile-cache key.

Event schema (README "Telemetry"): ``{"ts": unix_seconds, "seq": n,
"kind": str, "query_id"/"stage"/"task"/"worker": optional ids,
...kind-specific fields}`` — every value must be JSON-serializable
(non-serializable values are repr()'d rather than failing the caller).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class EventLog:
    """Bounded structured event ring with an optional JSONL sink."""

    def __init__(self, capacity: int = 4096,
                 path: Optional[str] = None):
        if capacity < 1:
            raise ValueError("event-log capacity must be >= 1")
        self.capacity = int(capacity)
        # sink resolution is per-log-call (env read at call time would
        # cost a getenv per event; the default log resolves it lazily
        # instead — see default_event_log)
        self.path = path
        self._lock = threading.Lock()
        self._ring: list = []  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock
        #: MONOTONIC per-kind totals (never decremented by ring
        #: eviction) — the counter-typed exposition must not go down or
        #: scrapers read every eviction as a counter reset
        self._kind_counts: dict = {}  # guarded-by: _lock
        self._sink = None  # guarded-by: _lock  (lazily opened file)
        self._sink_failed = False  # guarded-by: _lock

    def log(self, kind: str, **fields) -> dict:
        """Record one event; -> the event dict (already stamped). The
        id fields (`query_id`, `stage`, `task`, `worker`) are plain
        kwargs — callers pass whichever apply, matching the trace-event
        attribute names so the two streams join on them."""
        event = {"ts": time.time(), "kind": str(kind)}
        for k, v in fields.items():
            if v is None:
                continue
            try:
                json.dumps(v)
                event[k] = v
            except (TypeError, ValueError):
                event[k] = repr(v)
        line = None
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._kind_counts[event["kind"]] = (
                self._kind_counts.get(event["kind"], 0) + 1
            )
            self._ring.append(event)
            while len(self._ring) > self.capacity:
                self._ring.pop(0)
                self._dropped += 1
            if self.path and not self._sink_failed:
                try:
                    if self._sink is None:
                        self._sink = open(self.path, "a",
                                          encoding="utf-8")
                    line = self._sink
                except OSError:
                    self._sink_failed = True  # never poison callers
        if line is not None:
            try:
                # the file object's write/flush are thread-safe enough
                # for whole-line appends; a torn tail only costs the
                # reader one line (bench.py's event reader tolerates it)
                line.write(json.dumps(event) + "\n")
                line.flush()
            except (OSError, ValueError):
                with self._lock:
                    self._sink_failed = True
        return event

    def events(self, kind: Optional[str] = None,
               query_id: Optional[str] = None) -> list:
        """Snapshot copy of the ring, optionally filtered."""
        with self._lock:
            ring = list(self._ring)
        return [
            e for e in ring
            if (kind is None or e["kind"] == kind)
            and (query_id is None or e.get("query_id") == query_id)
        ]

    def stats(self) -> dict:
        with self._lock:
            return {
                "events": len(self._ring),
                "total": self._seq,
                "dropped": self._dropped,
                "sink": self.path if not self._sink_failed else None,
            }

    def telemetry_families(self) -> list:
        """Registry adapter (runtime/telemetry.py): per-kind event
        counters + the drop counter."""
        from datafusion_distributed_tpu.runtime.telemetry import family

        with self._lock:
            by_kind = dict(self._kind_counts)
            dropped, total = self._dropped, self._seq
        return [
            family("dftpu_events", "counter",
                   "Structured events ever logged, by kind.",
                   [({"kind": k}, v) for k, v in sorted(by_kind.items())]),
            family("dftpu_events_logged", "counter",
                   "Structured events ever logged.", [({}, total)]),
            family("dftpu_events_dropped", "counter",
                   "Events evicted from the bounded ring.",
                   [({}, dropped)]),
        ]

    def dump(self, path: Optional[str] = None) -> int:
        """Write the retained ring as JSON lines; -> events written."""
        target = path or self.path
        if not target:
            raise ValueError("no dump path (arg or DFTPU_EVENT_LOG)")
        events = self.events()
        with open(target, "w", encoding="utf-8") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return len(events)

    def clear(self) -> None:
        with self._lock:
            self._ring = []
            self._dropped = 0

    def close(self) -> None:
        with self._lock:
            sink, self._sink = self._sink, None
        if sink is not None:
            try:
                sink.close()
            except OSError:
                pass


_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[EventLog] = None  # guarded-by: _DEFAULT_LOCK


def default_event_log() -> EventLog:
    """The process-wide event log (lazily built so DFTPU_EVENT_LOG is
    read once, at first use)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = EventLog(
                capacity=int(os.environ.get("DFTPU_EVENT_LOG_CAP",
                                            "4096")),
                path=os.environ.get("DFTPU_EVENT_LOG") or None,
            )
        return _DEFAULT


def log_event(kind: str, **fields) -> dict:
    """Module-level convenience over the process-wide log."""
    return default_event_log().log(kind, **fields)
