"""Closed-loop runtime adaptivity: react mid-query when reality diverges
from the planner's estimate.

The planner predicts (sampled NDV -> predicted exchange bytes) and the
runtime measures (per-stage rows/bytes spans, predicted-vs-measured
counters); this module holds the shared policy for the three decision
points that *react*:

- **skew-aware shuffle splitting** — when one materialized partition
  exceeds ``skew_split_factor`` x the median, the coordinator splits the
  hot task into contiguous row-range views so sibling workers share the
  hot key's rows (grounding: *Chasing Similarity*'s distribution-aware
  placement). Contiguous sub-ranges preserve the producer-major,
  within-producer-stable row order of ``_shuffle_regroup``, so results
  stay byte-identical.
- **self-correcting partial aggregation** — the pushed-down partial
  operator is probed on its first task; when the measured reduction
  ratio exceeds ``partial_agg_bailout_ratio`` (i.e. the sampled-NDV
  prediction was wrong and the partial barely reduces), remaining tasks
  swap the partial for a per-row passthrough that emits identical
  partial-state columns (grounding: *Partial Partial Aggregates*'
  adaptive bail-out).
- **mid-query replanning** — when a completed stage's measured output
  cardinality diverges from ``StageDagNode.est_rows`` by
  ``replan_cardinality_factor``, the coordinator re-costs the
  not-yet-dispatched downstream stages and re-orders the ready backlog
  by corrected bytes (scheduling only — plan structure, and therefore
  bytes, are untouched), re-verifying affected exchanges first.

Everything here runs on the coordinator host after stage outputs
materialize — never inside traced code — and none of the knobs are
trace-relevant (see runtime/worker.py TRACE_RELEVANT_CONFIG_KEYS), so
toggling them compiles nothing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from datafusion_distributed_tpu.runtime.eventlog import log_event
from datafusion_distributed_tpu.runtime.telemetry import DEFAULT_REGISTRY

__all__ = [
    "AdaptivitySettings",
    "SkewReport",
    "detect_skew",
    "split_ranges",
    "note_skew_split",
    "note_partial_agg_bailout",
    "note_replan",
    "note_multiway_fusion",
    "note_multiway_bailout",
    "note_global_agg_selected",
]


@dataclass(frozen=True)
class AdaptivitySettings:
    """Runtime-adaptivity knobs, parsed from coordinator config options
    (set via ``SET skew_split_factor = ...`` etc.). A value of 0 disables
    that adaptation path; defaults keep every path armed but inert on
    small inputs (``skew_split_min_rows`` floors the split trigger so
    unit-test-sized partitions never split)."""

    skew_split_factor: float = 4.0
    skew_split_min_rows: int = 1024
    partial_agg_bailout_ratio: float = 0.95
    replan_cardinality_factor: float = 8.0

    @classmethod
    def from_options(cls, options) -> "AdaptivitySettings":
        def _num(key, default, cast):
            try:
                v = cast(options.get(key, default))
            except (TypeError, ValueError):
                return default
            return v if v >= 0 else default

        options = options or {}
        return cls(
            skew_split_factor=_num("skew_split_factor", 4.0, float),
            skew_split_min_rows=_num("skew_split_min_rows", 1024, int),
            partial_agg_bailout_ratio=_num(
                "partial_agg_bailout_ratio", 0.95, float
            ),
            replan_cardinality_factor=_num(
                "replan_cardinality_factor", 8.0, float
            ),
        )

    @property
    def skew_enabled(self) -> bool:
        return self.skew_split_factor > 0

    @property
    def bailout_enabled(self) -> bool:
        return self.partial_agg_bailout_ratio > 0

    @property
    def replan_enabled(self) -> bool:
        return self.replan_cardinality_factor > 0


@dataclass(frozen=True)
class SkewReport:
    """One hot partition: ``rows`` is ``ratio`` x the median."""

    partition: int
    rows: int
    median: float
    ratio: float


def detect_skew(
    counts: Sequence[int], factor: float, min_rows: int
) -> Optional[SkewReport]:
    """The single hottest partition iff it exceeds ``factor`` x the
    median row count AND carries at least ``min_rows`` rows. One report
    per call: splitting the hottest task first is the biggest win, and
    the next dispatch re-detects if a second partition still qualifies."""
    if factor <= 0 or len(counts) < 2:
        return None
    ordered = sorted(int(c) for c in counts)
    mid = len(ordered) // 2
    median = (
        float(ordered[mid])
        if len(ordered) % 2
        else (ordered[mid - 1] + ordered[mid]) / 2.0
    )
    hot = max(range(len(counts)), key=lambda i: int(counts[i]))
    rows = int(counts[hot])
    if rows < max(int(min_rows), 1):
        return None
    # an all-hot input (median ~ max) is load, not skew
    if median > 0 and rows / median < factor:
        return None
    if median <= 0 and rows < max(int(min_rows), 1):
        return None
    return SkewReport(
        partition=hot,
        rows=rows,
        median=median,
        ratio=rows / median if median > 0 else float("inf"),
    )


def split_ranges(rows: int, parts: int) -> list:
    """``parts`` contiguous ``(start, count)`` ranges covering
    ``[0, rows)``, each non-empty, remainder spread over the leading
    ranges. Contiguity is what keeps the split byte-identical: the
    concatenation of the sub-ranges IS the original task's row order."""
    parts = max(1, min(int(parts), max(int(rows), 1)))
    base, extra = divmod(int(rows), parts)
    out, start = [], 0
    for i in range(parts):
        count = base + (1 if i < extra else 0)
        out.append((start, count))
        start += count
    return out


def _count(name: str, help_text: str, amount: int = 1) -> None:
    # telemetry must never fail a query: swallow registry clashes the
    # same way runtime/coordinator.py does for its exchange counters
    try:
        DEFAULT_REGISTRY.counter(name, help_text).inc(amount)
    except Exception:
        pass


# eager family registration: scrapes and the telemetry goldens see the
# three adaptivity counters at 0 before any adaptation ever fires (the
# note_* helpers then inc the same families)
_count("dftpu_skew_splits",
       "hot shuffle partitions split into row-range sub-tasks", 0)
_count("dftpu_partial_agg_bailouts",
       "pushed-down partial aggregations bailed out to passthrough", 0)
_count("dftpu_replans",
       "mid-query re-cost/re-order passes over undispatched stages", 0)
_count("dftpu_joins_fused",
       "binary hash joins fused into multiway join stages", 0)
_count("dftpu_exchanges_deleted",
       "shuffle exchanges deleted by multiway join fusion", 0)
_count("dftpu_global_agg_selected",
       "aggregations planned as one global hash table (high NDV)", 0)
_count("dftpu_multiway_bailouts",
       "fused multiway joins bailed back to their binary chains", 0)


def note_skew_split(
    query_id, stage_id, partition: int, rows: int, subtasks: int,
    median: float,
) -> None:
    _count("dftpu_skew_splits",
           "hot shuffle partitions split into row-range sub-tasks")
    try:
        log_event(
            "skew_split",
            query_id=query_id,
            stage_id=int(stage_id),
            partition=int(partition),
            rows=int(rows),
            subtasks=int(subtasks),
            median_rows=float(median),
        )
    except Exception:
        pass


def note_partial_agg_bailout(
    query_id, stage_id, rows_in: int, rows_out: int, ratio: float,
    predicted_rows: int,
) -> None:
    _count("dftpu_partial_agg_bailouts",
           "pushed-down partial aggregations bailed out to passthrough")
    try:
        log_event(
            "partial_agg_bailout",
            query_id=query_id,
            stage_id=int(stage_id),
            rows_in=int(rows_in),
            rows_out=int(rows_out),
            ratio=round(float(ratio), 4),
            predicted_rows=int(predicted_rows),
        )
    except Exception:
        pass


def note_multiway_fusion(joins_fused: int, exchanges_deleted: int) -> None:
    """Planner-side (no query id yet): a fusion pass collapsed
    ``joins_fused`` binary joins into multiway stages and deleted
    ``exchanges_deleted`` intermediate shuffles."""
    _count("dftpu_joins_fused",
           "binary hash joins fused into multiway join stages",
           int(joins_fused))
    _count("dftpu_exchanges_deleted",
           "shuffle exchanges deleted by multiway join fusion",
           int(exchanges_deleted))
    try:
        log_event(
            "multiway_fusion",
            joins_fused=int(joins_fused),
            exchanges_deleted=int(exchanges_deleted),
        )
    except Exception:
        pass


def note_multiway_bailout(
    query_id, steps: int, measured_rows: int, num_slots: int,
) -> None:
    """A fused multiway join was swapped back to its binary chain because
    a measured build side outgrew the captured table sizing."""
    _count("dftpu_multiway_bailouts",
           "fused multiway joins bailed back to their binary chains")
    try:
        log_event(
            "multiway_bailout",
            query_id=query_id,
            steps=int(steps),
            measured_rows=int(measured_rows),
            num_slots=int(num_slots),
        )
    except Exception:
        pass


def note_global_agg_selected() -> None:
    """Planner-side: sampled NDV was high enough that the aggregate was
    planned as one shared global hash table instead of partial+merge."""
    _count("dftpu_global_agg_selected",
           "aggregations planned as one global hash table (high NDV)")
    try:
        log_event("global_agg_selected")
    except Exception:
        pass


def note_replan(
    query_id, stage_id, measured_rows: int, est_rows: int,
    rescaled_stages: int,
) -> None:
    _count("dftpu_replans",
           "mid-query re-cost/re-order passes over undispatched stages")
    try:
        log_event(
            "replan",
            query_id=query_id,
            stage_id=int(stage_id),
            measured_rows=int(measured_rows),
            est_rows=int(est_rows),
            rescaled_stages=int(rescaled_stages),
        )
    except Exception:
        pass
