"""gRPC transport for the worker service (multi-host deployments).

The reference's workers are tonic gRPC services speaking a protobuf contract
(`/root/reference/src/worker/worker.proto`: CoordinatorChannel, ExecuteTask,
GetWorkerInfo) with Arrow Flight framing on the data plane. Here the same
worker object (runtime/worker.py) is exposed over gRPC generic handlers:

    control plane: SetPlan (binary frame: plan JSON header + zstd Arrow-IPC
                   table slices — runtime/transport.py)
    data plane:    ExecuteTask -> server-streamed chunked binary frame;
                   gRPC flow control gives per-stream backpressure, the
                   64 MiB connection budget caps read-ahead, cancellation
                   propagates via stream teardown
    observability: GetInfo / TaskProgress

`GrpcWorkerClient` implements the same duck-typed surface as `Worker`, so
the Coordinator runs unchanged over in-process or remote workers — the
LocalWorkerConnection-vs-RemoteWorkerConnection duality of the reference
(`worker_connection_pool.rs:48-60`). `start_localhost_cluster` is the
`start_localhost_context` test fixture: real sockets, one process.
"""

from __future__ import annotations

import json
import threading
from concurrent import futures
from typing import Optional

from datafusion_distributed_tpu.runtime import transport

from datafusion_distributed_tpu.ops.table import Table
from datafusion_distributed_tpu.runtime.codec import (
    TableStore,
    collect_table_ids,
    decode_table,
    encode_table,
)
from datafusion_distributed_tpu.runtime.errors import (
    TaskTimeoutError,
    TransportError,
    WorkerError,
    WorkerUnavailableError,
    wrap_worker_exception,
)
from datafusion_distributed_tpu.runtime.worker import TaskKey, Worker

_SERVICE = "dftpu.Worker"


def _map_rpc_error(e, url: str, key=None) -> WorkerError:
    """gRPC status -> the retryable/fatal taxonomy (runtime/errors.py):
    DEADLINE_EXCEEDED is a blown deadline, UNAVAILABLE an unreachable or
    crashed endpoint, everything else a transport fault — all retryable, so
    the coordinator reroutes instead of failing the query on a flaky link.
    Errors the SERVER classified ride the E-frame payload, not gRPC status,
    and never reach this mapping."""
    import grpc

    code = e.code() if isinstance(e, grpc.RpcError) else None
    detail = None
    try:
        detail = e.details()
    except Exception:
        pass
    msg = f"rpc {code.name if code else type(e).__name__}: {detail or e}"
    if code == grpc.StatusCode.DEADLINE_EXCEEDED:
        cls = TaskTimeoutError
    elif code == grpc.StatusCode.UNAVAILABLE:
        cls = WorkerUnavailableError
    else:
        cls = TransportError
    return cls(msg, worker_url=url, task=key,
               original_type=type(e).__name__)


def _key_to_obj(key: TaskKey) -> list:
    return [key.query_id, key.stage_id, key.task_number]


def _key_from_obj(o) -> TaskKey:
    return TaskKey(o[0], o[1], o[2])


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


def _handlers(worker: Worker):
    import grpc
    import threading as _threading

    # segments published for a task's transfer streams whose tokens the
    # client may never release (it tears the stream with S-frames still
    # buffered): reclaimed when the client's `_release_incomplete` sends
    # Invalidate for the task, and bounded by an oldest-first sweep for
    # cleanly drained streams that never invalidate. Token release is
    # idempotent, so reclaiming a segment the client DID consume is a
    # no-op.
    task_shm_tokens: dict = {}
    task_shm_lock = _threading.Lock()

    def _reclaim_task_segments(key) -> None:
        with task_shm_lock:
            tokens = task_shm_tokens.pop(key, [])
            while len(task_shm_tokens) > 256:
                tokens.extend(
                    task_shm_tokens.pop(next(iter(task_shm_tokens)))
                )
        for name, token in tokens:
            try:
                worker.segment_pool.release(name, token)
            except Exception:
                pass  # reclaim must never mask the caller's own path

    def set_plan(request: bytes, context) -> bytes:
        header, blobs = transport.unpack_frame(request)
        key = _key_from_obj(header["key"])
        caps = header.get("table_caps") or {}
        try:
            # materialize shipped table slices into the worker's store at
            # their ORIGINAL padded capacities (see the client-side comment
            # on table_caps: re-padding would change the plan fingerprint);
            # put_as routes through the store's byte accounting AND the
            # enforced-budget gate, attributed to the shipping query
            from datafusion_distributed_tpu.runtime.codec import (
                staging_attribution,
            )

            with staging_attribution(key.query_id):
                for tid, raw in blobs.items():
                    worker.table_store.put_as(
                        tid, decode_table(raw, capacity=caps.get(tid))
                    )
            worker.set_plan(key, header["plan"], header["task_count"],
                            config=header.get("config"),
                            headers=header.get("headers"),
                            ttl=header.get("ttl"))
            return json.dumps({"ok": True}).encode()
        except WorkerError as e:
            # a failed set_plan registered no entry to own the staged
            # slices — release them or they leak until process exit
            worker.table_store.remove(list(blobs))
            return json.dumps({"error": e.to_dict()}).encode()
        except Exception as e:  # structured contract for transport errors too
            worker.table_store.remove(list(blobs))
            return json.dumps(
                {"error": wrap_worker_exception(e, worker.url, key).to_dict()}
            ).encode()

    def execute_task(request: bytes, context):
        """Server-streaming. Two protocols:

        bulk (no chunk_rows): header+table as ONE framed payload sliced
        into transport pieces; the client's read pace backpressures via
        gRPC flow control.

        streaming (chunk_rows > 0): a header message then one framed
        message PER ROW CHUNK — rows after a client cancellation are never
        even encoded (the reference's dropped-stream early exit,
        `impl_execute_task.rs:97-112`)."""
        msg = json.loads(request.decode())
        key = _key_from_obj(msg["key"])
        codec = msg.get("compression", "zstd")
        chunk = int(msg.get("chunk_bytes", transport.DEFAULT_CHUNK_BYTES))
        chunk_rows = int(msg.get("chunk_rows", 0))
        parts = msg.get("partitions")
        if parts:
            # partition-range multiplex: one stream serves partitions
            # [lo, hi) of the task's hash-partitioned output; each chunk
            # message is tagged with its partition id (the reference's
            # FlightAppMetadata partition tag, `impl_execute_task.rs:
            # 146-158`); accounting/invalidation is the worker's
            # drop-driven partitions_remaining, NOT this handler's finally
            try:
                for p, piece, _est in worker.execute_task_partitions(
                    key, parts["keys"], int(parts["num"]),
                    int(parts["lo"]), int(parts["hi"]),
                    per_dest_capacity=int(parts.get("per_dest_cap", 0)),
                    chunk_rows=chunk_rows or 65536,
                ):
                    if not context.is_active():  # cancelled: stop producing
                        return
                    yield b"P" + transport.pack_frame(
                        {"part": p}, {"table": encode_table(piece)},
                        codec=codec,
                    )
                yield b"H" + json.dumps(
                    {"progress": worker.task_progress(key)}
                ).encode()
            except WorkerError as e:
                yield b"E" + json.dumps(e.to_dict()).encode()
            except Exception as e:
                yield b"E" + json.dumps(
                    wrap_worker_exception(e, worker.url, key).to_dict()
                ).encode()
            finally:
                if worker.partitions_remaining(key) in (None, 0):
                    worker.table_store.remove(msg.get("table_ids", []))
            return
        try:
            try:
                out = worker.execute_task(key)
                # progress rides the response: the registry entry is
                # invalidated below, so a later TaskProgress call couldn't
                # see it
                progress = worker.task_progress(key)
            except WorkerError as e:
                yield b"E" + json.dumps(e.to_dict()).encode()
                return
            except Exception as e:
                yield b"E" + json.dumps(
                    wrap_worker_exception(e, worker.url, key).to_dict()
                ).encode()
                return
            if chunk_rows > 0:
                yield b"H" + json.dumps({"progress": progress}).encode()
                from datafusion_distributed_tpu.ops.table import (
                    host_view,
                    slice_view,
                    zero_copy_enabled,
                )

                # honor the session's `SET distributed.zero_copy` (the
                # coordinator ships it in the task config; the entry is
                # still registered — this handler's finally invalidates)
                data = worker.registry.get(key)
                zc = zero_copy_enabled(
                    data.config if data is not None else None
                )
                if zc:
                    # one host rebind; chunks are views and encode_table
                    # reads them without a device slice per chunk
                    out = host_view(out)
                n = int(out.num_rows)
                for lo in range(0, max(n, 1), chunk_rows):
                    if not context.is_active():  # cancelled: stop producing
                        return
                    count = min(chunk_rows, n - lo)
                    piece = (slice_view(out, lo, count) if zc
                             else out.slice_rows(lo, count))
                    yield b"T" + transport.pack_frame(
                        {}, {"table": encode_table(piece)}, codec=codec
                    )
                return
            frame = transport.pack_frame(
                {"progress": progress}, {"table": encode_table(out)},
                codec=codec,
            )
            for piece in transport.iter_chunks(frame, chunk):
                if not context.is_active():
                    return
                yield b"D" + piece
        finally:
            worker.registry.invalidate(key)
            worker.table_store.remove(msg.get("table_ids", []))

    def transfer_partitions(request: bytes, context):
        """Server-streaming DoGet-style transfer (the Arrow Flight layer
        of SURVEY.md §L3): serves the SAME partition-chunk sequence as
        `execute_task` partition multiplexing — the planes' byte-identity
        contract — but classifies the hop first:

        co-located (client hostname == ours): each chunk's Arrow IPC
        payload is PUBLISHED into the worker's segment pool and the
        stream carries only an S-frame reference {dir, seg, token} —
        zero payload bytes on the wire; the consumer mmap-reads the
        segment and drops its reference.

        remote: chunks ship as wire frames with ADAPTIVE per-column
        compression (A-frames, runtime/codec.encode_table_adaptive)
        under the codec set both ends negotiated, falling back to
        single-blob P-frames for tiny payloads or forced codecs."""
        from datafusion_distributed_tpu.runtime.codec import (
            ADAPTIVE_MIN_BYTES,
            encode_table_adaptive,
        )
        from datafusion_distributed_tpu.runtime.shm_plane import (
            SegmentError,
            SegmentPool,
        )

        msg = json.loads(request.decode())
        key = _key_from_obj(msg["key"])
        chunk_rows = int(msg.get("chunk_rows", 65536)) or 65536
        parts = msg["partitions"]
        peer_codecs = msg.get("wire_codecs") or None
        wire_mode = msg.get("wire_compression", "auto")
        base = transport.negotiate_codec(
            msg.get("compression", "zstd"), peer_codecs
        )
        if wire_mode in ("zstd", "lz4"):
            base = transport.negotiate_codec(wire_mode, peer_codecs)
        elif wire_mode == "off":
            base = "none"
        # adaptive picks only from codecs BOTH ends decode
        allowed = [
            c for c in transport.supported_codecs()
            if peer_codecs is None or c in peer_codecs
        ]
        pool = worker.segment_pool
        serve_shm = SegmentPool.same_host(msg.get("shm"))
        shm_tokens: list = []
        drained = False
        try:
            for p, piece, est in worker.execute_task_partitions(
                key, parts["keys"], int(parts["num"]),
                int(parts["lo"]), int(parts["hi"]),
                per_dest_capacity=int(parts.get("per_dest_cap", 0)),
                chunk_rows=chunk_rows,
            ):
                if not context.is_active():  # cancelled: stop producing
                    return
                if serve_shm:
                    payload = encode_table(piece)
                    try:
                        name, token = pool.publish(
                            payload, int(getattr(piece, "capacity", 0))
                        )
                    except SegmentError:
                        # pool unusable (tmpfs full/gone): degrade the
                        # REST of the stream to the wire path
                        serve_shm = False
                    else:
                        shm_tokens.append((name, token))
                        with task_shm_lock:
                            task_shm_tokens.setdefault(key, []).append(
                                (name, token)
                            )
                        yield b"S" + json.dumps({
                            "part": p, "seg": name, "token": token,
                            "dir": pool.descriptor()["dir"],
                            "nbytes": len(payload),
                        }).encode()
                        continue
                if wire_mode == "auto" and est > ADAPTIVE_MIN_BYTES:
                    blobs, col_codecs = encode_table_adaptive(
                        piece, allowed
                    )
                    if blobs:
                        yield b"A" + transport.pack_frame(
                            {"part": p, "cols": len(blobs)}, blobs,
                            codec=base, codecs=col_codecs,
                        )
                        continue
                yield b"P" + transport.pack_frame(
                    {"part": p}, {"table": encode_table(piece)},
                    codec=base,
                )
            yield b"H" + json.dumps(
                {"progress": worker.task_progress(key)}
            ).encode()
            drained = True
        except WorkerError as e:
            yield b"E" + json.dumps(e.to_dict()).encode()
        except Exception as e:
            yield b"E" + json.dumps(
                wrap_worker_exception(e, worker.url, key).to_dict()
            ).encode()
        finally:
            if not drained:
                # the producer side never finished: S-frames the client
                # will never open still hold their publish token —
                # reclaim this stream's own publishes (idempotent per
                # token, so segments the client DID consume-and-release
                # are untouched). A stream that drained server-side can
                # STILL be torn by the client with S-frames buffered;
                # that path is reclaimed by the client's Invalidate (its
                # `_release_incomplete`) via `_reclaim_task_segments`.
                for name, token in shm_tokens:
                    try:
                        pool.release(name, token)
                    except Exception:
                        pass
            if worker.partitions_remaining(key) in (None, 0):
                worker.table_store.remove(msg.get("table_ids", []))

    def get_info(request: bytes, context) -> bytes:
        return json.dumps(worker.get_info()).encode()

    def get_metrics(request: bytes, context) -> bytes:
        # telemetry exposition (runtime/telemetry.py): the snapshot is
        # JSON-able by construction; the client (or the observability
        # service) renders OpenMetrics text from it after merging
        return json.dumps({"metrics": worker.get_metrics()}).encode()

    def task_progress(request: bytes, context) -> bytes:
        msg = json.loads(request.decode())
        p = worker.task_progress(_key_from_obj(msg["key"]))
        return json.dumps({"progress": p}).encode()

    def invalidate(request: bytes, context) -> bytes:
        # query-end release (the coordinator's EOS sweep for peer-plane
        # producer tasks that were never, or only partially, pulled)
        msg = json.loads(request.decode())
        key = _key_from_obj(msg["key"])
        worker.release_task(key)
        _reclaim_task_segments(key)
        return json.dumps({"ok": True}).encode()

    unary = {
        "SetPlan": set_plan,
        "GetInfo": get_info,
        "GetMetrics": get_metrics,
        "TaskProgress": task_progress,
        "Invalidate": invalidate,
    }
    method_handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=None, response_serializer=None
        )
        for name, fn in unary.items()
    }
    method_handlers["ExecuteTask"] = grpc.unary_stream_rpc_method_handler(
        execute_task, request_deserializer=None, response_serializer=None
    )
    method_handlers["TransferPartitions"] = (
        grpc.unary_stream_rpc_method_handler(
            transfer_partitions,
            request_deserializer=None, response_serializer=None,
        )
    )
    return grpc.method_handlers_generic_handler(_SERVICE, method_handlers)


def serve_worker(worker: Worker, port: int = 0, host: str = "0.0.0.0"):
    """-> (grpc.Server, bound_port). Unlimited message sizes, matching the
    reference's into_worker_server (`worker_service.rs:127-158`). Binds to
    all interfaces by default (multi-host); pass host="127.0.0.1" for a
    loopback-only fixture."""
    import grpc

    # Peer-plane recursion holds a server thread per in-flight consumer
    # execute while its producer streams are served by the SAME pool (a
    # deep staged query can pin several threads per worker); size the pool
    # well past the worst realistic stage depth x concurrent streams.
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=32),
        options=[
            ("grpc.max_receive_message_length", -1),
            ("grpc.max_send_message_length", -1),
        ],
    )
    server.add_generic_rpc_handlers((_handlers(worker),))
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class GrpcWorkerClient:
    """Duck-typed as `Worker` for the Coordinator: set_plan / execute_task /
    get_info / task_progress / table_store / registry."""

    def __init__(self, url: str, compression: str = "zstd",
                 chunk_bytes: int = transport.DEFAULT_CHUNK_BYTES):
        # (in-flight byte budgeting lives in the coordinator's streaming
        # plane, runtime/streams.py — not per-connection)
        import grpc

        self.url = url
        self.compression = transport.effective_codec(compression)
        self.chunk_bytes = chunk_bytes
        target = url.removeprefix("grpc://")
        self._channel = grpc.insecure_channel(
            target,
            options=[
                ("grpc.max_receive_message_length", -1),
                ("grpc.max_send_message_length", -1),
            ],
        )
        self.table_store = TableStore()  # filled by encode_plan pre-flight
        self.registry = _NullRegistry()
        self._shipped_ids: dict[TaskKey, list] = {}
        self._progress_cache: dict[TaskKey, Optional[dict]] = {}
        # per-CONNECTION negotiated codec (None until the first data
        # call asks the server what it decodes)
        self._negotiated_codec: Optional[str] = None
        # set after a SegmentError: the shm plane stays off for this
        # connection (retries re-pull over the wire path)
        self._shm_broken = False
        # chaos hook (runtime/chaos.py kind="segment_lost"): tear the
        # next S-frame's segment before opening it
        self._chaos_tear_next_segment = False

    def _wire_codec(self) -> str:
        """The codec this connection puts on the wire: the constructor's
        request intersected with the SERVER's advertised `wire_codecs`
        (GetInfo), negotiated once per connection. A server without the
        field (version skew) or an unreachable GetInfo falls back to this
        end's `effective_codec` alone — the frame stays self-describing
        either way, so a mistaken pick degrades, never corrupts."""
        cached = self._negotiated_codec
        if cached is None:
            try:
                peer = self.get_info().get("wire_codecs")
            except Exception:
                peer = None
            cached = transport.negotiate_codec(self.compression, peer)
            self._negotiated_codec = cached
        return cached

    def _call(self, method: str, payload: dict,
              timeout: Optional[float] = None) -> dict:
        import grpc

        rpc = self._channel.unary_unary(
            f"/{_SERVICE}/{method}",
            request_serializer=None,
            response_deserializer=None,
        )
        try:
            resp = rpc(json.dumps(payload).encode(), timeout=timeout)
        except grpc.RpcError as e:
            raise _map_rpc_error(e, self.url) from e
        msg = json.loads(resp.decode())
        if "error" in msg:
            raise WorkerError.from_dict(msg["error"])
        return msg

    def set_plan(self, key: TaskKey, plan_obj: dict, task_count: int,
                 config: Optional[dict] = None,
                 headers: Optional[dict] = None,
                 ttl: Optional[float] = None,
                 timeout: Optional[float] = None) -> int:
        """``timeout``: dispatch deadline, enforced by gRPC itself;
        DEADLINE_EXCEEDED surfaces as the retryable TaskTimeoutError.

        -> the framed wire bytes this ship put on the wire (compressed
        payload + codec framing): returned, not stashed on the client —
        clients are cached per url and shared across concurrent
        dispatches, so instance state would attribute one thread's frame
        size to another's dispatch span (runtime/tracing.py)."""
        import grpc

        tids = collect_table_ids(plan_obj)
        blobs = {
            tid: encode_table(self.table_store.get(tid)) for tid in tids
        }
        self._shipped_ids[key] = tids
        frame = transport.pack_frame(
            {
                "key": _key_to_obj(key),
                "plan": plan_obj,
                "task_count": task_count,
                "config": config or {},
                "headers": headers or {},
                "ttl": ttl,
                # padded capacities of the shipped tables: the wire payload
                # only carries live rows, so without these the server would
                # re-pad to pow2(rows) — changing leaf capacities, and with
                # them the plan's structural fingerprint (breaking the
                # post-decode DFTPU043 check AND fragmenting the
                # stage-share compile cache by shape)
                "table_caps": {
                    tid: int(self.table_store.get(tid).capacity)
                    for tid in tids
                },
            },
            blobs,
            codec=self._wire_codec(),
        )
        rpc = self._channel.unary_unary(
            f"/{_SERVICE}/SetPlan",
            request_serializer=None, response_deserializer=None,
        )
        try:
            msg = json.loads(rpc(frame, timeout=timeout).decode())
        except grpc.RpcError as e:
            # the ship may or may not have landed server-side; drop the
            # local copies either way (a retry re-encodes) and let the
            # retryable mapped error drive rerouting. Best-effort
            # Invalidate: a deadline-abandoned server handler may still
            # register the entry, pinning decoded slices on the struggling
            # worker until the TTL sweep — narrow the window (the sweep
            # remains the backstop for registrations landing after this)
            self._shipped_ids.pop(key, None)
            self.table_store.remove(tids)
            try:
                self._call("Invalidate", {"key": _key_to_obj(key)},
                           timeout=5.0)
            except Exception:
                pass
            raise _map_rpc_error(e, self.url, key) from e
        if "error" in msg:
            self._shipped_ids.pop(key, None)
            self.table_store.remove(tids)
            raise WorkerError.from_dict(msg["error"])
        # local copies served their purpose once serialized
        self.table_store.remove(tids)
        return len(frame)

    def execute_task(self, key: TaskKey,
                     timeout: Optional[float] = None) -> Table:
        import grpc

        rpc = self._channel.unary_stream(
            f"/{_SERVICE}/ExecuteTask",
            request_serializer=None, response_deserializer=None,
        )
        req = json.dumps({
            "key": _key_to_obj(key),
            "table_ids": self._shipped_ids.pop(key, []),
            "compression": self._wire_codec(),
            "chunk_bytes": self.chunk_bytes,
        }).encode()
        stream = rpc(req, timeout=timeout)

        def chunks():
            try:
                for piece in stream:
                    tag, body = piece[:1], piece[1:]
                    if tag == b"E":
                        raise WorkerError.from_dict(json.loads(body.decode()))
                    yield body
            except grpc.RpcError as e:
                stream.cancel()
                raise _map_rpc_error(e, self.url, key) from e
            except BaseException:
                stream.cancel()  # cancellation propagates to the producer
                raise

        # NOTE: gRPC's stream flow control is the read-ahead backpressure
        # (the reference's 64 MiB budget role); the budget is NOT a cap on
        # result size — large-but-valid outputs must stream through.
        frame = transport.collect_chunks(chunks())
        header, blobs = transport.unpack_frame(frame)
        # server invalidates its registry after the call; progress rides the
        # response and is served from this cache
        self._progress_cache[key] = header.get("progress")
        return decode_table(blobs["table"])

    def execute_task_stream(self, key: TaskKey, chunk_rows: int = 65536,
                            cancel=None):
        """Streaming protocol: yields (chunk Table, wire_bytes). Setting
        ``cancel`` cancels the gRPC stream — the server stops encoding rows
        (true wire-level early exit)."""
        rpc = self._channel.unary_stream(
            f"/{_SERVICE}/ExecuteTask",
            request_serializer=None, response_deserializer=None,
        )
        req = json.dumps({
            "key": _key_to_obj(key),
            "table_ids": self._shipped_ids.pop(key, []),
            "compression": self._wire_codec(),
            "chunk_rows": int(chunk_rows),
        }).encode()
        stream = rpc(req)
        try:
            import grpc

            try:
                for piece in stream:
                    tag, body = piece[:1], piece[1:]
                    if tag == b"E":
                        raise WorkerError.from_dict(
                            json.loads(body.decode())
                        )
                    if tag == b"H":
                        self._progress_cache[key] = json.loads(
                            body.decode()
                        ).get("progress")
                        continue
                    _, blobs = transport.unpack_frame(body)
                    yield decode_table(blobs["table"]), len(body)
                    if cancel is not None and cancel.is_set():
                        return
            except grpc.RpcError as e:
                raise _map_rpc_error(e, self.url, key) from e
        finally:
            stream.cancel()

    def execute_task_partitions(self, key: TaskKey, key_names,
                                num_partitions: int, part_lo: int,
                                part_hi: int, per_dest_capacity: int = 0,
                                chunk_rows: int = 65536, cancel=None):
        """Partition-range multiplex (the reference's RemoteWorkerConnection
        stream carrying a partition range, demuxed per partition,
        `worker_connection_pool.rs:243-308`). Yields
        (partition_id, chunk Table, wire_bytes)."""
        rpc = self._channel.unary_stream(
            f"/{_SERVICE}/ExecuteTask",
            request_serializer=None, response_deserializer=None,
        )
        req = json.dumps({
            "key": _key_to_obj(key),
            "table_ids": self._shipped_ids.pop(key, []),
            "compression": self._wire_codec(),
            "chunk_rows": int(chunk_rows),
            "partitions": {
                "keys": list(key_names), "num": int(num_partitions),
                "lo": int(part_lo), "hi": int(part_hi),
                "per_dest_cap": int(per_dest_capacity),
            },
        }).encode()
        stream = rpc(req)
        completed = False
        try:
            import grpc

            try:
                for piece in stream:
                    tag, body = piece[:1], piece[1:]
                    if tag == b"E":
                        raise WorkerError.from_dict(
                            json.loads(body.decode())
                        )
                    if tag == b"H":
                        # trails the last chunk: the stream fully drained
                        # and the server's drop-driven release already ran
                        completed = True
                        self._progress_cache[key] = json.loads(
                            body.decode()
                        ).get("progress")
                        continue
                    header, blobs = transport.unpack_frame(body)
                    yield (header["part"], decode_table(blobs["table"]),
                           len(body))
                    if cancel is not None and cancel.is_set():
                        return
            except grpc.RpcError as e:
                raise _map_rpc_error(e, self.url, key) from e
        finally:
            stream.cancel()
            self._release_incomplete(key, completed)

    def transfer_partitions(self, key: TaskKey, key_names,
                            num_partitions: int, part_lo: int,
                            part_hi: int, per_dest_capacity: int = 0,
                            chunk_rows: int = 65536, cancel=None,
                            wire_compression: str = "auto",
                            shm: bool = True):
        """Streaming DoGet-style pull (the TransferPartitions RPC):
        same yield contract as `execute_task_partitions` —
        (partition_id, chunk Table, wire_bytes) — but the server
        classifies the hop and picks the cheapest plane per chunk:
        S-frames carry a shared-memory segment reference (co-located,
        zero payload bytes on the wire), A-frames adaptive per-column
        compressed payloads, P-frames the plain single-blob fallback.
        A torn segment marks the shm plane broken for this connection
        and raises a RETRYABLE TransportError — the coordinator's
        normal retry re-pulls the partition over the wire path."""
        import os

        from datafusion_distributed_tpu.runtime import shm_plane
        from datafusion_distributed_tpu.runtime.codec import (
            decode_table_adaptive,
        )
        from datafusion_distributed_tpu.runtime.telemetry import (
            DEFAULT_REGISTRY,
        )

        wire_ctr = DEFAULT_REGISTRY.counter(
            "dftpu_wire_bytes",
            "Payload bytes that crossed the wire, by data plane",
            labels=("plane",),
        )
        saved_ctr = DEFAULT_REGISTRY.counter(
            "dftpu_wire_bytes_saved",
            "Wire bytes avoided (shm references, compression delta)",
            labels=("plane",),
        )
        rpc = self._channel.unary_stream(
            f"/{_SERVICE}/TransferPartitions",
            request_serializer=None, response_deserializer=None,
        )
        req = {
            "key": _key_to_obj(key),
            "table_ids": self._shipped_ids.pop(key, []),
            "compression": self._wire_codec(),
            "wire_compression": wire_compression,
            "wire_codecs": transport.supported_codecs(),
            "chunk_rows": int(chunk_rows),
            "partitions": {
                "keys": list(key_names), "num": int(num_partitions),
                "lo": int(part_lo), "hi": int(part_hi),
                "per_dest_cap": int(per_dest_capacity),
            },
        }
        if shm and not self._shm_broken:
            # only the hostname ships: the server reachability-checks
            # its OWN pool dir, the client checks the dir the S-frame
            # names — neither trusts a stale descriptor
            import socket

            req["shm"] = {"host": socket.gethostname()}
        stream = rpc(json.dumps(req).encode())
        completed = False
        try:
            import grpc

            try:
                for piece in stream:
                    tag, body = piece[:1], piece[1:]
                    if tag == b"E":
                        raise WorkerError.from_dict(
                            json.loads(body.decode())
                        )
                    if tag == b"H":
                        # trails the last chunk: the stream fully drained
                        # and the server's drop-driven release already ran
                        completed = True
                        self._progress_cache[key] = json.loads(
                            body.decode()
                        ).get("progress")
                        continue
                    if tag == b"S":
                        info = json.loads(body.decode())
                        if self._chaos_tear_next_segment:
                            # chaos kind="segment_lost": tear the segment
                            # between publish and open (the crash window
                            # a dying producer process leaves behind)
                            self._chaos_tear_next_segment = False
                            try:
                                os.unlink(os.path.join(
                                    info["dir"], info["seg"] + ".seg"
                                ))
                            except OSError:
                                pass
                        try:
                            payload, _cap = shm_plane.open_segment_at(
                                info["dir"], info["seg"]
                            )
                        except shm_plane.SegmentError as e:
                            # release what we failed to read (idempotent
                            # on a gone segment), then degrade: wire-only
                            # for this connection, retryable for this pull
                            shm_plane.release_at(
                                info["dir"], info["seg"], info["token"]
                            )
                            self._shm_broken = True
                            DEFAULT_REGISTRY.counter(
                                "dftpu_shm_fallbacks",
                                "Shm segments lost; pulls degraded to "
                                "the wire path",
                            ).inc()
                            raise TransportError(
                                f"shm segment lost ({e}); retry pulls "
                                f"over the wire path",
                                worker_url=self.url, task=key,
                            ) from e
                        shm_plane.release_at(
                            info["dir"], info["seg"], info["token"]
                        )
                        # decode WITHOUT capacity — identical to the
                        # P-frame path (the planes' byte-identity
                        # contract); padding is re-derived downstream
                        saved_ctr.inc(int(info["nbytes"]), plane="shm")
                        yield (info["part"], decode_table(payload),
                               len(body))
                    elif tag == b"A":
                        header, blobs = transport.unpack_frame(body)
                        wire_ctr.inc(len(body), plane="stream")
                        saved_ctr.inc(
                            transport.frame_saved_bytes(header),
                            plane="stream",
                        )
                        yield (header["part"],
                               decode_table_adaptive(
                                   blobs, header["cols"]
                               ),
                               len(body))
                    else:  # b"P"
                        header, blobs = transport.unpack_frame(body)
                        wire_ctr.inc(len(body), plane="stream")
                        saved_ctr.inc(
                            transport.frame_saved_bytes(header),
                            plane="stream",
                        )
                        yield (header["part"],
                               decode_table(blobs["table"]), len(body))
                    if cancel is not None and cancel.is_set():
                        return
            except grpc.RpcError as e:
                raise _map_rpc_error(e, self.url, key) from e
        finally:
            stream.cancel()
            self._release_incomplete(key, completed)

    def _release_incomplete(self, key: TaskKey, completed: bool) -> None:
        """Best-effort remote release of a partition stream that tore
        down before its trailing H-frame (abandoned LIMIT stream, torn
        segment, retry reroute): the server's drop-driven release only
        fires when EVERY partition is served, so an abandoned remote
        task would otherwise pin its registry entry and shipped slices
        until TTL. The in-process planes get the same sweep from the
        coordinator's `_cleanup_task`; this is its remote face."""
        if completed:
            return
        try:
            self._call("Invalidate", {"key": _key_to_obj(key)})
        except Exception:
            pass  # release must never mask the stream's own error

    def get_info(self) -> dict:
        return self._call("GetInfo", {})

    def get_metrics(self) -> dict:
        """The SERVER worker's telemetry snapshot (the `get_metrics`
        RPC, runtime/telemetry.py wire format) — duck-typed with
        `Worker.get_metrics` so the observability merge runs unchanged
        over either transport."""
        return self._call("GetMetrics", {}).get("metrics", {})

    @property
    def peer_capable(self) -> bool:
        """Asks the SERVER whether it was wired with a peer resolver (the
        client handle cannot know); cached — cluster wiring is static."""
        cached = getattr(self, "_peer_capable_cache", None)
        if cached is None:
            try:
                cached = bool(self.get_info().get("peer_capable", False))
            except Exception:
                cached = False
            self._peer_capable_cache = cached
        return cached

    def release_task(self, key: TaskKey) -> None:
        self._shipped_ids.pop(key, None)
        self._progress_cache.pop(key, None)
        self._call("Invalidate", {"key": _key_to_obj(key)})

    def task_progress(self, key: TaskKey):
        if key in self._progress_cache:
            return self._progress_cache[key]
        return self._call("TaskProgress", {"key": _key_to_obj(key)}).get(
            "progress"
        )


class _NullRegistry:
    """The server invalidates its own registry; the client has nothing to
    clean (Coordinator calls registry.invalidate uniformly)."""

    def invalidate(self, key) -> None:
        pass


# ---------------------------------------------------------------------------
# localhost cluster fixture
# ---------------------------------------------------------------------------


class GrpcPeerResolver:
    """Worker-side channel resolver for the peer data plane: url -> cached
    GrpcWorkerClient (the reference's DefaultChannelResolver channel cache,
    `channel_resolver.rs:113-171`). Shared by all workers in a process."""

    def __init__(self) -> None:
        import threading

        self._clients: dict[str, GrpcWorkerClient] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def get_worker(self, url: str) -> GrpcWorkerClient:
        with self._lock:
            if url not in self._clients:
                self._clients[url] = GrpcWorkerClient(url)
            return self._clients[url]


class GrpcCluster:
    """N gRPC workers on random localhost ports, one process — the
    `start_localhost_context` analogue (`src/test_utils/localhost.rs`).

    Membership is DYNAMIC (the gRPC face of the in-memory
    `DynamicCluster`): `add_worker` spawns a new server and bumps the
    monotonically increasing `membership_epoch`; `remove_worker` stops a
    server NOW (in-flight RPCs fail with UNAVAILABLE -> the retryable
    taxonomy); `drain_worker` keeps the server running for in-flight work
    and peer pulls but drops the url from `get_urls()` so no new tasks
    route to it."""

    def __init__(self, num_workers: int, ttl_seconds: float = 600.0):
        self.servers = []  # guarded-by: _lock
        self.urls = []  # guarded-by: _lock
        # test introspection
        self.local_workers: list[Worker] = []  # guarded-by: _lock
        self._clients: dict[str, GrpcWorkerClient] = {}  # guarded-by: _lock
        self._peer_resolver = GrpcPeerResolver()
        self._ttl = ttl_seconds
        self._epoch = 0  # guarded-by: _lock
        # url -> (server, Worker)
        self._by_url: dict[str, tuple] = {}  # guarded-by: _lock
        # requested label -> bound url: a membership schedule names a
        # joiner by label ("grpc://w-new") but the real endpoint is the
        # bound localhost port; later leave/drain events for the label
        # must resolve to the server they spawned
        self._aliases: dict[str, str] = {}  # guarded-by: _lock
        self._draining: list[str] = []  # guarded-by: _lock
        self._departed: set = set()  # guarded-by: _lock
        # chaos membership events mutate from worker-call threads while
        # coordinator pool threads read urls/epoch — same guarantee as
        # DynamicCluster's RLock (a reader never sees a torn url-set/epoch
        # pair, concurrent mutations never lose an epoch bump)
        self._lock = threading.RLock()
        for i in range(num_workers):
            self.add_worker()

    def _resolve(self, url: str) -> str:
        return self._aliases.get(url, url)

    @property
    def membership_epoch(self) -> int:
        with self._lock:
            return self._epoch

    def get_urls(self):
        with self._lock:
            return list(self.urls)

    def get_worker(self, url: str) -> GrpcWorkerClient:
        with self._lock:
            url = self._resolve(url)
            if url in self._departed:
                raise WorkerUnavailableError(
                    f"worker {url} has left the cluster", worker_url=url
                )
            if url not in self._clients:
                self._clients[url] = GrpcWorkerClient(url)
            return self._clients[url]

    # -- dynamic membership --------------------------------------------------
    def add_worker(self, url: Optional[str] = None) -> str:
        """Spawn + serve a new worker; -> its url. A requested ``url`` is
        only a label — the real endpoint is the bound localhost port, and
        the label resolves to it for later membership calls."""
        i = len(self.local_workers)
        w = Worker(url=url or f"grpc-local-{i}", ttl_seconds=self._ttl,
                   peer_channels=self._peer_resolver)
        server, port = serve_worker(w)
        real_url = f"grpc://127.0.0.1:{port}"
        w.url = real_url
        with self._lock:
            if url:
                self._aliases[url] = real_url
            self.servers.append(server)
            self.urls.append(real_url)
            self.local_workers.append(w)
            self._by_url[real_url] = (server, w)
            self._departed.discard(real_url)
            self._epoch += 1
        return real_url

    def remove_worker(self, url: str, release: bool = True) -> None:
        """Abrupt leave: stop the server now. ``release`` clears the local
        worker's registry/store the way the dying process would."""
        with self._lock:
            url = self._resolve(url)
            server, w = self._by_url[url]
            if url in self.urls:
                self.urls.remove(url)
            if url in self._draining:
                self._draining.remove(url)
            self._departed.add(url)
            self._epoch += 1
        server.stop(grace=None)
        if release:
            w.registry.clear()
            w.table_store.tables.clear()

    def drain_worker(self, url: str) -> None:
        with self._lock:
            url = self._resolve(url)
            if url not in self.urls:
                return
            self.urls.remove(url)
            self._draining.append(url)
            self._epoch += 1

    def is_departed(self, url: str) -> bool:
        with self._lock:
            return self._resolve(url) in self._departed

    def is_drained(self, url: str) -> bool:
        with self._lock:
            url = self._resolve(url)
            if url not in self._draining:
                return False
            _server, w = self._by_url[url]
        return len(w.registry) == 0 and not w.table_store.tables

    def finish_drains(self) -> list:
        with self._lock:
            draining = list(self._draining)
        removed = [u for u in draining if self.is_drained(u)]
        for u in removed:
            self.remove_worker(u, release=False)
        return removed

    def membership_snapshot(self) -> dict:
        with self._lock:
            return {
                "epoch": self._epoch,
                "active": list(self.urls),
                "draining": list(self._draining),
                "departed": sorted(self._departed),
            }

    def shutdown(self) -> None:
        for s in self.servers:
            s.stop(grace=None)
        for w in self.local_workers:
            # reclaim shm pool directories (the backstop for references
            # a dead consumer never released)
            w.segment_pool.shutdown()


def start_localhost_cluster(num_workers: int) -> GrpcCluster:
    return GrpcCluster(num_workers)
