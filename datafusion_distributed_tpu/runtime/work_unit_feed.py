"""Work-unit feeds: runtime data-feeding of per-task work discovered late.

The reference streams "units of work" (e.g. file addresses discovered during
execution) from the coordinator to worker tasks over the coordinator channel,
chunked by 256, with create/send/receive/process timestamps per unit
(`/root/reference/src/work_unit_feed/`, worker.proto WorkUnit). Only the feed
UUID crosses the wire; the provider object stays coordinator-side.

Host-runtime equivalent: feeds are queues keyed by UUID in a registry. The
coordinator drains the user's provider (any iterable or callable) into the
consuming worker's remote registry in chunks; `WorkUnitScanExec` is the leaf
that blocks on its feed, loads the units (parquet paths or shipped tables)
and pads them into the task's batch. Timestamps are stamped at the same four
lifecycle points as the reference.
"""

from __future__ import annotations

import queue
import time
import uuid as uuid_mod
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

from datafusion_distributed_tpu.ops.table import Table
from datafusion_distributed_tpu.plan.physical import (
    DistributedTaskContext,
    ExecContext,
    ExecutionPlan,
)
from datafusion_distributed_tpu.schema import Schema

CHUNK = 256  # units per message (query_coordinator.rs:44-47)
_DONE = object()


@dataclass
class WorkUnit:
    payload: Any  # e.g. a file path
    created_at: float = field(default_factory=time.time)
    sent_at: Optional[float] = None
    received_at: Optional[float] = None
    processed_at: Optional[float] = None


class WorkUnitFeedRegistry:
    """Coordinator-side: feed id -> provider (iterable or zero-arg callable
    returning one). Registered via SessionContext/DistributedExt-style API."""

    def __init__(self) -> None:
        self.providers: dict[str, Any] = {}

    def register(self, provider) -> str:
        fid = uuid_mod.uuid4().hex
        self.providers[fid] = provider
        return fid

    def units(self, fid: str) -> Iterable[WorkUnit]:
        provider = self.providers[fid]
        items = provider() if callable(provider) else provider
        for payload in items:
            yield WorkUnit(payload)


class RemoteWorkUnitFeedRegistry:
    """Worker-side: per-(feed id, task) queues the coordinator fills
    (impl_coordinator_channel.rs:128-178 demux analogue)."""

    def __init__(self) -> None:
        self.queues: dict[tuple[str, int], "queue.Queue"] = {}

    def queue_for(self, fid: str, task_number: int) -> "queue.Queue":
        key = (fid, task_number)
        if key not in self.queues:
            self.queues[key] = queue.Queue()
        return self.queues[key]

    def drain(self, fid: str, task_number: int,
              timeout: float = 10.0) -> list[WorkUnit]:
        """Block until the feed closes; return all units (bulk execution
        consumes the whole feed before tracing — the 10 s bound mirrors the
        reference's plan-wait timeout)."""
        q = self.queue_for(fid, task_number)
        out: list[WorkUnit] = []
        while True:
            batch = q.get(timeout=timeout)
            if batch is _DONE:
                return out
            now = time.time()
            for u in batch:
                u.received_at = now
                out.append(u)


def stream_feed(
    registry: WorkUnitFeedRegistry,
    remote: RemoteWorkUnitFeedRegistry,
    fid: str,
    task_router: Callable[[WorkUnit, int], int],
    task_count: int,
) -> int:
    """Coordinator loop: chunk units to each task's queue; -> units sent."""
    per_task: dict[int, list[WorkUnit]] = {i: [] for i in range(task_count)}
    sent = 0
    for unit in registry.units(fid):
        t = task_router(unit, task_count)
        unit.sent_at = time.time()
        per_task[t].append(unit)
        sent += 1
        if len(per_task[t]) >= CHUNK:
            remote.queue_for(fid, t).put(per_task[t])
            per_task[t] = []
    for t, batch in per_task.items():
        if batch:
            remote.queue_for(fid, t).put(batch)
        remote.queue_for(fid, t).put(_DONE)
    return sent


class WorkUnitScanExec(ExecutionPlan):
    """Leaf fed by a work-unit feed: units are parquet file paths (the
    reference's work-unit file scan, `test_utils/work_unit_file_scan.rs`)
    loaded at task-load time after the feed closes."""

    def __init__(self, feed_id: str, schema: Schema, capacity: int,
                 remote_registry: Optional[RemoteWorkUnitFeedRegistry] = None,
                 dictionaries: Optional[dict] = None):
        super().__init__()
        self.feed_id = feed_id
        self._schema = schema
        self.capacity = capacity
        self.remote_registry = remote_registry
        self.dictionaries = dictionaries

    def children(self):
        return []

    def with_new_children(self, children):
        assert not children
        return self

    def schema(self):
        return self._schema

    def output_capacity(self):
        return self.capacity

    def load(self, task: DistributedTaskContext) -> Table:
        from datafusion_distributed_tpu.io.parquet import read_parquet

        if self.remote_registry is None:
            raise RuntimeError(
                "WorkUnitScanExec has no remote feed registry attached"
            )
        units = self.remote_registry.drain(self.feed_id, task.task_index)
        now = time.time()
        for u in units:
            u.processed_at = now
        paths = [u.payload for u in units]
        if not paths:
            return Table.empty(self._schema, self.capacity, self.dictionaries)
        return read_parquet(paths, capacity=self.capacity,
                            dictionaries=self.dictionaries)

    def _execute(self, ctx: ExecContext) -> Table:
        return ctx.inputs[self.node_id]

    def display(self):
        return f"WorkUnitScan feed={self.feed_id[:8]} cap={self.capacity}"
