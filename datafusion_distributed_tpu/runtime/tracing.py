"""End-to-end distributed query tracing (host-side spans + events).

The reference's ObservabilityService answers *what is running where*
(Ping / GetTaskProgress / GetClusterWorkers); nothing in either engine
answered *where a query's wall time went* across
coordinator -> dispatch -> worker -> exchange. This module is that layer:
hierarchical spans ``query -> stage -> task -> attempt`` with typed child
spans for the hot phases (compile/verify, codec encode, dispatch RPC,
worker execute, exchange transfer, TableStore staging) and structured
trace *events* for every fault-path transition the engine already has
(retry, reroute, quarantine, heal, cancel, membership epoch change).

Design constraints (mirrors the MetricsStore contracts):

- ALWAYS CHEAP WHEN OFF: call sites hold a `NULL_TRACER` whose methods
  are no-ops; no span objects, no clock reads, no per-task dict copies.
  `SET distributed.tracing = off|on|sampled` selects the mode per query.
- HOST-SIDE ONLY: spans wrap coordinator/worker *host* phases; nothing
  here may run inside a jax-traced function (tools/check_tracer_safety.py
  rule DFTPU109 enforces it), and the wire context must never enter a
  compile-cache key (span ids differ per task — keying on them would
  force one XLA trace per task; see plan/physical.py's cfg_items filter).
- BOUNDED: a ring buffer per query (oldest spans dropped once
  ``span_cap`` is hit, count surfaced as ``dropped``), LRU across queries
  with RUNNING queries pinned — identical retention contract to
  MetricsStore.stage_spans.
- DETERMINISTIC ENOUGH TO TEST: all timestamps are `time.monotonic`
  (one system-wide clock — comparable across processes on one host, the
  gRPC-localhost tier included); tests assert ordering, never wall-clock.

Cross-wire propagation: the coordinator attaches ``trace_ctx``
(`{"q": query_id, "parent": span_id}`) to the per-dispatch config dict of
the task envelope (runtime/coordinator.py `_dispatch_task`); the worker
records its decode/execute spans as plain JSON-able dicts carrying that
wire parent (runtime/worker.py), and they ride the existing task-progress
payload back — over the in-process transport AND the gRPC response — to
be spliced into the query trace under the propagated parent span.

Exports: Chrome trace-event JSON (``to_chrome_trace`` — load the file in
Perfetto / chrome://tracing), a text profile report (``render_profile``,
folded into `explain_analyze`), and live aggregate counters
(`ObservabilityService.get_trace_summary`, console panel).
"""

from __future__ import annotations

import json
import threading
import time
import zlib
from collections import deque
from typing import Any, Optional

#: `SET distributed.tracing` modes (validated at SET time, sql/context.py)
TRACING_MODES = ("off", "on", "sampled")

#: config key the trace context rides under in the task envelope. MUST
#: stay out of every compile-cache key (plan/physical.py filters it from
#: cfg_items; runtime/worker.py strips it before execute_plan) — span ids
#: differ per task and would otherwise fragment the program caches into
#: one XLA trace per task.
TRACE_CTX_KEY = "trace_ctx"

_SPAN_CAP = 4096     # ring-buffer bound per query
_EVENT_CAP = 2048    # trace-level event bound per query
_QUERY_CAP = 32      # LRU bound across queries (running ones pinned)


def table_nbytes(table) -> int:
    """Host-side device-buffer byte count of an ops Table: data + validity
    of every column (no device sync — `.nbytes` reads the aval). The
    data-plane attribution unit: in-process shipments move exactly these
    buffers (by reference), the wire transport serializes them (plus codec
    framing), so spans attributed with this match `nbytes` by
    construction."""
    total = 0
    for c in getattr(table, "columns", ()):
        data = getattr(c, "data", None)
        if data is not None:
            total += int(data.nbytes)
        validity = getattr(c, "validity", None)
        if validity is not None:
            total += int(validity.nbytes)
    return total


def resolve_tracing_mode(options: Optional[dict]) -> str:
    """The effective `SET distributed.tracing` mode from a config-options
    dict (unknown/missing -> off: tracing is strictly opt-in)."""
    mode = str((options or {}).get("tracing", "off") or "off").strip().lower()
    return mode if mode in TRACING_MODES else "off"


def _sampled(query_id: str, rate: float) -> bool:
    """Deterministic per-query sampling decision: a hash of the query id
    against ``rate`` — the same query id always decides the same way, so a
    replayed run re-traces the same queries."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(query_id.encode()) / 0xFFFFFFFF) < rate


class Span:
    """One closed span. ``t0``/``t1`` are raw `time.monotonic` seconds;
    exports normalize against the trace origin."""

    __slots__ = ("span_id", "parent_id", "name", "kind", "t0", "t1",
                 "attrs")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 kind: str, t0: float, t1: float = 0.0,
                 attrs: Optional[dict] = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.t0 = t0
        self.t1 = t1
        self.attrs = attrs if attrs is not None else {}

    @property
    def duration(self) -> float:
        return max(self.t1 - self.t0, 0.0)

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> dict:
        return {
            "id": self.span_id, "parent": self.parent_id,
            "name": self.name, "kind": self.kind,
            "t0": self.t0, "t1": self.t1, "attrs": dict(self.attrs),
        }


class _NullSpan:
    """The span NULL_TRACER hands out: swallows every mutation."""

    __slots__ = ()
    span_id = None
    parent_id = None
    attrs: dict = {}
    t0 = t1 = 0.0
    duration = 0.0

    def set(self, **attrs) -> "_NullSpan":
        return self


_A_NULL_SPAN = _NullSpan()


class _NullCtx:
    """Reusable no-op context manager yielding the null span."""

    __slots__ = ()

    def __enter__(self) -> _NullSpan:
        return _A_NULL_SPAN

    def __exit__(self, *exc) -> bool:
        return False


_A_NULL_CTX = _NullCtx()


class _NullTracer:
    """The off-mode tracer: every method is a constant-time no-op — call
    sites keep one unconditional code path and pay ~nothing when tracing
    is off (the "always cheap when off" contract)."""

    __slots__ = ()
    active = False

    def span(self, name, kind, parent=None, **attrs):
        return _A_NULL_CTX

    def start_span(self, name, kind, parent=None, **attrs):
        return _A_NULL_SPAN

    def end_span(self, span) -> None:
        pass

    def event(self, name, **attrs) -> None:
        pass

    def reserved_id(self, key):
        return None

    def finish_reserved(self, key, name, kind, t0, t1, parent=None,
                        **attrs) -> None:
        pass

    def current_id(self):
        return None

    def wire_ctx(self):
        return None

    def splice(self, span_dicts, default_parent=None) -> None:
        pass


NULL_TRACER = _NullTracer()


class QueryTrace:
    """One query's bounded span/event store. Thread-safe: spans land from
    the coordinator's stage/task fan-out threads and (spliced) worker
    payloads concurrently."""

    def __init__(self, query_id: str, span_cap: int = _SPAN_CAP,
                 event_cap: int = _EVENT_CAP):
        self.query_id = query_id
        self.t0 = time.monotonic()
        self.t1: Optional[float] = None
        self.finished = False
        # ring buffers: deque(maxlen=...) drops the OLDEST on overflow;
        # `dropped` counts evictions so exports can say "N spans dropped"
        self.spans: deque = deque(maxlen=span_cap)  # guarded-by: _lock
        self.events: deque = deque(maxlen=event_cap)  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock
        self.events_dropped = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        self._next_id = 0  # guarded-by: _lock
        self._reserved: dict = {}  # guarded-by: _lock
        self.root_id: Optional[int] = None
        # summary tally memo, filled by TraceStore._tally once finished
        self._tally_cache: Optional[tuple] = None

    # -- id allocation ------------------------------------------------------
    def new_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def reserve(self, key) -> int:
        """Pre-allocate a span id for ``key`` (e.g. ``("stage", 3)``) so
        children created BEFORE the span closes (task spans inside a still
        -running stage) can parent under it; `finish_reserved` later
        appends the span with this id."""
        with self._lock:
            sid = self._reserved.get(key)
            if sid is None:
                self._next_id += 1
                sid = self._reserved[key] = self._next_id
            return sid

    # -- recording ----------------------------------------------------------
    def add_span(self, span: Span) -> None:
        with self._lock:
            if len(self.spans) == self.spans.maxlen:
                self.dropped += 1
            self.spans.append(span)

    def add_event(self, t: float, name: str, attrs: dict,
                  parent: Optional[int]) -> None:
        with self._lock:
            if len(self.events) == self.events.maxlen:
                self.events_dropped += 1
            self.events.append((t, name, attrs, parent))

    # -- inspection ---------------------------------------------------------
    def span_list(self) -> list:
        with self._lock:
            return list(self.spans)

    def event_list(self) -> list:
        with self._lock:
            return list(self.events)

    def root_span(self) -> Optional[Span]:
        rid = self.root_id
        if rid is None:
            return None
        for s in self.span_list():
            if s.span_id == rid:
                return s
        return None

    def finish(self) -> None:
        self.finished = True
        if self.t1 is None:
            self.t1 = time.monotonic()


class Tracer:
    """Per-query recording facade over a QueryTrace. Implicit parenting
    rides a PER-THREAD span stack (`span()` pushes/pops), so nested host
    phases need no explicit plumbing; work fanned out to pool threads
    passes an explicit ``parent`` (usually a reserved stage span id) to
    seed its own stack."""

    __slots__ = ("trace", "_local")
    active = True

    def __init__(self, trace: QueryTrace):
        self.trace = trace
        self._local = threading.local()

    # -- parent stack -------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current_id(self) -> Optional[int]:
        st = self._stack()
        return st[-1] if st else self.trace.root_id

    # -- spans --------------------------------------------------------------
    def span(self, name: str, kind: str, parent: Optional[int] = None,
             **attrs):
        """Context manager: opens a span now, closes+records it on exit.
        An exception closing the span is recorded as ``error=<TypeName>``
        and re-raised."""
        return _SpanCtx(self, name, kind, parent, attrs)

    def start_span(self, name: str, kind: str,
                   parent: Optional[int] = None, **attrs) -> Span:
        """Explicit begin (no stack participation) — for spans whose end
        lives in a different scope (the query root)."""
        pid = parent if parent is not None else self.current_id()
        return Span(self.trace.new_id(), pid, name, kind,
                    time.monotonic(), attrs=attrs)

    def end_span(self, span: Span) -> None:
        span.t1 = time.monotonic()
        self.trace.add_span(span)

    def reserved_id(self, key) -> int:
        return self.trace.reserve(key)

    def finish_reserved(self, key, name: str, kind: str, t0: float,
                        t1: float, parent: Optional[int] = None,
                        **attrs) -> None:
        """Record the span pre-allocated by `reserved_id(key)` with
        explicit timestamps (the stage spans: the scheduler knows
        submit/start/end after the fact). Default parent: the recording
        thread's current span (the scheduler span), else the root."""
        sid = self.trace.reserve(key)
        pid = parent if parent is not None else self.current_id()
        self.trace.add_span(Span(sid, pid, name, kind, t0, t1, attrs))

    # -- events -------------------------------------------------------------
    def event(self, name: str, **attrs) -> None:
        self.trace.add_event(time.monotonic(), name, attrs,
                             self.current_id())

    # -- cross-wire ---------------------------------------------------------
    def wire_ctx(self) -> dict:
        """The context that rides the task envelope: worker-side spans
        recorded under it join the trace at `splice` time via the
        propagated parent span id."""
        return {"q": self.trace.query_id, "parent": self.current_id()}

    def splice(self, span_dicts, default_parent: Optional[int] = None
               ) -> None:
        """Adopt worker-side span dicts (see worker_span) into this trace:
        each gets a fresh local id and parents under its propagated
        ``wire_parent`` (falling back to ``default_parent`` / the root).
        Worker timestamps are CLOCK_MONOTONIC — system-wide on Linux, so
        same-host workers (in-process and gRPC-localhost tiers) splice
        without rebasing."""
        if default_parent is None:
            default_parent = self.current_id()
        for d in span_dicts:
            try:
                pid = d.get("wire_parent")
                if pid is None:
                    pid = default_parent
                attrs = dict(d.get("attrs") or {})
                attrs.setdefault("remote", True)
                self.trace.add_span(Span(
                    self.trace.new_id(), pid,
                    str(d.get("name", "worker")),
                    str(d.get("kind", "execute")),
                    float(d.get("t0", 0.0)), float(d.get("t1", 0.0)),
                    attrs,
                ))
            except (TypeError, ValueError, KeyError):
                continue  # a malformed wire span must never fail the task


class _SpanCtx:
    __slots__ = ("_tracer", "_span", "_name", "_kind", "_parent", "_attrs")

    def __init__(self, tracer: Tracer, name, kind, parent, attrs):
        self._tracer = tracer
        self._name = name
        self._kind = kind
        self._parent = parent
        self._attrs = attrs
        self._span: Optional[Span] = None

    def __enter__(self) -> Span:
        tr = self._tracer
        pid = self._parent if self._parent is not None else tr.current_id()
        sp = Span(tr.trace.new_id(), pid, self._name, self._kind,
                  time.monotonic(), attrs=self._attrs)
        tr._stack().append(sp.span_id)
        self._span = sp
        return sp

    def __exit__(self, exc_type, exc, tb) -> bool:
        sp = self._span
        tr = self._tracer
        st = tr._stack()
        if st and st[-1] == sp.span_id:
            st.pop()
        elif sp.span_id in st:  # defensive: unwound out of order
            st.remove(sp.span_id)
        if exc_type is not None:
            sp.attrs.setdefault("error", exc_type.__name__)
        sp.t1 = time.monotonic()
        tr.trace.add_span(sp)
        return False


def worker_span(name: str, kind: str, t0: float, t1: float,
                wire_parent, **attrs) -> dict:
    """A worker-side span as a plain JSON-able dict: rides the existing
    task-progress payload back to the coordinator (in-process AND gRPC)
    where `Tracer.splice` adopts it under the propagated parent."""
    return {"name": name, "kind": kind, "t0": t0, "t1": t1,
            "wire_parent": wire_parent, "attrs": attrs}


class TraceStore:
    """query_id -> QueryTrace, LRU-bounded with running queries pinned
    (the MetricsStore retention contract). One process-wide default store
    (`DEFAULT_TRACE_STORE`) backs `ctx.last_trace()`,
    `QueryHandle.trace()`, explain_analyze's profile fold and the
    observability summary."""

    def __init__(self, query_cap: int = _QUERY_CAP,
                 span_cap: int = _SPAN_CAP):
        self.query_cap = query_cap
        self.span_cap = span_cap
        # insertion order == LRU order
        self._traces: dict = {}  # guarded-by: _lock; per-query: swept-by finish
        self._running: set = set()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._started_total = 0  # guarded-by: _lock

    # -- lifecycle ----------------------------------------------------------
    def begin(self, query_id: str, mode: str,
              sample_rate: float = 0.125):
        """-> a live Tracer for this query, or NULL_TRACER when the mode
        (or the sampling decision) says no. The trace is pinned against
        LRU eviction until `finish(query_id)`."""
        if mode == "off":
            return NULL_TRACER
        if mode == "sampled" and not _sampled(query_id, sample_rate):
            return NULL_TRACER
        trace = QueryTrace(query_id, span_cap=self.span_cap)
        with self._lock:
            self._running.add(query_id)
            self._traces[query_id] = trace
            self._started_total += 1
            self._evict_locked()
        return Tracer(trace)

    def finish(self, query_id: str) -> None:
        with self._lock:
            self._running.discard(query_id)
            trace = self._traces.get(query_id)
            self._evict_locked()
        if trace is not None:
            trace.finish()

    def _evict_locked(self) -> None:
        if len(self._traces) <= self.query_cap:
            return
        for qid in list(self._traces):
            if len(self._traces) <= self.query_cap:
                break
            if qid in self._running:
                continue  # never evict a live query's trace
            self._traces.pop(qid)

    # -- lookup -------------------------------------------------------------
    def get(self, query_id: str) -> Optional[QueryTrace]:
        with self._lock:
            trace = self._traces.get(query_id)
            if trace is not None:  # move-to-end: LRU touch
                self._traces.pop(query_id)
                self._traces[query_id] = trace
            return trace

    def last(self) -> Optional[QueryTrace]:
        """Most recently FINISHED trace (running ones are still filling)."""
        with self._lock:
            finished = [t for t in self._traces.values() if t.finished]
        if not finished:
            return None
        return max(finished, key=lambda t: t.t1 or 0.0)

    def annotate(self, query_id: str, **attrs) -> None:
        """Attach attrs to a trace's root span after the fact (the serving
        tier adds admission queue-wait once the handle resolves)."""
        trace = self.get(query_id)
        if trace is None:
            return
        root = trace.root_span()
        if root is not None:
            root.attrs.update(attrs)

    # -- aggregate counters (observability surface) -------------------------
    @staticmethod
    def _tally(trace: QueryTrace) -> tuple:
        """(spans_by_kind, events_by_name, bytes, dropped) for one trace.
        Cached once the trace is FINISHED — its spans/events are immutable
        from then on (post-finish `annotate` only touches root attrs, not
        counts), so the console polling the summary twice a second scans
        only the handful of running traces, not every retained one."""
        cached = getattr(trace, "_tally_cache", None)
        if cached is not None:
            return cached
        by_kind: dict = {}
        by_name: dict = {}
        nbytes = 0
        for s in trace.span_list():
            by_kind[s.kind] = by_kind.get(s.kind, 0) + 1
            b = s.attrs.get("bytes")
            if b:
                nbytes += int(b)
        for _t, name, _a, _p in trace.event_list():
            by_name[name] = by_name.get(name, 0) + 1
        out = (by_kind, by_name, nbytes, trace.dropped)
        if trace.finished:
            trace._tally_cache = out
        return out

    def summary(self) -> dict:
        with self._lock:
            traces = list(self._traces.values())
            running = len(self._running)
            started = self._started_total
        spans_by_kind: dict = {}
        events_by_name: dict = {}
        total_bytes = 0
        dropped = 0
        for t in traces:
            by_kind, by_name, nbytes, t_dropped = self._tally(t)
            dropped += t_dropped
            for k, n in by_kind.items():
                spans_by_kind[k] = spans_by_kind.get(k, 0) + n
            for k, n in by_name.items():
                events_by_name[k] = events_by_name.get(k, 0) + n
            total_bytes += nbytes
        return {
            "traces": len(traces),
            "traces_started": started,
            "running": running,
            "spans": sum(spans_by_kind.values()),
            "spans_by_kind": spans_by_kind,
            "spans_dropped": dropped,
            "events": sum(events_by_name.values()),
            "events_by_name": events_by_name,
            "data_plane_bytes": total_bytes,
        }


DEFAULT_TRACE_STORE = TraceStore()


# ---------------------------------------------------------------------------
# analysis helpers (tests + profile report)
# ---------------------------------------------------------------------------


def _interval_union(intervals) -> list:
    """Merge [lo, hi] intervals -> disjoint sorted list."""
    ivs = sorted((lo, hi) for lo, hi in intervals if hi > lo)
    out: list = []
    for lo, hi in ivs:
        if out and lo <= out[-1][1]:
            out[-1][1] = max(out[-1][1], hi)
        else:
            out.append([lo, hi])
    return out


def trace_coverage(trace: QueryTrace) -> tuple:
    """(covered_fraction, max_gap_fraction) of the ROOT span's interval by
    the union of every other span — the acceptance metric: >= 95% of the
    measured query wall attributed, no unattributed gap over 5%."""
    root = trace.root_span()
    if root is None or root.duration <= 0:
        return 0.0, 1.0
    lo, hi = root.t0, root.t1
    union = _interval_union(
        (max(s.t0, lo), min(s.t1, hi))
        for s in trace.span_list() if s.span_id != root.span_id
    )
    covered = sum(b - a for a, b in union)
    # gaps: before the first covered interval, between them, after the last
    gaps = []
    cursor = lo
    for a, b in union:
        gaps.append(a - cursor)
        cursor = b
    gaps.append(hi - cursor)
    dur = hi - lo
    return covered / dur, (max(gaps) if gaps else dur) / dur


def stage_data_rates(trace: QueryTrace) -> dict:
    """stage_id -> {"bytes", "wall_s", "bytes_per_s"}: every byte-carrying
    span (codec encode, dispatch ship, exchange transfer, worker staging)
    summed per stage lane and divided by the stage's EXECUTE wall (queue
    wait excluded) — the measured GB/s column the zero-copy roadmap item
    needs."""
    spans = trace.span_list()
    stage_spans = {
        s.attrs.get("stage"): s for s in spans if s.kind == "stage"
    }
    # children index: stage lane membership is transitive over parents
    by_id = {s.span_id: s for s in spans}

    def stage_of(s: Span):
        seen = 0
        cur = s
        while cur is not None and seen < 64:
            if cur.kind == "stage":
                return cur.attrs.get("stage")
            cur = by_id.get(cur.parent_id)
            seen += 1
        return None

    out: dict = {}
    for s in spans:
        b = s.attrs.get("bytes")
        if not b:
            continue
        sid = s.attrs.get("stage")
        if sid is None:
            sid = stage_of(s)
        if sid is None:
            continue
        slot = out.setdefault(sid, {"bytes": 0, "wall_s": 0.0})
        slot["bytes"] += int(b)
    for sid, slot in out.items():
        st = stage_spans.get(sid)
        wall = None
        if st is not None:
            wall = max(st.duration - float(st.attrs.get("queue_s", 0.0)),
                       0.0)
        slot["wall_s"] = wall if wall else 0.0
        slot["bytes_per_s"] = (
            slot["bytes"] / wall if wall else None
        )
    return out


def self_times(trace: QueryTrace) -> list:
    """[(span, self_seconds)] sorted descending: span duration minus the
    union of its direct children's intervals (overlapping children — a
    stage's concurrent tasks — must not subtract twice)."""
    spans = trace.span_list()
    children: dict = {}
    for s in spans:
        children.setdefault(s.parent_id, []).append(s)
    out = []
    for s in spans:
        kids = children.get(s.span_id, ())
        covered = sum(
            b - a for a, b in _interval_union(
                (max(k.t0, s.t0), min(k.t1, s.t1)) for k in kids
            )
        )
        out.append((s, max(s.duration - covered, 0.0)))
    out.sort(key=lambda p: -p[1])
    return out


def format_bytes(n: float) -> str:
    """Human-readable byte count (shared with console.py — one formatter,
    no drift between the panel and the profile report)."""
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024:
            return f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}TiB"


_fmt_bytes = format_bytes


def render_profile(trace: QueryTrace, top_n: int = 10) -> str:
    """The per-query text profile (folded into explain_analyze): top-N
    spans by self time, per-stage data-plane bytes/sec, queue-wait vs
    execute split, fault events."""
    root = trace.root_span()
    spans = trace.span_list()
    if root is None or not spans:
        return ""
    lines = [f"-- trace profile (query {trace.query_id[:8]}) --"]
    cov, max_gap = trace_coverage(trace)
    lines.append(
        f"wall {root.duration:.4f}s  {len(spans)} spans"
        + (f" ({trace.dropped} dropped)" if trace.dropped else "")
        + f"  coverage {cov * 100.0:.1f}%"
        f"  max gap {max_gap * 100.0:.1f}%"
    )
    lines.append("top spans by self time:")
    for s, self_s in self_times(trace)[:top_n]:
        if self_s <= 0.0:
            continue
        where = []
        for k in ("stage", "task", "attempt", "worker"):
            v = s.attrs.get(k)
            if v is not None:
                where.append(f"{k}={v}")
        b = s.attrs.get("bytes")
        if b:
            where.append(_fmt_bytes(b))
        lines.append(
            f"  {self_s:8.4f}s  {s.kind:<9} {s.name:<18} "
            + " ".join(where)
        )
    rates = stage_data_rates(trace)
    if rates:
        lines.append("per-stage data plane:")
        for sid in sorted(rates, key=lambda x: (x is None, x)):
            slot = rates[sid]
            rate = slot.get("bytes_per_s")
            rate_txt = (
                f"{rate / 1e9:.3f} GB/s" if rate else "n/a"
            )
            lines.append(
                f"  stage {sid}: {_fmt_bytes(slot['bytes'])} "
                f"in {slot['wall_s']:.4f}s = {rate_txt}"
            )
    stage_spans = [s for s in spans if s.kind == "stage"]
    if stage_spans:
        queue = sum(float(s.attrs.get("queue_s", 0.0)) for s in stage_spans)
        execute = sum(s.duration for s in stage_spans) - queue
        lines.append(
            f"queue wait {queue:.4f}s vs execute {max(execute, 0.0):.4f}s "
            "(summed over stages)"
        )
    events = trace.event_list()
    if events:
        counts: dict = {}
        for _t, name, _a, _p in events:
            counts[name] = counts.get(name, 0) + 1
        lines.append(
            "events: " + ", ".join(
                f"{k}={counts[k]}" for k in sorted(counts)
            )
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------


def to_chrome_trace(trace: QueryTrace) -> dict:
    """Chrome trace-event JSON (the 'X' complete-event + 'i' instant-event
    subset Perfetto renders directly). Lanes (tids) group spans by stage /
    worker so the stage overlap and the data-plane hops read visually."""
    spans = trace.span_list()
    by_id = {s.span_id: s for s in spans}
    base = trace.t0
    lanes: dict = {}

    def lane_for(s: Span) -> str:
        if s.kind in ("query", "schedule", "plan"):
            return "coordinator"
        cur = s
        hops = 0
        while cur is not None and hops < 64:
            sid = cur.attrs.get("stage")
            if cur.kind == "stage" and sid is not None:
                return f"stage {sid}"
            cur = by_id.get(cur.parent_id)
            hops += 1
        return "coordinator"

    def tid_of(label: str) -> int:
        if label not in lanes:
            lanes[label] = len(lanes) + 1
        return lanes[label]

    events = []
    for s in spans:
        args = {k: v for k, v in s.attrs.items()}
        args["span_id"] = s.span_id
        if s.parent_id is not None:
            args["parent_id"] = s.parent_id
        events.append({
            "name": s.name,
            "cat": s.kind,
            "ph": "X",
            "ts": round((s.t0 - base) * 1e6, 3),
            "dur": round(s.duration * 1e6, 3),
            "pid": 1,
            "tid": tid_of(lane_for(s)),
            "args": args,
        })
    for t, name, attrs, parent in trace.event_list():
        parent_span = by_id.get(parent)
        lane = lane_for(parent_span) if parent_span else "coordinator"
        events.append({
            "name": name, "cat": "event", "ph": "i", "s": "t",
            "ts": round((t - base) * 1e6, 3),
            "pid": 1, "tid": tid_of(lane), "args": dict(attrs),
        })
    for label, tid in sorted(lanes.items(), key=lambda kv: kv[1]):
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": label},
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "query_id": trace.query_id,
            "spans_dropped": trace.dropped,
        },
    }


def chrome_trace_json(trace: QueryTrace) -> str:
    return json.dumps(to_chrome_trace(trace))
