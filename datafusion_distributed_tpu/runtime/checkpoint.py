"""Query checkpoint/resume: per-stage output snapshots + recovery.

The serving-hardening half of the ROADMAP item that PR 5's peer-heal
machinery did not cover: peer healing re-ships *producers* onto
survivors mid-query, but a COORDINATOR loss today throws away every
completed stage of every admitted query. This module generalizes that
idea to whole queries — on stage completion the coordinator snapshots
the stage's materialized consumer slices into the workers' TableStores
(data stays on the cluster; the coordinator keeps metadata only), and a
fresh session/coordinator resumes an admitted query from its last
completed stage frontier instead of re-running it from scratch.

Records are validated, never trusted:

- each `StageCheckpoint` carries the stage's STRUCTURAL FINGERPRINT
  (plan/fingerprint.py — literal values included, since the pristine
  pre-hoist subtree is fingerprinted): on resume the re-planned query's
  stage must fingerprint identically or the checkpoint is ignored and
  the stage re-executes (`checkpoint_fp_mismatch`);
- each staged slice is fetched from the worker recorded as holding it:
  a departed worker (or an evicted id) invalidates ONLY that stage
  (`checkpoint_slices_lost`) — the stage re-executes, and its own
  producers still restore from THEIR checkpoints, so a partially-lost
  frontier heals incrementally exactly like the elastic-membership
  re-ship path;
- the membership epoch at save time rides the record for observability
  (the snapshot a resume decision can be audited against).

Restored slices are the byte-exact Tables the original run produced, so
a resumed query's downstream computation — and therefore its result —
is byte-identical to an uninterrupted run.

Scope: the in-process data plane (workers exposing `table_store`).
A wire transport would stage checkpoint slices through a store RPC;
workers without the surface simply never checkpoint (save returns
None, resume falls back to full re-execution). The AdaptiveCoordinator
opts out entirely (`Coordinator._checkpoint_eligible`): its consumer
task counts derive from runtime LoadInfo, so a restored lattice could
disagree with a re-derived one.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from datafusion_distributed_tpu.runtime import leakcheck as _leakcheck

#: query-record lifecycle states
ADMITTED = "admitted"  # running (or interrupted mid-run): recoverable
RESUMED = "resumed"    # picked up by ServingSession.recover()
DONE = "done"          # resolved; slices released


@dataclass(frozen=True)
class StageCheckpoint:
    """One completed stage's snapshot: the consumer-side scan rebuilt
    verbatim on restore. Frozen — a record is immutable once saved (the
    cross-thread handoff relies on it, like SystemMetrics)."""

    exec_index: int          # which coordinator.execute() of the query
    stage_id: int
    fingerprint: str         # structural fp of the pristine exchange subtree
    #: (worker_url, table_id, nbytes) per consumer slice — the task lattice
    slices: tuple
    replicated: bool
    pinned: bool
    t_prod: int              # producer task count at save time
    membership_epoch: Optional[int]
    saved_s: float           # monotonic save stamp


class QueryRecord:
    """One admitted query's checkpoint state in the store."""

    __slots__ = ("record_id", "sql", "priority", "status", "stages",
                 "resumes")

    def __init__(self, sql: str, priority: int):
        self.record_id = uuid.uuid4().hex
        self.sql = sql
        self.priority = int(priority)
        self.status = ADMITTED
        #: (exec_index, stage_id) -> StageCheckpoint
        self.stages: dict = {}
        self.resumes = 0


class CheckpointStore:
    """Cross-session registry of admitted queries and their completed-
    stage snapshots. Deliberately decoupled from any ServingSession so it
    SURVIVES a session/coordinator teardown — construct one, pass it to
    session after session, and `ServingSession.recover()` resumes
    whatever the previous session left unresolved.

    Thread-safe: per-query coordinators save stages from stage-DAG
    fan-out threads while the serving tier admits/releases concurrently.
    Slice staging/fetching runs OUTSIDE the lock (worker TableStore calls
    block on their own locks); only record bookkeeping is held under it.

    Memory accounting: checkpoint slices stage through `TableStore.
    put_as` — the ACCOUNTED surface — so they count against each
    worker's staged bytes, enforced budget, and spill machinery like any
    other entry (before the budget work they were visible but uncapped
    demand). ``budget_bytes`` additionally caps the store's OWN total:
    past it, the oldest recoverable checkpoints evict (slices released,
    resume degrades to re-execution, `checkpoint_evicted_budget`
    counter) instead of growing unbounded; the just-saved checkpoint is
    protected so a single over-cap stage still makes progress.
    """

    def __init__(self, budget_bytes: int = 0) -> None:
        self._lock = threading.Lock()
        self._records: dict[str, QueryRecord] = {}  # guarded-by: _lock
        self.saves = 0  # guarded-by: _lock
        self.restores = 0  # guarded-by: _lock
        self.budget_bytes = max(int(budget_bytes or 0), 0)
        self.evicted_budget = 0  # guarded-by: _lock

    # -- query lifecycle -----------------------------------------------------
    def admit(self, sql: str, priority: int = 0) -> str:
        """Register an admitted query; -> its record id."""
        rec = QueryRecord(sql, priority)
        with self._lock:
            self._records[rec.record_id] = rec
        return rec.record_id

    def mark_resumed(self, record_id: str) -> None:
        with self._lock:
            rec = self._records.get(record_id)
            if rec is not None:
                rec.status = RESUMED
                rec.resumes += 1

    def incomplete(self) -> list:
        """Records a fresh session should recover: admitted (or already
        once-resumed) queries that never resolved. Snapshot list — the
        caller iterates without the lock."""
        with self._lock:
            return [
                r for r in self._records.values() if r.status != DONE
            ]

    def release(self, record_id: str, channels) -> int:  # releases: checkpoint-slice
        """The query resolved (or was cancelled): drop its record and
        release every staged checkpoint slice through ``channels``
        (departed workers already released theirs with their process);
        -> slices released. The zero-leak half of the acceptance gate."""
        with self._lock:
            rec = self._records.pop(record_id, None)
        if rec is None:
            return 0
        released = 0
        if _leakcheck.enabled():
            for sk in rec.stages:
                _leakcheck.note_release(
                    "checkpoint-slice", (record_id, sk[0], sk[1])
                )
        for ck in rec.stages.values():
            for url, tid, _nbytes in ck.slices:
                try:
                    store = getattr(channels.get_worker(url),
                                    "table_store", None)
                    if store is not None:
                        store.remove([tid])
                        released += 1
                except Exception:
                    pass  # departed worker: its store died with it
        return released

    # -- stage snapshots ------------------------------------------------------
    def save_stage(self, record_id: str, exec_index: int, stage_id: int,  # acquires: checkpoint-slice (managed)
                   fingerprint: str, tables, replicated: bool,
                   pinned: bool, t_prod: int, resolver,
                   channels) -> Optional[int]:
        """Stage ``tables`` (the consumer-side scan slices) into the live
        workers' TableStores, round-robin, and record the checkpoint;
        -> staged bytes, or None when the snapshot could not be taken
        (no store surface / a mid-save departure — never an error: a
        failed checkpoint degrades to re-execution, not a failed query).
        """
        from datafusion_distributed_tpu.runtime.tracing import table_nbytes

        try:
            urls = resolver.get_urls()
        except Exception:
            urls = []
        if not urls:
            return None
        staged: list = []  # (url, tid, nbytes)
        total = 0
        try:
            for i, t in enumerate(tables):
                url = urls[(stage_id + i) % len(urls)]
                store = getattr(channels.get_worker(url), "table_store",
                                None)
                if store is None or not hasattr(store, "put_as"):
                    raise LookupError("worker has no TableStore surface")
                tid = (
                    f"ckpt-{record_id[:8]}-{exec_index}-{stage_id}-{i}-"
                    f"{uuid.uuid4().hex[:8]}"
                )
                store.put_as(tid, t)
                nb = table_nbytes(t)
                staged.append((url, tid, nb))
                total += nb
        except Exception:
            # partial snapshot is worthless: release what staged and skip
            for url, tid, _nb in staged:
                try:
                    getattr(channels.get_worker(url), "table_store").remove(
                        [tid]
                    )
                except Exception:
                    pass
            return None
        ck = StageCheckpoint(
            exec_index=exec_index, stage_id=stage_id,
            fingerprint=fingerprint, slices=tuple(staged),
            replicated=bool(replicated), pinned=bool(pinned),
            t_prod=int(t_prod),
            membership_epoch=getattr(resolver, "membership_epoch", None),
            saved_s=time.monotonic(),
        )
        displaced = None
        with self._lock:
            rec = self._records.get(record_id)
            if rec is None:
                released = True  # query resolved while we staged
            else:
                # same-key re-save (two executors racing one record):
                # the displaced snapshot's slices must release or they
                # leak in the workers' stores for the process lifetime
                displaced = rec.stages.get((exec_index, stage_id))
                rec.stages[(exec_index, stage_id)] = ck
                self.saves += 1
                released = False
                if _leakcheck.enabled():
                    # recovery checkpoints INTENTIONALLY outlive the
                    # query (no query attribution): CheckpointStore
                    # release/_drop_stage are the release paths, so only
                    # assert_clean-style audits see a stuck slice
                    _leakcheck.note_acquire(
                        "checkpoint-slice",
                        (record_id, exec_index, stage_id),
                        tag="CheckpointStore.save_stage",
                    )
        if displaced is not None:
            for url, tid, _nb in displaced.slices:
                try:
                    getattr(channels.get_worker(url), "table_store").remove(
                        [tid]
                    )
                except Exception:
                    pass
        if released:
            for url, tid, _nb in staged:
                try:
                    getattr(channels.get_worker(url), "table_store").remove(
                        [tid]
                    )
                except Exception:
                    pass
            return None
        self._enforce_budget(channels, protect=(record_id,
                                                (exec_index, stage_id)))
        return total

    def _enforce_budget(self, channels, protect=None) -> None:
        """Evict the OLDEST recoverable checkpoints while the store's
        total staged bytes exceed ``budget_bytes`` (0 = uncapped).
        ``protect`` — (record_id, stage_key) of the just-saved
        checkpoint — is never evicted, so one over-cap stage still
        lands. Slice release runs outside the lock."""
        if not self.budget_bytes:
            return
        while True:
            evicted = None
            with self._lock:
                total = sum(
                    nb
                    for r in self._records.values()
                    for ck in r.stages.values()
                    for _u, _t, nb in ck.slices
                )
                if total <= self.budget_bytes:
                    return
                cands = [
                    (ck.saved_s, rid, key)
                    for rid, r in self._records.items()
                    for key, ck in r.stages.items()
                    if (rid, key) != protect
                ]
                if not cands:
                    return  # only the protected save remains: keep it
                _, rid, key = min(cands)
                evicted = self._records[rid].stages.pop(key)
                self.evicted_budget += 1
                if _leakcheck.enabled():
                    _leakcheck.note_release(
                        "checkpoint-slice", (rid, key[0], key[1])
                    )
            for url, tid, _nb in evicted.slices:
                try:
                    getattr(channels.get_worker(url), "table_store").remove(
                        [tid]
                    )
                except Exception:
                    pass  # departed worker: its slices died with it

    def restore_stage(self, record_id: str, exec_index: int,
                      stage_id: int, fingerprint: Optional[str],
                      channels):
        """-> (slices, replicated, pinned, t_prod) for a valid checkpoint
        of this stage, or (None, reason) where reason is one of
        "miss" / "fp_mismatch" / "slice_lost". Every slice is fetched
        from the worker recorded as holding it; a departed worker or an
        evicted id invalidates the checkpoint (and drops the record so
        the re-executed stage can save a fresh one)."""
        with self._lock:
            rec = self._records.get(record_id)
            ck = rec.stages.get((exec_index, stage_id)) if rec else None
        if ck is None:
            return None, "miss"
        if fingerprint is None or ck.fingerprint != fingerprint:
            self._drop_stage(record_id, exec_index, stage_id, channels)
            return None, "fp_mismatch"
        tables = []
        try:
            for url, tid, _nb in ck.slices:
                store = getattr(channels.get_worker(url), "table_store",
                                None)
                if store is None:
                    raise LookupError(f"no store on {url}")
                tables.append(store.get(tid))
        except Exception:
            self._drop_stage(record_id, exec_index, stage_id, channels)
            return None, "slice_lost"
        with self._lock:
            self.restores += 1
        return (tables, ck.replicated, ck.pinned, ck.t_prod), "hit"

    def _drop_stage(self, record_id: str, exec_index: int, stage_id: int,
                    channels) -> None:
        """Invalidate one stage's checkpoint (release surviving slices)."""
        with self._lock:
            rec = self._records.get(record_id)
            ck = (
                rec.stages.pop((exec_index, stage_id), None)
                if rec else None
            )
        if ck is None:
            return
        if _leakcheck.enabled():
            _leakcheck.note_release(
                "checkpoint-slice", (record_id, exec_index, stage_id)
            )
        for url, tid, _nb in ck.slices:
            try:
                getattr(channels.get_worker(url), "table_store").remove(
                    [tid]
                )
            except Exception:
                pass

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            recs = list(self._records.values())
            out = {
                "queries": len(recs),
                "recoverable": sum(1 for r in recs if r.status != DONE),
                "stages": sum(len(r.stages) for r in recs),
                "staged_bytes": sum(
                    nb
                    for r in recs
                    for ck in r.stages.values()
                    for _u, _t, nb in ck.slices
                ),
                "saves": self.saves,
                "restores": self.restores,
                "budget_bytes": self.budget_bytes,
                "checkpoint_evicted_budget": self.evicted_budget,
            }
        return out


def exchange_fingerprints(plan) -> dict:
    """{stage_id: fingerprint-or-None} over a plan's PRISTINE exchange
    subtrees, pre-hoist — literal values are structural, so two queries
    differing only in literals can never share a stage snapshot. Shared
    by `QueryCheckpointer.begin_execute` (intra-query checkpoint keys)
    and the cross-query sub-plan cache (runtime/result_cache.py), so
    the two tiers' keys can never drift."""
    from datafusion_distributed_tpu.plan.fingerprint import (
        plan_fingerprint,
    )

    fps: dict = {}
    try:
        exchanges = plan.collect(
            lambda n: getattr(n, "is_exchange", False)
        )
    except Exception:
        exchanges = []
    for node in exchanges:
        sid = node.stage_id if node.stage_id is not None else 0
        fps[sid] = plan_fingerprint(node)
    return fps


class QueryCheckpointer:
    """Per-query facade installed as `Coordinator.checkpoints`: binds one
    store record to one cluster and tracks the execute-call sequence so
    subquery and overflow-retry executes key their stages independently
    of the main execute (the sequence is deterministic for a given SQL,
    so a resume's Nth execute matches the original run's Nth).

    `begin_execute` runs on the driver thread before any stage fan-out;
    the per-execute fingerprint map is read-only afterwards, so
    save/restore from concurrent stage threads need no lock here (the
    store serializes record mutation itself)."""

    def __init__(self, store: CheckpointStore, record_id: str, resolver,
                 channels):
        self.store = store
        self.record_id = record_id
        self.resolver = resolver
        self.channels = channels
        self._exec_index = -1
        self._stage_fps: dict = {}

    def begin_execute(self, plan) -> None:
        """Stamp a new execute() and fingerprint its pristine exchange
        subtrees (pre-hoist — see `exchange_fingerprints`)."""
        self._exec_index += 1
        self._stage_fps = exchange_fingerprints(plan)

    def stage_fingerprint(self, stage_id: int) -> Optional[str]:
        return self._stage_fps.get(stage_id)

    def save(self, stage_id: int, tables, replicated: bool, pinned: bool,
             t_prod: int) -> Optional[int]:
        fp = self.stage_fingerprint(stage_id)
        if fp is None:
            return None  # unfingerprintable stage: not checkpointable
        return self.store.save_stage(
            self.record_id, self._exec_index, stage_id, fp, tables,
            replicated, pinned, t_prod, self.resolver, self.channels,
        )

    def restore(self, stage_id: int):
        """-> ((slices, replicated, pinned, t_prod), "hit") or
        (None, reason)."""
        return self.store.restore_stage(
            self.record_id, self._exec_index, stage_id,
            self.stage_fingerprint(stage_id), self.channels,
        )
