"""Meshes-as-workers: a Worker that owns a device mesh and runs a SPAN of
a stage's tasks as ONE SPMD program.

This composes the two tiers of SURVEY.md §2.10 ("same-mesh = collective,
off-mesh = host RPC") that previously never met: the host coordinator/worker
runtime (exchanges between workers) and the mesh executor (SPMD over a
device mesh). A stage with T tasks running over K mesh workers of width W
is dispatched as contiguous spans — worker k executes tasks
[kW, (k+1)W) by stacking the span's leaf slices over its mesh axis and
shard_mapping the stage pipeline: one XLA program per worker per stage
instead of W host-scheduled programs, with data staying in that mesh's
HBM. Between meshes the existing host planes (peer pulls / coordinator
streams) move bytes per-task, unchanged — the reference's whole L3+L7
topology (`/root/reference/src/worker/worker_service.rs:42-52`) with the
intra-worker parallelism swapped from a thread pool to a device mesh.

Stage plans contain no exchange nodes (exchanges end stages), so the
span program has no collectives — its parallelism is pure data-parallel
SPMD; any stray exchange raises loudly (no mesh_axis in the exec config).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from datafusion_distributed_tpu.ops.table import Table, concat_tables
from datafusion_distributed_tpu.plan.exchanges import IsolatedArmExec
from datafusion_distributed_tpu.plan.physical import (
    _PRECISION_TAG,
    DistributedTaskContext,
    ExecContext,
    ExecutionPlan,
    MemoryScanExec,
    ParquetScanExec,
)
from datafusion_distributed_tpu.runtime.worker import (
    TaskData,
    TaskKey,
    Worker,
)

AXIS = "span"

# same import as mesh_executor.py: the experimental entry point still
# accepts check_rep (the top-level jax.shard_map dropped it)
from jax.experimental.shard_map import shard_map as _shard_map


def span_specializable(plan: ExecutionPlan) -> bool:
    """Span dispatch covers the regular stage shapes; plans whose leaves
    depend on the GLOBAL task index in ways a local re-slice cannot express
    (isolated union arms, work-unit feeds) fall back to per-task dispatch."""
    from datafusion_distributed_tpu.runtime.work_unit_feed import (
        WorkUnitScanExec,
    )

    return not plan.collect(
        lambda n: isinstance(n, (IsolatedArmExec, WorkUnitScanExec))
    )


def span_specialized(plan: ExecutionPlan, lo: int, hi: int) -> ExecutionPlan:
    """Re-slice a stage plan's leaves to tasks [lo, hi), re-indexed to
    local positions 0..hi-lo (the mesh axis): the span analogue of
    `_task_specialized` (`query_coordinator.rs:346-382`)."""
    from datafusion_distributed_tpu.runtime.peer import PeerShuffleScanExec

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        if isinstance(node, PeerShuffleScanExec):
            if node.pinned_task is not None or node.pull_all:
                return node
            if node.replicated:
                # broadcast: wrap virtual-partition ids so a span wider
                # than the planned fan-out still pulls a FULL copy per
                # local task (an out-of-range local index would read an
                # empty build side and silently drop join matches)
                P_ = max(node.num_partitions, 1)
                pulls = [
                    node.pulls_per_task[(lo + i) % P_]
                    for i in range(hi - lo)
                ]
            else:
                pulls = node.pulls_per_task[lo:hi]
            return PeerShuffleScanExec(
                pulls, node.key_names, node.num_partitions,
                node.per_dest_capacity, node._schema, node.dictionaries,
                replicated=node.replicated, budget_bytes=node.budget_bytes,
                chunk_rows=node.chunk_rows,
                capacity_hint=node.capacity_hint,
            )
        if isinstance(node, MemoryScanExec) and not node.pinned and (
            not node.replicated
        ):
            sub = node.tasks[lo:hi]
            if not sub and node.tasks:
                # span entirely past this scan's slices (sibling feeds had
                # more): per-task dispatch would read empty via the
                # tasks[0] reference; give the span the same empty table
                from datafusion_distributed_tpu.plan.physical import (
                    _dicts_of,
                )

                ref = node.tasks[0]
                sub = [Table.empty(node.schema(), ref.capacity,
                                   _dicts_of(ref))]
            return MemoryScanExec(sub, node.schema())
        if isinstance(node, ParquetScanExec):
            return ParquetScanExec(
                node.file_groups[lo:hi], node.schema(), node.capacity,
                projection=node.projection, dictionaries=node.dictionaries,
            )
        children = [walk(c) for c in node.children()]
        return node.with_new_children(children) if children else node

    return walk(plan)


def execute_stage_span_on_mesh(
    plan: ExecutionPlan,
    mesh: Mesh,
    span_width: int,
    task_count: int,
    config: Optional[dict] = None,
) -> list[Table]:
    """Execute a span-specialized stage plan over ``mesh``: local task i of
    the span runs on device i; -> per-task output Tables. No collectives —
    out_specs stack the per-device outputs on the span axis.

    Compilation is NOT memoized across calls (unlike mesh_executor's
    _MESH_COMPILE_CACHE): every span plan arrives freshly decoded with new
    node ids AND query-specific leaves (peer pull keys carry the query id,
    memscan refs are per-shipment uuids), so a cache key would virtually
    never repeat; each span also executes exactly once per query. If a
    workload emerges that re-ships byte-identical span plans, key a cache
    on (plan_obj JSON hash, mesh devices, input shape/dict signature) at
    set_stage_plan and reuse the decoded plan object so jit's own cache
    hits."""
    leaves = plan.collect(lambda n: not n.children())
    stacked: dict = {}

    def _stack(*xs):
        # host-backed leaves (the zero-copy plane's peer pulls arrive as
        # numpy views) stack ON THE HOST: their buffers then enter the
        # device exactly once, at the device_put below, instead of paying
        # a per-slice H2D for the stack plus a D2H for the re-stage
        if all(isinstance(x, (np.ndarray, np.generic)) for x in xs):
            return np.stack(xs)
        return jnp.stack(xs)

    for leaf in leaves:
        if not hasattr(leaf, "load"):
            continue
        per_task = [
            leaf.load(DistributedTaskContext(i, task_count))
            for i in range(span_width)
        ]
        per_task = _repad_uniform(per_task)
        stacked[leaf.node_id] = jax.tree.map(_stack, *per_task)

    # Inputs pulled from OTHER meshes arrive committed to foreign devices
    # (the in-process bypass shares buffers); stage them onto THIS mesh
    # explicitly, through host — exactly the DCN hop a real multi-host
    # deployment pays here. Host-resident (numpy) buffers skip the
    # round-trip and enter via device_put directly (on CPU jax shares the
    # buffer through the dlpack/Arrow-layout import — see
    # ops.table.to_device for the column-level dlpack path).
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, P(AXIS))
    stacked = {
        nid: jax.tree.map(
            lambda x: jax.device_put(
                x if isinstance(x, np.ndarray) else np.asarray(x), sharding
            ), t
        )
        for nid, t in stacked.items()
    }

    overflow_names: list = []

    def run(inputs_stacked):
        local = {
            nid: jax.tree.map(lambda x: x[0], t)
            for nid, t in inputs_stacked.items()
        }
        ctx = ExecContext(
            task=DistributedTaskContext(0, task_count),
            inputs=local,
            config=dict(config or {}),
        )
        out = plan.execute(ctx)
        overflow_names.clear()
        overflow_names.extend(name for name, _ in ctx.overflow_flags)
        flags = (
            jnp.stack([f for _, f in ctx.overflow_flags])
            if ctx.overflow_flags else jnp.zeros((0,), jnp.bool_)
        )
        return (
            jax.tree.map(lambda x: x[None], out),
            flags[None, :],
        )

    in_specs = jax.tree.map(lambda _: P(AXIS), stacked)
    fn = _shard_map(
        run, mesh=mesh, in_specs=(in_specs,),
        out_specs=(P(AXIS), P(AXIS)), check_rep=False,
    )
    # multi-device executables cache fine (see the serialization note in
    # mesh_executor.py — the old disable-around-invocation workaround was
    # removed after re-verification)
    out_stacked, flags = jax.jit(fn)(stacked)
    flags = np.asarray(flags)  # [W, F]
    if flags.size:
        cap = [
            n for i, n in enumerate(overflow_names)
            if not n.startswith(_PRECISION_TAG) and bool(flags[:, i].any())
        ]
        prec = [
            n for i, n in enumerate(overflow_names)
            if n.startswith(_PRECISION_TAG) and bool(flags[:, i].any())
        ]
        if cap:
            raise RuntimeError(
                f"hash table overflow in span program (nodes: {cap}); "
                "re-plan with more slots"
            )
        if prec:
            raise RuntimeError(
                "int32 accumulator range exceeded in span program "
                f"(nodes: {prec}); run with DFTPU_PRECISION=x64"
            )
    return [
        jax.tree.map(lambda x: x[i], out_stacked) for i in range(span_width)
    ]


def _repad_uniform(tables: list[Table]) -> list[Table]:
    """Stacking requires identical shapes AND identical pytree structure/
    aux across the span's slices: same capacity (peer pulls concat to
    exact row counts, so capacities routinely differ by a few chunks),
    same Dictionary identity per string column (pulled slices carry their
    producers' dictionaries; empty fallbacks may carry none), and same
    validity presence."""
    from datafusion_distributed_tpu.ops.table import (
        Column,
        unify_dictionaries,
    )

    cap = max(int(t.capacity) for t in tables)
    tables = [
        t if int(t.capacity) == cap else concat_tables([t], capacity=cap)
        for t in tables
    ]
    names = tables[0].names
    ncols = len(names)
    new_cols: list[list] = [[None] * ncols for _ in tables]
    for ci in range(ncols):
        cols = [t.columns[ci] for t in tables]
        d, luts = unify_dictionaries([c.dictionary for c in cols])
        has_validity = any(c.validity is not None for c in cols)
        for ti, c in enumerate(cols):
            data = c.data
            lut = luts[ti]
            if lut is not None:
                if len(lut) == 0:
                    data = jnp.zeros_like(data)
                else:
                    data = jnp.asarray(lut)[
                        jnp.clip(data, 0, len(lut) - 1)
                    ]
            validity = c.validity
            if has_validity and validity is None:
                validity = jnp.ones(data.shape, dtype=jnp.bool_)
            new_cols[ti][ci] = Column(
                data, validity, c.dtype,
                d if d is not None else c.dictionary,
            )
    return [
        Table(names, tuple(new_cols[ti]), tables[ti].num_rows)
        for ti in range(len(tables))
    ]


@dataclass
class _SpanState:
    """Shared state of one shipped span: the plan runs ONCE on the mesh;
    every task key of the span serves its slot from the cached outputs."""

    plan: ExecutionPlan
    lo: int
    hi: int
    task_count: int
    outputs: Optional[list] = None
    lock: threading.Lock = field(default_factory=threading.Lock)
    config: dict = field(default_factory=dict)


class MeshWorker(Worker):
    """A Worker whose executor is a device mesh: spans of stage tasks run
    as one SPMD program (`execute_stage_span_on_mesh`); the per-task
    service surface (execute_task / partition streams / peer pulls) is
    inherited unchanged — consumers cannot tell a mesh worker from a
    thread-pool worker."""

    def __init__(self, url: str, devices, ttl_seconds: float = 600.0,
                 version: str = "0.1.0", peer_channels=None):
        super().__init__(url, ttl_seconds, version,
                         peer_channels=peer_channels)
        self.devices = list(devices)
        self.mesh = Mesh(np.asarray(self.devices), (AXIS,))
        self.mesh_width = len(self.devices)
        self._spans: dict = {}  # (query_id, stage_id, lo) -> _SpanState; per-query: bounded 16

    # -- control plane ------------------------------------------------------
    def set_stage_plan(self, query_id: str, stage_id: int, lo: int, hi: int,
                       task_count: int, plan_obj: dict,
                       config: Optional[dict] = None,
                       headers: Optional[dict] = None,
                       ttl: Optional[float] = None) -> None:
        """Ship ONE span-specialized plan covering tasks [lo, hi); registers
        a TaskData per task so the inherited data-plane surfaces work."""
        from datafusion_distributed_tpu.runtime.codec import (
            collect_table_ids,
            decode_plan,
        )
        from datafusion_distributed_tpu.runtime.errors import (
            wrap_worker_exception,
        )
        from datafusion_distributed_tpu.runtime.peer import (
            attach_peer_channels,
        )

        key0 = TaskKey(query_id, stage_id, lo)
        try:
            plan = decode_plan(plan_obj, self.table_store)
            # same post-decode integrity/verify gate as Worker.set_plan:
            # span programs are stage-shared BY CONSTRUCTION, so a
            # mis-decoded span plan is exactly the wrong-binding hazard
            from datafusion_distributed_tpu.runtime.worker import (
                _check_decoded_plan,
            )

            _check_decoded_plan(plan, plan_obj, self.url, key0,
                                config=config)
            if self.on_plan is not None:
                plan = self.on_plan(plan, key0)
        except Exception as e:
            raise wrap_worker_exception(e, self.url, key0) from e
        attach_peer_channels(plan, self.peer_channels, self)
        state = _SpanState(plan=plan, lo=lo, hi=hi, task_count=task_count,
                           config=dict(config or {}))
        # bounded retention: span outputs are device buffers; a long-lived
        # worker must not accumulate them past the active-query window
        # (task-level cleanup still runs through the registry as usual)
        while len(self._spans) >= 16:
            self._spans.pop(next(iter(self._spans)))
        self._spans[(query_id, stage_id, lo)] = state
        tids = collect_table_ids(plan_obj)
        for i in range(lo, hi):
            data = TaskData(
                key=TaskKey(query_id, stage_id, i), plan=plan,
                task_count=task_count, config=dict(config or {}),
                headers=dict(headers or {}),
                shipped_table_ids=tids if i == lo else [],
                ttl=ttl,
            )
            data.span = (state, i - lo)  # type: ignore[attr-defined]
            self.registry.put(data)

    # -- data plane ---------------------------------------------------------
    def execute_task(self, key: TaskKey) -> Table:
        data = self.registry.get(key)
        span = getattr(data, "span", None) if data is not None else None
        if span is None:
            return super().execute_task(key)
        state, local_idx = span
        import time as _time

        with state.lock:
            if state.outputs is None:
                data.executed_at = _time.time()
                # always run at full mesh width: a short span's trailing
                # devices load empty slices (the reference's short
                # coalesce groups yield empty streams the same way)
                state.outputs = execute_stage_span_on_mesh(
                    state.plan, self.mesh, self.mesh_width,
                    state.task_count, config=state.config,
                )
                data.finished_at = _time.time()
        out = state.outputs[local_idx]
        data.metrics.setdefault("rows_out", int(out.num_rows))
        data.metrics.setdefault("span", [state.lo, state.hi])
        return out


class InMemoryMeshCluster:
    """K mesh workers × W devices each over the process's device list —
    the meshes-as-workers test fixture: 2×4 on the virtual 8-device CPU
    mesh models two hosts each owning a 4-chip slice, with the host data
    plane (peer pulls) between them."""

    def __init__(self, num_workers: int, devices_per_worker: int,
                 devices=None, ttl_seconds: float = 600.0):
        devices = list(devices if devices is not None else jax.devices())
        need = num_workers * devices_per_worker
        if len(devices) < need:
            raise ValueError(
                f"{need} devices needed, {len(devices)} available"
            )
        self.workers = {}
        for k in range(num_workers):
            url = f"mesh://worker-{k}"
            self.workers[url] = MeshWorker(
                url,
                devices[k * devices_per_worker:(k + 1) * devices_per_worker],
                ttl_seconds=ttl_seconds,
            )
        for w in self.workers.values():
            w.peer_channels = self

    def get_urls(self):
        return list(self.workers.keys())

    def get_worker(self, url: str):
        return self.workers[url]
