"""Host-disk spill segment for the enforced worker memory budget.

DataFusion survives memory pressure through its `MemoryPool` + spilling
operators (SURVEY §L0): operators reserve bytes against a shared pool
and spill sorted runs / hash partitions to disk when a reservation
fails. The TPU host tier's analogue lives one level lower — the
TableStore is the single byte-accounted owner of every staged buffer
(PR 8), so enforcement and spill happen BY ENTRY: when a worker's
staged bytes exceed `distributed.worker_memory_budget_bytes`, the store
spills its coldest unreferenced owned entries into this segment and
refaults them transparently on `get`.

File format ("encode_table-framed"): one file per spilled entry —

    magic b"DFSP" | u32 version | u32 capacity | u64 payload length |
    Arrow IPC stream payload (runtime/codec.encode_table)

The capacity rides the frame so a refaulted Table rebuilds with the
EXACT padded capacity of the original (decode_table(capacity=...)):
capacities enter compiled-program shapes, so a refault must never
re-shape what it restores. Values round-trip byte-exactly through the
Arrow IPC payload, which is what keeps spill-engaged TPC-H runs
byte-identical to unconstrained runs.

Locking contract (tools/check_concurrency.py DFTPU205): `write_spill`
and `read_spill` are REGISTERED BLOCKING CALLS — file I/O on a spill
segment must never run under a store lock. The TableStore picks victims
under its lock, releases it, does the I/O here, then re-acquires to
swap the entry; the lint holds every caller to that shape.

Zero-leak contract: every `SpillSlot` is released exactly once (entry
release, refault completion, or a raced re-insert); `live_files()` /
`stats()["spill_files"]` must read 0 once a store is drained — the
chaos `kind="oom"` schedule's leak gate asserts it alongside the
staged-slice gate.
"""

from __future__ import annotations

import os
import struct
import tempfile
import threading
import uuid
from typing import Optional

from datafusion_distributed_tpu.runtime import leakcheck as _leakcheck

_MAGIC = b"DFSP"
_VERSION = 1
_HEADER = struct.Struct(">4sIIQ")  # magic, version, capacity, payload len


class SpillError(RuntimeError):
    """A spill write/read failed (disk full, torn frame, vanished file).
    Callers degrade: a failed WRITE leaves the entry resident (budget
    unenforced, never data loss); a failed READ of a live slot is a real
    error — the bytes exist nowhere else."""


class SpillSlot:
    """One spilled entry's on-disk location + restore metadata.

    ``dict_cols`` retains the original columns' `Dictionary` OBJECTS
    (host-side, small by design — only codes are device data): a
    refault remaps the decoded codes back into the ORIGINAL dictionary's
    code space and rebinds the original object. Two invariants depend on
    this: codes stay comparable across exchange boundaries with tables
    that never spilled (dictionary codes are only meaningful within one
    dict_id space), and the column's pytree aux — (dtype, dictionary) —
    is IDENTICAL pre/post spill, so a refault never forces an XLA
    retrace of the stage consuming it."""

    __slots__ = ("path", "nbytes", "file_bytes", "capacity", "released",
                 "dict_cols")

    def __init__(self, path: str, nbytes: int, file_bytes: int,
                 capacity: int, dict_cols: Optional[dict] = None):
        self.path = path
        self.nbytes = int(nbytes)       # logical (accounted) bytes
        self.file_bytes = int(file_bytes)
        self.capacity = int(capacity)
        self.released = False
        self.dict_cols = dict_cols or {}


def _rebind_dictionaries(table, dict_cols: dict):
    """Restore the ORIGINAL Dictionary objects on a refaulted table.

    The wire decode built fresh (GC'd, re-sorted) dictionaries with new
    dict_ids; left that way, a refaulted table's codes would live in a
    DIFFERENT code space from sibling tables that never spilled (silent
    wrong results on code-compared paths) and the new aux identity would
    force an XLA retrace per refault. Each decoded code is remapped
    through a values lookup table back into the original dictionary's
    code space; a value missing from the original dictionary (impossible
    for a faithful round trip) aborts the rebind for that column and
    keeps the decoded fallback — values stay correct either way."""
    if not dict_cols:
        return table
    import numpy as np

    from datafusion_distributed_tpu.ops.table import Column, Table

    new_cols = []
    changed = False
    for name, col in zip(table.names, table.columns):
        orig = dict_cols.get(name)
        decoded = getattr(col, "dictionary", None)
        if orig is None or decoded is None or decoded is orig:
            new_cols.append(col)
            continue
        index = orig.index()  # value -> original code
        lut = np.empty(len(decoded.values), dtype=np.int32)
        ok = True
        for i, v in enumerate(decoded.values):
            code = index.get(v)
            if code is None:
                ok = False
                break
            lut[i] = code
        if not ok:
            new_cols.append(col)
            continue
        codes = np.asarray(col.data)
        safe = np.clip(codes, 0, len(lut) - 1) if len(lut) else codes
        remapped = np.where(
            (codes >= 0) & (codes < len(lut)), lut[safe], codes
        ).astype(np.int32)
        import jax.numpy as jnp

        new_cols.append(Column(
            data=jnp.asarray(remapped), validity=col.validity,
            dtype=col.dtype, dictionary=orig,
        ))
        changed = True
    if not changed:
        return table
    return Table(table.names, tuple(new_cols), table.num_rows)


class SpillManager:
    """Owns one spill directory (lazily created under the system temp
    dir, or ``root`` when given) and its slot lifecycle. Thread-safe:
    concurrent spills/refaults from stage fan-out threads touch disjoint
    files; only the counters share the lock."""

    def __init__(self, root: Optional[str] = None):
        self._root = root
        self._dir: Optional[str] = None
        self._lock = threading.Lock()
        self._live: set = set()  # guarded-by: _lock
        self.spills = 0  # guarded-by: _lock
        self.spill_bytes = 0  # guarded-by: _lock
        self.refaults = 0  # guarded-by: _lock
        self.refault_bytes = 0  # guarded-by: _lock

    def _ensure_dir(self) -> str:
        with self._lock:
            if self._dir is None:
                self._dir = self._root or tempfile.mkdtemp(
                    prefix="dftpu-spill-"
                )
                os.makedirs(self._dir, exist_ok=True)
            return self._dir

    # -- blocking I/O entry points (never call under a store lock) ----------
    def write_spill(self, table, nbytes: int) -> SpillSlot:  # acquires: spill-slot
        """Encode ``table`` into a framed spill file; -> its slot.
        BLOCKING (disk write) — registered with the DFTPU205 lint."""
        from datafusion_distributed_tpu.runtime.codec import encode_table

        payload = encode_table(table)
        cap = int(getattr(table, "capacity", 0))
        path = os.path.join(self._ensure_dir(), f"{uuid.uuid4().hex}.spill")
        try:
            with open(path, "wb") as f:
                f.write(_HEADER.pack(_MAGIC, _VERSION, cap, len(payload)))
                f.write(payload)
        except OSError as e:
            try:
                os.unlink(path)
            except OSError:
                pass
            raise SpillError(f"spill write failed: {e}") from e
        dict_cols = {
            name: col.dictionary
            for name, col in zip(getattr(table, "names", ()),
                                 getattr(table, "columns", ()))
            if getattr(col, "dictionary", None) is not None
        }
        slot = SpillSlot(path, nbytes, _HEADER.size + len(payload), cap,
                         dict_cols=dict_cols)
        with self._lock:
            self._live.add(path)
            self.spills += 1
            self.spill_bytes += slot.nbytes
        if _leakcheck.enabled():
            _leakcheck.note_acquire("spill-slot", path,
                                    tag="SpillManager.write_spill")
        return slot

    def read_spill(self, slot: SpillSlot):
        """Decode a spilled entry back into a Table (original capacity
        preserved). BLOCKING (disk read) — registered with the DFTPU205
        lint. The slot stays live; the caller releases it once the
        refault is installed (a raced second reader must still be able
        to read)."""
        from datafusion_distributed_tpu.runtime.codec import decode_table

        try:
            with open(slot.path, "rb") as f:
                header = f.read(_HEADER.size)
                magic, version, cap, plen = _HEADER.unpack(header)
                if magic != _MAGIC or version != _VERSION:
                    raise SpillError(
                        f"bad spill frame header in {slot.path}"
                    )
                payload = f.read(plen)
                if len(payload) != plen:
                    raise SpillError(f"torn spill frame in {slot.path}")
        except OSError as e:
            raise SpillError(f"spill read failed: {e}") from e
        table = decode_table(payload, capacity=cap or None)
        table = _rebind_dictionaries(table, slot.dict_cols)
        with self._lock:
            self.refaults += 1
            self.refault_bytes += slot.nbytes
        return table

    # -- lifecycle -----------------------------------------------------------
    def release(self, slot: SpillSlot) -> None:  # releases: spill-slot
        """Unlink a slot's file (idempotent)."""
        if slot.released:
            return
        slot.released = True
        if _leakcheck.enabled():
            _leakcheck.note_release("spill-slot", slot.path)
        with self._lock:
            self._live.discard(slot.path)
        try:
            os.unlink(slot.path)
        except OSError:
            pass  # already gone (process restart sweep, test cleanup)

    def live_files(self) -> int:
        with self._lock:
            return len(self._live)

    def stats(self) -> dict:
        with self._lock:
            return {
                "spills": self.spills,
                "spill_bytes": self.spill_bytes,
                "refaults": self.refaults,
                "refault_bytes": self.refault_bytes,
                "spill_files": len(self._live),
            }
