"""Worker runtime: plan hosting, task registry, execution service.

The reference's worker (`/root/reference/src/worker/worker_service.rs`) is a
gRPC service holding a TTL cache of `TaskKey -> TaskData`, a per-query
session builder, plan hooks, and the ExecuteTask data plane. This is the
TPU-native equivalent for the host runtime tier: inside a mesh no worker
objects exist at all (the SPMD program IS the stage execution); workers come
into play across meshes/hosts, where each worker owns a device (or mesh) and
the coordinator moves stage outputs between them.

Transport-agnostic by design: `Worker` is plain Python called in-process
(the InMemoryChannelResolver analogue); `runtime/grpc_worker.py` wraps the
same object behind gRPC for multi-host deployments.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from datafusion_distributed_tpu.ops.table import Table
from datafusion_distributed_tpu.plan.physical import (
    DistributedTaskContext,
    ExecContext,
    ExecutionPlan,
)
from datafusion_distributed_tpu.runtime.codec import TableStore, decode_plan
from datafusion_distributed_tpu.runtime.errors import (
    TaskTimeoutError,
    WorkerError,
    wrap_worker_exception,
)


def call_with_deadline(fn, timeout: Optional[float], worker_url: str, task):
    """Run ``fn()`` under a wall-clock deadline: on expiry raise the
    retryable `TaskTimeoutError` and ABANDON the still-running call (a hung
    execution cannot be interrupted from Python; the coordinator's retry
    machinery reroutes the task meanwhile). A bare DAEMON thread, not a
    ThreadPoolExecutor: pool workers are non-daemon and joined at
    interpreter exit, so one truly hung task would wedge process shutdown —
    the exact failure mode deadlines exist to convert. ``timeout``
    None/<=0 calls inline."""
    if not timeout or timeout <= 0:
        return fn()
    import threading

    box: dict = {}
    done = threading.Event()

    def run() -> None:
        try:
            box["value"] = fn()
        except BaseException as e:  # re-raised in the caller below
            box["error"] = e
        finally:
            done.set()

    threading.Thread(target=run, daemon=True,
                     name="dftpu-deadline").start()
    if not done.wait(timeout):
        raise TaskTimeoutError(
            f"deadline of {timeout}s elapsed",
            worker_url=worker_url,
            task=task,
        )
    if "error" in box:
        raise box["error"]
    return box["value"]


@dataclass(frozen=True)
class TaskKey:
    """(query, stage, task) addressing — the reference's `TaskKey`
    (`worker.proto`)."""

    query_id: str
    stage_id: int
    task_number: int


def _check_decoded_plan(plan: ExecutionPlan, plan_obj: dict,
                        worker_url: str, key, config=None) -> None:
    """Post-decode verification (plan/verify.py wiring, worker side).

    1. Integrity: the decoded plan's structural fingerprint must match the
       fingerprint stamped at encode time (``plan_obj["_fp"]``,
       runtime/codec.py). The compiled-program caches key on this
       fingerprint — stage-shared programs especially — so a silently
       miscoded plan would bind another stage's compiled program to this
       task's inputs (the physical.py wrong-binding hazard). A mismatch is
       the classified fatal `PlanIntegrityError` (DFTPU043), never wrong
       results. Runs before any `on_plan` hook (hooks legitimately rewrite
       plans per task).
    2. Static verification: under ``verify_plans=strict`` (propagated via
       the coordinator's config options) the decoded stage plan re-runs the
       schema/capacity passes — a defense against version-skewed decoders
       reconstructing a structurally broken tree.
    """
    from datafusion_distributed_tpu.plan.verify import (
        PlanVerificationError,
        resolve_verify_mode,
        verify_physical_plan,
    )

    mode = resolve_verify_mode(config)
    if mode == "off":
        return
    wire_fp = plan_obj.get("_fp")
    if wire_fp is not None:
        from datafusion_distributed_tpu.plan.fingerprint import prepare_plan
        from datafusion_distributed_tpu.runtime.errors import (
            PlanIntegrityError,
        )

        got = prepare_plan(plan).fingerprint
        if got is not None and got != wire_fp:
            raise PlanIntegrityError(
                f"DFTPU043: decoded plan fingerprint {got} does not match "
                f"the wire fingerprint {wire_fp} — the plan was corrupted "
                "in transit or mis-decoded; executing it could bind a "
                "fingerprint-keyed compiled program to wrong inputs",
                worker_url=worker_url, task=key,
            )
    if mode == "strict":
        result = verify_physical_plan(plan, include_cache_audit=False)
        if not result.ok:
            raise PlanVerificationError(result, context=f"worker {worker_url} post-decode")


@dataclass
class TaskData:
    """Per-task state (the reference's `task_data.rs`): the decoded plan plus
    temporal metrics for observability."""

    key: TaskKey
    plan: ExecutionPlan
    task_count: int
    plan_added_at: float = field(default_factory=time.time)
    executed_at: Optional[float] = None
    finished_at: Optional[float] = None
    metrics: dict = field(default_factory=dict)
    # coordinator-propagated session config (config-over-headers analogue,
    # `config_extension_ext.rs:1-82`) and verbatim user headers
    # (`passthrough_headers.rs`)
    config: dict = field(default_factory=dict)
    headers: dict = field(default_factory=dict)
    # partition-range data plane state (the reference's per-task partition
    # accounting, `impl_execute_task.rs:97-112` / `task_data.rs`): the
    # task's output partitioned once per (keys, P) spec, a served set (a
    # retried range must not double-decrement), and a remaining count —
    # the entry self-invalidates when every partition was served. `lock`
    # serializes build/accounting across concurrent range streams.
    partition_spec: Optional[tuple] = None
    partition_slices: Optional[list] = None
    partitions_remaining: Optional[int] = None
    partitions_served: set = field(default_factory=set)
    lock: threading.Lock = field(default_factory=threading.Lock)
    # shipment-store ids this task's plan references: released whenever the
    # registry entry dies (drop-driven cleanup OR TTL eviction), so a
    # cancelled/errored partition stream cannot leak TableStore entries on
    # a long-lived worker (ADVICE r4)
    shipped_table_ids: list = field(default_factory=list)
    # store ids of the STAGED partition slices (zero-copy accounting of
    # the peer partition plane): released with the entry like shipped ids,
    # and replaced wholesale when the partition spec changes (a re-spec
    # must not pin the previous regrouped buffer)
    staged_partition_ids: list = field(default_factory=list)
    # per-entry idle TTL override (None = the registry default). Peer-plane
    # producers ship at plan time but are first PULLED when their consumer
    # stage finally runs — on a deep plan under load that gap exceeded the
    # 600 s default and the entry evicted mid-query ("no plan for task").
    ttl: Optional[float] = None


RESERVED_HEADER_PREFIX = "x-dftpu-"

#: The ONLY config keys a traced program reads through
#: `ExecContext.config` (physical.py `collect_metrics`, exchanges.py
#: `mesh_axis` — tests/test_stage_scheduler.py pins the inventory by AST
#: scan). The stage-compile shared key keeps exactly these: everything
#: else in `SET distributed.*` is coordinator-side plumbing (scheduling,
#: fault tolerance, planning) that rides along in the shipped config, and
#: flipping it — stage_parallelism, peer_shuffle, a retry budget — must
#: NOT force an XLA recompile of structurally identical stages. An
#: allow-list closes the class, not just the known knobs; any NEW
#: `ExecContext.config` read in traced code must add its key here.
TRACE_RELEVANT_CONFIG_KEYS = frozenset({
    "mesh_axis",
    "collect_metrics",
})

#: each key's READ-SITE default: the shared key normalizes by dropping
#: entries equal to it, so a config that ships the default explicitly
#: hashes identically to one that omits the key (no spurious recompile
#: between two coordinators that spell the same effective config
#: differently)
_TRACE_RELEVANT_DEFAULTS = {
    "mesh_axis": None,        # plan/exchanges.py ctx.config.get("mesh_axis")
    "collect_metrics": True,  # plan/physical.py .get("collect_metrics", True)
}


def validate_passthrough_headers(headers: dict) -> None:
    """User headers must not collide with the engine's reserved prefix
    (the reference rejects `x-datafusion-distributed-*` the same way)."""
    for k in headers:
        if k.lower().startswith(RESERVED_HEADER_PREFIX):
            raise ValueError(
                f"passthrough header {k!r} uses the reserved prefix "
                f"{RESERVED_HEADER_PREFIX!r}"
            )


class TaskRegistry:
    """TTL cache of TaskData (the moka TTI cache, `worker_service.rs:26,39`:
    entries idle longer than `ttl_seconds` are evicted so abandoned queries
    cannot leak plans/buffers)."""

    def __init__(self, ttl_seconds: float = 600.0,
                 on_evict: Optional[Callable[[TaskData], None]] = None):
        self.ttl = ttl_seconds
        self._entries: dict[TaskKey, tuple[float, TaskData]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        # fired (outside hot paths, under the registry lock) for EVERY entry
        # leaving the registry — invalidate, TTL expiry, or sweep — so owners
        # can release per-task resources (the worker's shipped table slices)
        self.on_evict = on_evict

    def put(self, data: TaskData) -> None:
        with self._lock:
            self._evict_locked()
            # replacement evicts the displaced entry (releases its shipped
            # slices — table ids are unique per encode, so the new entry's
            # slices are untouched): a re-ship of the same key (retry to
            # the same worker, peer-producer refresh after membership
            # churn) must not strand the old attempt's slices, and callers
            # must NOT pre-invalidate — that would open a window where a
            # concurrent pull sees "no plan" for a key that is merely
            # being replaced
            old = self._entries.get(data.key)
            self._entries[data.key] = (time.time(), data)
            if old is not None:
                self._fire_evict(old[1])

    def get(self, key: TaskKey) -> Optional[TaskData]:
        with self._lock:
            self._evict_locked()
            hit = self._entries.get(key)
            if hit is None:
                return None
            ts, data = hit
            if time.time() - ts > (
                data.ttl if data.ttl is not None else self.ttl
            ):
                del self._entries[key]
                self._fire_evict(data)
                return None
            self._entries[key] = (time.time(), data)  # touch (TTI semantics)
            return data

    def invalidate(self, key: TaskKey) -> None:
        with self._lock:
            hit = self._entries.pop(key, None)
            if hit is not None:
                self._fire_evict(hit[1])

    def clear(self) -> None:
        """Evict EVERY entry (firing on_evict for each — shipped slices
        are released), as a dying worker process would: DynamicCluster's
        abrupt-leave path uses this so leak accounting across membership
        churn stays exact."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
            for _, data in entries:
                self._fire_evict(data)

    def _evict_locked(self) -> None:
        # DFTPU201/203 fix: caller holds `_lock` (the *_locked-suffix
        # convention the concurrency lint enforces; the old name implied
        # a self-locking method)
        now = time.time()
        dead = [
            k for k, (ts, d) in self._entries.items()
            if now - ts > (d.ttl if d.ttl is not None else self.ttl)
        ]
        for k in dead:
            _, data = self._entries.pop(k)
            self._fire_evict(data)

    def _fire_evict(self, data: TaskData) -> None:
        if self.on_evict is not None:
            try:
                self.on_evict(data)
            except Exception:
                pass  # cleanup must never poison the registry paths

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class Worker:
    """One worker = one executor endpoint.

    API mirrors the reference service surface (`worker_service.rs`):
      set_plan     <- CoordinatorChannel SetPlanRequest
      execute_task <- ExecuteTask
      get_info     <- GetWorkerInfo (version checks for rolling upgrades)
    """

    def __init__(
        self,
        url: str = "mem://worker",
        ttl_seconds: float = 600.0,
        version: str = "0.1.0",
        on_plan: Optional[Callable[[ExecutionPlan, TaskKey], ExecutionPlan]] = None,
        peer_channels=None,
    ):
        self.url = url
        self.version = version
        self.registry = TaskRegistry(
            ttl_seconds,
            on_evict=self._on_task_evict,
        )
        self.on_plan = on_plan
        self.table_store = TableStore()
        # per-worker typed metric registry (runtime/telemetry.py): the
        # `get_metrics` RPC serves its snapshot, and the observability
        # service merges per-worker snapshots (worker=url label) into
        # the cluster view. Collector adapters sample the table store's
        # existing accounting at snapshot time — no hot-path overhead.
        from datafusion_distributed_tpu.runtime.telemetry import (
            MetricRegistry,
        )

        self.telemetry = MetricRegistry()
        self.telemetry.register_collector(
            self.table_store.telemetry_families
        )
        self.telemetry.gauge(
            "dftpu_worker_tasks_cached",
            "Task registry entries currently held.",
        ).set_function(lambda: len(self.registry))
        self._tm_tasks = self.telemetry.counter(
            "dftpu_worker_tasks_executed",
            "Task executions by outcome.", labels=("status",),
        )
        self._tm_rows = self.telemetry.counter(
            "dftpu_worker_rows_out", "Rows produced by task executions.",
        )
        self._tm_exec = self.telemetry.histogram(
            "dftpu_worker_execute_seconds",
            "Per-task execute wall seconds (host-side, around the "
            "compiled program).",
        )
        # ChannelResolver-like (get_worker(url)) used by the peer-to-peer
        # data plane to open streams to producer workers (the reference's
        # consumer-side WorkerConnectionPool, `worker_connection_pool.rs`)
        self.peer_channels = peer_channels
        # co-located segment pool (runtime/shm_plane.py): the streaming
        # transfer RPC publishes chunk payloads here when the consumer
        # is classified same-host; cheap to build — no directory exists
        # until the first publish
        from datafusion_distributed_tpu.runtime.shm_plane import (
            SegmentPool,
        )

        self.segment_pool = SegmentPool()
        # final progress of partition-range tasks, retained past their
        # drop-driven invalidation (consumed once by task_progress)
        self._final_progress: dict[TaskKey, Optional[dict]] = {}
        # keys whose set_plan attempt was abandoned by a dispatch deadline:
        # the still-running decode thread must not register an orphan
        # entry (pinning decoded tables until the TTL sweep) after the
        # coordinator rerouted — see set_plan's timeout path
        self._abandoned_lock = threading.Lock()
        self._abandoned_plans: set = set()  # guarded-by: _abandoned_lock

    # stage-shared compiled programs (slot key -> (last_touch, execute_plan
    # shared cache)): every task of a stage decodes its own plan copy, but
    # the traced program is task-invariant (padded capacities make shapes
    # uniform; task identity only selects host-side leaf data), so one
    # compile serves all tasks — the single biggest host-tier cost at
    # scale was N_tasks identical XLA compiles per stage. Slots are keyed
    # by the stage plan's STRUCTURAL FINGERPRINT (plan/fingerprint.py), so
    # repeated queries — and literal-hoisted template variants — reuse the
    # stage program ACROSS queries; plans without a fingerprint fall back
    # to a per-query slot. CLASS-level on purpose: co-hosted workers
    # (InMemoryCluster, one process) then pay one compile per stage
    # instead of one per worker; separate worker processes are unaffected.
    # Retention is time/count-based, NOT registry-driven: the coordinator
    # invalidates each task entry right after it executes, so "no registry
    # entries for this query" happens transiently MID-query and must not
    # destroy the cache (review r5). A slot is dropped _STAGE_COMPILE_TTL_S
    # after CREATION — absolute age, not idle time: a compiled program's
    # closure pins its creator task's decoded plan (incl. shipped tables),
    # and a HOT template would otherwise refresh an idle-TTL forever and
    # pin the very first submission's tables for the template's lifetime.
    # Expiry of a hot slot just costs one recompile per TTL window. The
    # LRU cap bounds retention in count on busy workers (it counts
    # per-STAGE slots now, hence larger than the old per-query cap of 8);
    # dict order still tracks recency-of-USE so eviction takes cold slots
    # first.
    _stage_compiles: dict = {}  # guarded-by: _stage_compiles_lock
    _stage_compiles_lock = threading.Lock()
    _STAGE_COMPILE_SLOT_CAP = 64
    _STAGE_COMPILE_TTL_S = 600.0

    def _on_task_evict(self, data: TaskData) -> None:
        """Registry-exit hook (invalidate, TTL expiry, sweep): release the
        task's shipped table slices and its staged partition slices."""
        self.table_store.remove(data.shipped_table_ids)
        self.table_store.remove(data.staged_partition_ids)

    @classmethod
    def _sweep_stage_compiles_locked(cls, now: float) -> None:
        """Drop slots older than the TTL (absolute age since creation —
        see the class comment). Caller holds `_stage_compiles_lock`."""
        dead = [
            q for q, (ts, _) in cls._stage_compiles.items()
            if now - ts > cls._STAGE_COMPILE_TTL_S
        ]
        for q in dead:
            del cls._stage_compiles[q]

    def _stage_compile_cache(self, key: TaskKey, data: TaskData):
        """(shared_cache, shared_key) for execute_plan, or (None, None) when
        stage-sharing is unsafe: IsolatedArmExec bakes `task_index` into the
        traced program (plan/exchanges.py assigned_task branch), a user
        `on_plan` hook may rewrite plans per-task, and a CUSTOM plan node
        (register_codec extension path) may read ``ctx.task.task_index``
        inside ``_execute`` — undetectable from here, so any node class
        outside this package disables sharing unless it declares
        ``stage_shareable = True`` (meaning: its trace does not depend on
        task identity).

        Known limitation, not a safety issue: over the gRPC transport each
        task's decode mints fresh ``Dictionary`` objects (pytree aux,
        identity by dict_id), so string-bearing stages fragment the key and
        miss; the in-process transport resolves shipped table ids to the
        SAME store-held tables, where sharing fully engages."""
        import os

        if os.environ.get("DFTPU_STAGE_SHARE", "1") == "0":
            return None, None
        if self.on_plan is not None:
            return None, None

        def _unshareable(n) -> bool:
            if getattr(n, "assigned_task", None) is not None:
                return True
            mod = type(n).__module__
            return not (
                mod == "datafusion_distributed_tpu"
                or mod.startswith("datafusion_distributed_tpu.")
            ) and not getattr(n, "stage_shareable", False)

        if data.plan.collect(_unshareable):
            return None, None
        from datafusion_distributed_tpu.plan.fingerprint import prepare_plan

        # fingerprint-keyed slot: identical stage structures — re-submitted
        # queries, literal-only template variants — share one compiled
        # program across queries; an unfingerprintable plan degrades to the
        # old per-query slot (sharing only among its own tasks). The
        # fingerprint also rides the shared program key inside execute_plan,
        # so two stages that merely COLLIDE on (query, stage id) — e.g. a
        # coordinator reusing ids after a replan — miss instead of binding
        # each other's inputs.
        prep = prepare_plan(data.plan)
        if prep.fingerprint is not None:
            slot = ("fp", prep.fingerprint)
            stage_identity = prep.fingerprint
        else:
            slot = ("q", key.query_id)
            stage_identity = (key.query_id, key.stage_id)
        now = time.time()
        with self._stage_compiles_lock:
            self._sweep_stage_compiles_locked(now)
            hit = self._stage_compiles.pop(slot, None)
            if hit is not None:
                created, cache = hit
            else:
                while len(self._stage_compiles) >= self._STAGE_COMPILE_SLOT_CAP:
                    self._stage_compiles.pop(
                        next(iter(self._stage_compiles))
                    )
                created, cache = now, {}
            # re-insert at the end: pop+insert keeps dict order = use
            # recency (for LRU eviction) while the stored timestamp stays
            # the CREATION time (for the absolute-age TTL)
            self._stage_compiles[slot] = (created, cache)
        shared_key = (
            stage_identity,
            data.task_count,
            tuple(sorted(
                (k, v) for k, v in (data.config or {}).items()
                if k in TRACE_RELEVANT_CONFIG_KEYS
                and v != _TRACE_RELEVANT_DEFAULTS[k]
            )),
        )
        return cache, shared_key

    # -- control plane ------------------------------------------------------
    def set_plan(self, key: TaskKey, plan_obj: dict, task_count: int,
                 config: Optional[dict] = None,
                 headers: Optional[dict] = None,
                 ttl: Optional[float] = None,
                 timeout: Optional[float] = None) -> None:
        """``timeout``: dispatch deadline — a hung decode converts into a
        retryable TaskTimeoutError instead of wedging the dispatcher. An
        abandoned decode is tombstoned so it cannot register an orphan
        entry after the coordinator rerouted (a residual race window
        degrades to the registry's TTL sweep, never to a permanent leak)."""
        if timeout:
            with self._abandoned_lock:
                # a NEW attempt for this key supersedes a stale tombstone
                self._abandoned_plans.discard(key)
            try:
                return call_with_deadline(
                    lambda: self.set_plan(key, plan_obj, task_count,
                                          config=config, headers=headers,
                                          ttl=ttl),
                    timeout, self.url, key,
                )
            except TaskTimeoutError:
                with self._abandoned_lock:
                    self._abandoned_plans.add(key)
                    while len(self._abandoned_plans) > 512:
                        self._abandoned_plans.pop()
                # the abandoned decode may have registered just before the
                # tombstone landed; eviction releases its shipped slices
                self.registry.invalidate(key)
                raise
        if headers:
            validate_passthrough_headers(headers)
        # enforced worker memory budget: the knob rides the task config
        # (`SET distributed.worker_memory_budget_bytes`) — apply it to
        # THIS worker's store before decode stages anything, so wire
        # workers enforce the same budget the coordinator's in-process
        # push covers locally. Not trace-relevant: never a compile key.
        if config and "worker_memory_budget_bytes" in config:
            try:
                self.table_store.set_budget(
                    config["worker_memory_budget_bytes"]
                )
            except Exception:
                pass
        # idle-worker retention bound: stage-compile slots pin decoded
        # plans (incl. store-held device tables); access-driven TTL alone
        # never fires on a worker that stops executing, so sweep on the
        # control-plane entry too
        with self._stage_compiles_lock:
            self._sweep_stage_compiles_locked(time.time())
        # cross-wire trace context (runtime/tracing.py): when the
        # coordinator ships one, worker-side phases record spans as plain
        # dicts that ride the task-progress payload back and splice into
        # the query trace under the propagated parent. Host-side only —
        # nothing trace-related may enter a jax-traced function
        # (DFTPU109) or a compile-cache key (execute strips it).
        tctx = (config or {}).get("trace_ctx")
        decode_t0 = time.monotonic() if tctx else 0.0
        try:
            plan = decode_plan(plan_obj, self.table_store)
            _check_decoded_plan(plan, plan_obj, self.url, key,
                                config=config)
            if self.on_plan is not None:
                plan = self.on_plan(plan, key)
        except Exception as e:  # structured propagation to the coordinator
            raise wrap_worker_exception(e, self.url, key) from e
        wire_spans = None
        if tctx:
            from datafusion_distributed_tpu.runtime.tracing import (
                worker_span,
            )

            wire_spans = [worker_span(
                "worker_decode", "codec", decode_t0, time.monotonic(),
                tctx.get("parent"), worker=self.url,
            )]
        from datafusion_distributed_tpu.runtime.codec import collect_table_ids
        from datafusion_distributed_tpu.runtime.peer import (
            attach_peer_channels,
        )

        attach_peer_channels(plan, self.peer_channels, self)
        with self._abandoned_lock:
            if key in self._abandoned_plans:
                # this decode ran past its dispatch deadline; the
                # coordinator already rerouted — registering now would
                # orphan the entry until the TTL sweep
                self._abandoned_plans.discard(key)
                self.table_store.remove(collect_table_ids(plan_obj))
                return
        self.registry.put(TaskData(
            key=key, plan=plan, task_count=task_count,
            config=dict(config or {}), headers=dict(headers or {}),
            metrics={"spans": wire_spans} if wire_spans else {},
            shipped_table_ids=collect_table_ids(plan_obj),
            ttl=ttl,
        ))

    # -- data plane ---------------------------------------------------------
    def execute_task(self, key: TaskKey,
                     timeout: Optional[float] = None) -> Table:
        """``timeout``: execution deadline (seconds). On expiry the attempt
        is abandoned and the retryable TaskTimeoutError surfaces — the
        fault-tolerant coordinator reroutes the task to another worker."""
        if timeout:
            return call_with_deadline(
                lambda: self._execute_task_body(key), timeout, self.url, key
            )
        return self._execute_task_body(key)

    def _execute_task_body(self, key: TaskKey) -> Table:
        data = self.registry.get(key)
        if data is None:
            raise WorkerError(
                f"no plan for task {key} (expired or never set)",
                worker_url=self.url,
                task=key,
            )
        data.executed_at = time.time()
        tctx = (data.config or {}).get("trace_ctx")
        exec_t0 = time.monotonic() if tctx else 0.0
        traces_before = 0
        if tctx:
            from datafusion_distributed_tpu.plan import physical as _phys

            traces_before = _phys.trace_count()
        try:
            from datafusion_distributed_tpu.plan.physical import execute_plan
            from datafusion_distributed_tpu.runtime.metrics import MetricsStore

            store = MetricsStore()
            shared_cache, shared_key = self._stage_compile_cache(key, data)
            # the wire trace context must NOT reach ExecContext.config or
            # any compile-cache key: span ids differ per task, and keying
            # a program on them would force one XLA trace per task
            # (plan/physical.py filters it from cfg_items as a second
            # line of defense)
            exec_config = {
                k: v for k, v in (data.config or {}).items()
                if k != "trace_ctx"
            }
            out = execute_plan(
                data.plan,
                DistributedTaskContext(key.task_number, data.task_count),
                config=exec_config or None,
                metrics_store=store,
                task_label=f"task{key.task_number}",
                use_cache=False,  # freshly decoded plans never hit the cache
                shared_cache=shared_cache,
                shared_key=shared_key,
            )
            data.metrics["nodes"] = store.per_task.get(
                f"task{key.task_number}", {}
            )
        except WorkerError:
            self._tm_tasks.inc(status="error")
            raise
        except Exception as e:
            self._tm_tasks.inc(status="error")
            raise wrap_worker_exception(e, self.url, key) from e
        data.finished_at = time.time()
        data.metrics["rows_out"] = int(out.num_rows)
        data.metrics["elapsed_s"] = data.finished_at - data.executed_at
        # telemetry (host-side, after the compiled program returned —
        # never inside traced code, DFTPU110)
        self._tm_tasks.inc(status="ok")
        self._tm_rows.inc(data.metrics["rows_out"])
        self._tm_exec.observe(data.metrics["elapsed_s"])
        if tctx:
            from datafusion_distributed_tpu.plan import physical as _phys
            from datafusion_distributed_tpu.runtime.tracing import (
                worker_span,
            )

            # compile-cache attribution: new_traces > 0 means this
            # execute paid a fresh XLA trace (a stage-compile cache miss);
            # 0 means it reused a shared program (hit)
            data.metrics.setdefault("spans", []).append(worker_span(
                "worker_execute", "execute", exec_t0, time.monotonic(),
                tctx.get("parent"), worker=self.url,
                rows=data.metrics["rows_out"],
                new_traces=_phys.trace_count() - traces_before,
            ))
        return out

    def execute_task_stream(self, key: TaskKey, chunk_rows: int = 65536,
                            cancel=None):
        """Streaming data plane: execute once, then yield the output as
        (chunk Table, est_bytes) row-slices. A set ``cancel`` event stops
        slicing — un-yielded rows never cross the wire (the reference's
        dropped-stream early exit, `impl_execute_task.rs:97-112`).

        Zero-copy plane (default): the output is rebound to host buffers
        ONCE and every chunk is a view of it — no per-chunk device slice
        copies (`SET distributed.zero_copy = off` restores the copying
        slicer)."""
        from datafusion_distributed_tpu.ops.table import (
            host_view,
            slice_view,
            zero_copy_enabled,
        )
        from datafusion_distributed_tpu.planner.statistics import row_width

        data = self.registry.get(key)
        zc = zero_copy_enabled(data.config if data is not None else None)
        out = self.execute_task(key)
        if zc:
            out = host_view(out)
        n = int(out.num_rows)
        width = row_width(out.schema())
        if n == 0:
            yield out.slice_rows(0, 0), 0
            return
        for lo in range(0, n, max(chunk_rows, 1)):
            if cancel is not None and cancel.is_set():
                return
            count = min(chunk_rows, n - lo)
            yield (
                slice_view(out, lo, count) if zc
                else out.slice_rows(lo, count)
            ), count * width

    def execute_task_partitions(
        self,
        key: TaskKey,
        key_names,
        num_partitions: int,
        part_lo: int,
        part_hi: int,
        per_dest_capacity: int = 0,
        chunk_rows: int = 65536,
        cancel=None,
    ):
        """Partition-range data plane: one stream carries partitions
        [part_lo, part_hi) of this task's hash-partitioned output, each
        chunk tagged with its partition id — the reference's multiplexed
        ExecuteTask stream (`worker_connection_pool.rs:243-308` demuxes the
        same shape into per-partition channels). The output is executed and
        partitioned ONCE per (keys, P) spec and cached on the TaskData;
        `partitions_remaining` decrements per served partition and the
        registry entry self-invalidates at zero (the drop-driven accounting
        of `impl_execute_task.rs:97-112`).

        Yields (partition_id, chunk Table, est_bytes).
        """
        from datafusion_distributed_tpu.planner.statistics import row_width

        data = self.registry.get(key)
        if data is None:
            raise WorkerError(
                f"no plan for task {key} (expired or never set)",
                worker_url=self.url,
                task=key,
            )
        spec = (tuple(key_names), int(num_partitions))
        with data.lock:
            if data.partition_slices is None or data.partition_spec != spec:
                from datafusion_distributed_tpu.ops.table import (
                    host_view,
                    zero_copy_enabled,
                )

                zc = zero_copy_enabled(data.config)
                out = self.execute_task(key)
                if zc:
                    # rebind to host buffers ONCE (free on CPU, the one
                    # unavoidable D2H elsewhere); all partition slices and
                    # chunk yields below are views of this buffer
                    out = host_view(out)
                if not key_names:
                    # replicate mode (peer broadcast / gather): the FULL
                    # output serves under every virtual partition id — the
                    # reference's NetworkBroadcastExec virtual-partition
                    # scheme (`broadcast.rs:30-69`); entries are references,
                    # not copies, and the per-partition drop accounting
                    # self-invalidates after the last consumer pulled
                    data.partition_slices = [out] * num_partitions
                else:
                    # same hash as the in-mesh shuffle kernel, so codes
                    # co-locate across tiers (function-level import:
                    # runtime/coordinator.py imports this module at top
                    # level)
                    from datafusion_distributed_tpu.runtime.coordinator import (  # noqa: E501
                        _shuffle_regroup,
                    )

                    cap = per_dest_capacity or max(int(out.capacity), 8)
                    data.partition_slices = _shuffle_regroup(
                        [out], key_names, num_partitions, cap,
                        zero_copy=zc, exact=zc,
                    )
                data.partition_spec = spec
                data.partitions_served = set()
                data.partitions_remaining = num_partitions
                # staged-byte accounting on EITHER plane (the copying
                # plane's padded slices are real allocations too); on the
                # view plane these are views/aliases of one buffer
                self._stage_partition_slices(key, data)
            # a concurrent stream finishing its range must not yank the
            # slices out from under this one: hold our own reference
            slices = data.partition_slices
        from datafusion_distributed_tpu.ops.table import (
            is_host_backed,
            slice_view,
        )

        try:
            for p in range(part_lo, min(part_hi, num_partitions)):
                piece = slices[p]
                n = int(piece.num_rows)
                width = row_width(piece.schema())
                view = is_host_backed(piece)
                if n == 0:
                    yield p, piece.slice_rows(0, 0), 0
                else:
                    for lo in range(0, n, max(chunk_rows, 1)):
                        if cancel is not None and cancel.is_set():
                            return
                        count = min(chunk_rows, n - lo)
                        yield p, (
                            slice_view(piece, lo, count) if view
                            else piece.slice_rows(lo, count)
                        ), count * width
                with data.lock:
                    if p not in data.partitions_served:
                        data.partitions_served.add(p)
                        data.partitions_remaining -= 1
        finally:
            with data.lock:
                done = data.partitions_remaining is not None and (
                    data.partitions_remaining <= 0
                )
            # Replicate mode (empty key_names: peer broadcast/gather) must
            # NOT self-invalidate on the last distinct partition — a
            # consumer stage forced wider than the planned fan-out re-pulls
            # a virtual partition id (modulo wrap), and racing that pull
            # against the drop-invalidation fails it with "no plan".
            # Broadcast producers are released by the coordinator's
            # query-end sweep instead (the reference keeps its broadcast
            # batch cache for the query lifetime the same way,
            # `broadcast.rs:71-98`).
            # The same retention applies to any producer shipped with a
            # per-entry TTL override (data.ttl — peer-plane producers, which
            # the coordinator's query-end sweep owns): a consumer whose load
            # succeeded against THIS producer but failed against a departed
            # sibling retries its whole pull set, and the re-pull of an
            # already-fully-served partition must serve from the cached
            # slices instead of dying with a fatal "no plan" (elastic
            # membership: partial-success loads are routine under churn).
            if done and key_names and data.ttl is None:
                # metrics fire on last drop (impl_execute_task.rs:97-112):
                # retain the final progress past the invalidation so the
                # consumer's post-stream progress read still sees it
                self._stash_final_progress(key)
                self.registry.invalidate(key)

    def _stage_partition_slices(self, key: TaskKey, data: TaskData) -> None:
        """Register the partitioned output's slices in the table store so
        the worker's staged-byte accounting covers the peer data plane
        (before this, partition slices lived only on the TaskData —
        invisible to `nbytes`/observability). Slices are views of ONE
        regrouped buffer (or the same replicated output object), so
        identity dedup/view registration counts the buffer once. Released
        by the registry-exit hook like shipped slices; a racing eviction
        (query-end sweep vs a late pull) is healed by the re-check."""
        if data.staged_partition_ids:
            # re-partition under a NEW (keys, P) spec: the previous
            # regrouped buffer's ids must not stay pinned/double-counted
            self.table_store.remove(data.staged_partition_ids)
        from datafusion_distributed_tpu.runtime.codec import (
            staging_attribution,
        )

        with staging_attribution(key.query_id):
            staged = [
                self.table_store.put(s) for s in data.partition_slices
            ]
        data.staged_partition_ids = staged
        if self.registry.get(key) is not data:
            # evicted while we staged: nobody will fire the exit hook for
            # these ids anymore — release them here (idempotent)
            self.table_store.remove(staged)
            data.staged_partition_ids = []

    def transfer_partitions(
        self,
        key: TaskKey,
        key_names,
        num_partitions: int,
        part_lo: int,
        part_hi: int,
        per_dest_capacity: int = 0,
        chunk_rows: int = 65536,
        cancel=None,
        wire_compression: str = "auto",
        shm=None,
    ):
        """In-process face of the streaming `TransferPartitions` RPC
        (grpc_worker.py): same partition-chunk sequence as
        `execute_task_partitions` — the planes' byte-identity contract.
        ``wire_compression``/``shm`` are accepted for surface parity and
        ignored: an in-process hop ships references, zero wire bytes."""
        yield from self.execute_task_partitions(
            key, key_names, num_partitions, part_lo, part_hi,
            per_dest_capacity=per_dest_capacity, chunk_rows=chunk_rows,
            cancel=cancel,
        )

    def partitions_remaining(self, key: TaskKey) -> Optional[int]:
        data = self.registry.get(key)
        return None if data is None else data.partitions_remaining

    def release_task(self, key: TaskKey) -> None:
        """Query-end release of a task that may never have been pulled
        (failed query / unpulled virtual partitions); registry eviction
        frees its shipped table slices."""
        self.registry.invalidate(key)

    def _stash_final_progress(self, key: TaskKey) -> None:
        """Bounded retention (a worker serving many queries must not grow
        this forever when nobody reads the final progress back)."""
        if len(self._final_progress) > 256:
            self._final_progress.pop(next(iter(self._final_progress)))
        self._final_progress[key] = self.task_progress(key)

    # -- observability ------------------------------------------------------
    @property
    def peer_capable(self) -> bool:
        """Whether this worker can open streams to peers (the peer data
        plane needs a channel resolver wired at construction)."""
        return self.peer_channels is not None

    def get_info(self) -> dict:
        from datafusion_distributed_tpu.runtime import transport

        return {"url": self.url, "version": self.version,
                "tasks_cached": len(self.registry),
                "peer_capable": self.peer_capable,
                # wire codecs this process can decode: clients intersect
                # with their own before choosing a connection codec (the
                # per-connection negotiation surface)
                "wire_codecs": transport.supported_codecs(),
                # shm data-plane accounting (runtime/shm_plane.py)
                "shm": self.segment_pool.stats(),
                # staged-byte accounting (zero-copy data plane): actual
                # staged bytes/entries/views + peak, per worker — the
                # observability service's data-plane surface
                "store": self.table_store.stats()}

    def get_metrics(self) -> dict:
        """This worker's typed-registry snapshot (runtime/telemetry.py
        wire format) — the `get_metrics` RPC body on both transports;
        `ObservabilityService.get_metrics()` merges per-worker snapshots
        under a worker=url label."""
        return self.telemetry.snapshot()

    def task_progress(self, key: TaskKey) -> Optional[dict]:
        data = self.registry.get(key)
        if data is None:
            return self._final_progress.pop(key, None)
        return {
            "plan_added_at": data.plan_added_at,
            "executed_at": data.executed_at,
            "finished_at": data.finished_at,
            **data.metrics,
        }
