"""Worker health tracking: consecutive-failure circuit breaker with
half-open recovery probes.

The reference schedules around unreachable workers at the connection-pool
layer (`worker_connection_pool.rs` marks broken channels); scheduling-aware
systems treat tolerating slow/failing participants as a first-class
scheduler concern (Chasing Similarity, arXiv:1810.00511). Here the
coordinator's router consults this tracker on every dispatch: a worker that
keeps failing is QUARANTINED (circuit open) so tasks flow to healthy peers;
after a cool-down the circuit goes HALF-OPEN and the next dispatch acts as a
recovery probe — success closes the circuit, another failure re-opens it
with an escalated cool-down.

Deliberately transport-agnostic and clock-injectable (deterministic tests).
Thread-safe: stage fan-out records failures from concurrent task threads.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


@dataclass
class HealthPolicy:
    #: consecutive failures that trip the breaker (quarantine the worker)
    failure_threshold: int = 3
    #: first quarantine duration; escalates by ``backoff_factor`` per
    #: consecutive trip (a worker that fails its recovery probe waits longer)
    quarantine_seconds: float = 30.0
    backoff_factor: float = 2.0
    max_quarantine_seconds: float = 300.0


@dataclass
class _WorkerState:
    state: str = CLOSED
    consecutive_failures: int = 0
    #: consecutive breaker trips (resets on a successful probe)
    trips: int = 0
    open_until: float = 0.0
    #: half-open: when the outstanding probe's admission expires — until
    #: then further dispatches are refused (ONE probe, not a stampede)
    probe_until: float = 0.0
    total_failures: int = 0
    total_successes: int = 0
    #: hedge losses (speculative peer finished first) — observability
    #: only, NEVER breaker input: slow is not broken
    hedge_losses: int = 0


class HealthTracker:
    """Per-worker circuit breakers keyed by url."""

    def __init__(self, policy: Optional[HealthPolicy] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy or HealthPolicy()
        self._clock = clock
        self._lock = threading.Lock()
        self._workers: dict[str, _WorkerState] = {}  # guarded-by: _lock

    def _state_locked(self, url: str) -> _WorkerState:
        # DFTPU201 fix (naming): caller holds `_lock` — the *_locked
        # suffix is the convention the concurrency lint enforces for
        # helpers that mutate guarded state on the caller's lock
        s = self._workers.get(url)
        if s is None:
            s = self._workers[url] = _WorkerState()
        return s

    def record_success(self, url: str) -> None:
        with self._lock:
            s = self._state_locked(url)
            s.total_successes += 1
            s.consecutive_failures = 0
            s.trips = 0
            s.state = CLOSED

    def record_hedge_loss(self, url: str) -> None:
        """The worker lost a hedge race (its attempt was outpaced by a
        speculative re-dispatch). Distinct from `record_failure` by
        design: a hedge loss NEVER advances `consecutive_failures` or
        trips the breaker — a slow-but-correct worker must stay routable
        (hedging exists to route around it per task), and quarantining
        on slowness would amplify one straggler into lost capacity."""
        with self._lock:
            s = self._state_locked(url)
            s.hedge_losses += 1

    def record_failure(self, url: str) -> bool:
        """-> True when this failure TRIPPED the breaker (closed/half-open ->
        open); the caller counts quarantine events off that edge."""
        with self._lock:
            s = self._state_locked(url)
            s.total_failures += 1
            s.consecutive_failures += 1
            if s.state == HALF_OPEN:
                # failed recovery probe: straight back to open, longer
                tripped = self._open(s)
                return tripped
            if (
                s.state == CLOSED
                and s.consecutive_failures >= self.policy.failure_threshold
            ):
                return self._open(s)
            return False

    def _open(self, s: _WorkerState) -> bool:
        s.trips += 1
        dur = min(
            self.policy.quarantine_seconds
            * (self.policy.backoff_factor ** (s.trips - 1)),
            self.policy.max_quarantine_seconds,
        )
        s.state = OPEN
        s.open_until = self._clock() + dur
        return True

    def is_available(self, url: str) -> bool:
        """Whether the router may send work to ``url`` now. An expired
        quarantine flips the breaker to half-open and admits the dispatch
        as the recovery probe — ONE probe at a time: while the probe is
        outstanding further dispatches are refused, so a stage fan-out
        landing right after expiry cannot stampede a still-dead worker.
        A probe that never resolves (its task died without a recorded
        outcome) re-admits after another quarantine period."""
        with self._lock:
            s = self._workers.get(url)
            if s is None or s.state == CLOSED:
                return True
            now = self._clock()
            if s.state == OPEN:
                if now >= s.open_until:
                    s.state = HALF_OPEN
                    s.probe_until = now + self.policy.quarantine_seconds
                    return True
                return False
            # HALF_OPEN: the admitted probe is still in flight
            if now >= s.probe_until:
                s.probe_until = now + self.policy.quarantine_seconds
                return True
            return False

    def route_filter(self, urls) -> list[str]:
        """Candidate urls for ONE dispatch. Unlike `healthy`, a probe
        admission PINS the dispatch to the probing worker (returns only
        it): admitting a probe from a candidate listing and then routing
        the task elsewhere would consume the probe slot without ever
        resolving it, leaving a recovered worker routed-around for extra
        quarantine periods."""
        with self._lock:
            now = self._clock()
            avail = []
            for u in urls:
                s = self._workers.get(u)
                if s is None or s.state == CLOSED:
                    avail.append(u)
                    continue
                if s.state == OPEN and now >= s.open_until:
                    s.state = HALF_OPEN
                    s.probe_until = now + self.policy.quarantine_seconds
                    return [u]  # this dispatch IS the recovery probe
                if s.state == HALF_OPEN and now >= s.probe_until:
                    # the admitted probe never resolved: re-admit one
                    s.probe_until = now + self.policy.quarantine_seconds
                    return [u]
            return avail

    def forget(self, url: str) -> bool:
        """Drop all breaker state for ``url`` (the worker left the
        membership — quarantine/backoff state for a nonexistent endpoint
        is dead weight, and a rejoining worker under the same url starts
        with a clean slate). -> whether state existed."""
        with self._lock:
            return self._workers.pop(url, None) is not None

    def prune(self, live_urls) -> list[str]:
        """Forget every tracked worker NOT in ``live_urls`` — called by the
        coordinator on membership change so the per-worker maps track the
        cluster instead of growing monotonically across churn. -> the urls
        dropped."""
        live = set(live_urls)
        with self._lock:
            dead = [u for u in self._workers if u not in live]
            for u in dead:
                del self._workers[u]
            return dead

    def state_of(self, url: str) -> str:
        with self._lock:
            s = self._workers.get(url)
            return CLOSED if s is None else s.state

    def telemetry_families(self) -> list:
        """Typed-registry adapter (runtime/telemetry.py): worker counts
        by breaker state plus per-worker success/failure/hedge-loss
        totals (labeled by url — bounded by cluster size, and pruned
        with the membership like the breaker state itself)."""
        from datafusion_distributed_tpu.runtime.telemetry import family

        snap = self.snapshot()
        by_state = {CLOSED: 0, OPEN: 0, HALF_OPEN: 0}
        for s in snap.values():
            by_state[s["state"]] = by_state.get(s["state"], 0) + 1
        fams = [family(
            "dftpu_health_workers", "gauge",
            "Tracked workers by circuit-breaker state.",
            [({"state": k}, v) for k, v in sorted(by_state.items())],
        )]
        for key, metric, help_text in (
            ("total_successes", "dftpu_health_successes",
             "Successful dispatch outcomes per worker."),
            ("total_failures", "dftpu_health_failures",
             "Failed dispatch outcomes per worker."),
            ("hedge_losses", "dftpu_health_hedge_losses",
             "Hedge races lost per worker (never breaker input)."),
        ):
            samples = [
                ({"url": url}, s[key]) for url, s in sorted(snap.items())
            ]
            if samples:
                fams.append(family(metric, "counter", help_text, samples))
        return fams

    def snapshot(self) -> dict:
        """url -> breaker state, for observability surfaces."""
        with self._lock:
            now = self._clock()
            return {
                url: {
                    "state": s.state,
                    "consecutive_failures": s.consecutive_failures,
                    "trips": s.trips,
                    "open_for_s": max(s.open_until - now, 0.0)
                    if s.state == OPEN else 0.0,
                    "total_failures": s.total_failures,
                    "total_successes": s.total_successes,
                    "hedge_losses": s.hedge_losses,
                }
                for url, s in self._workers.items()
            }
