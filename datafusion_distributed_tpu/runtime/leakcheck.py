"""Opt-in runtime resource-leak harness (``DFTPU_LEAK_CHECK=1``).

The static half of the resource model lives in
tools/check_resource_lifecycle.py: declared acquire/release lifecycles
(``# acquires: <kind>`` / ``# releases: <kind>``), path-sensitive
DFTPU301–307 discipline rules, and per-query growth annotations. This
module is the dynamic half — the instrumented witness that the declared
model matches reality under the suite's seeded chaos/churn/hedging
schedules:

- ``install()`` (called from the package ``__init__`` when
  ``DFTPU_LEAK_CHECK=1``, mirroring lockcheck) arms cheap explicit
  hooks embedded at every tracked acquisition/release point:
  TableStore entry insert/release (kind ``store-entry``, attributed to
  the owning query), SpillManager slot create/release (``spill-slot``),
  shm segment-pool token create/drop (``shm-segment``), PartitionFeed
  puller thread start/exit (``stream-puller``), and CheckpointStore
  stage save/drop (``checkpoint-slice``). When the harness is not
  installed every hook is a two-instruction no-op.
- every live resource keeps its creation-site tag: kind, key, owning
  query id (when the acquiring surface runs under
  ``staging_attribution``/a task key), and the acquisition stack.
- ``sweep_query(qid)`` — called from ``Coordinator.sweep_query`` at
  query end — flags every still-live resource attributed to that query
  as a leak: counted into ``dftpu_leaked_resources{kind}`` telemetry,
  recorded with its acquisition stack, and (under
  ``DFTPU_LEAK_CHECK=strict``) raised as `ResourceLeakError`.
- ``assert_clean()`` is the test-facing gate: zero live tracked
  resources (catalog tables and other process-lifetime entries are
  acquired OUTSIDE the harness's attribution and excluded via
  ``exclude_unattributed=True`` where a test only cares about
  query-scoped state).
- ``report()`` / the ``DFTPU_LEAK_CHECK_ARTIFACT=<path>`` atexit dump
  merge the observed live/leaked sets with the DECLARED static model
  (loaded from tools/check_resource_lifecycle.py when available), the
  same merged-artifact shape lockcheck uses for lock edges.

Zero-dependency on purpose: stdlib only, so the package ``__init__``
can install it before any other submodule import.
"""

from __future__ import annotations

import atexit
import os
import threading
import traceback
import _thread

__all__ = [
    "ResourceLeakError",
    "assert_clean",
    "enabled",
    "install",
    "leaks",
    "live",
    "note_acquire",
    "note_release",
    "note_transfer",
    "report",
    "reset",
    "strict",
    "sweep_query",
]

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_DIR)

_STACK_LIMIT = 14
_MAX_LEAK_RECORDS = 200

_installed = False
_strict = False
#: raw lock (never instrumented — the lock harness wraps package locks,
#: and the leak harness must not recurse into it)
_lock = _thread.allocate_lock()
_live: dict = {}  # (kind, key) -> record dict
_leaks: list = []  # flagged survivor records (bounded)
_counts: dict = {}  # kind -> acquired/released/leaked totals
_unmatched_releases = 0
_seq = 0


class ResourceLeakError(RuntimeError):
    """Tracked resources survived query end under strict mode; carries
    the survivor records (kind, key, query id, acquisition stack)."""

    def __init__(self, message: str, records: list):
        super().__init__(message)
        self.records = records


def enabled() -> bool:
    return _installed


def strict() -> bool:
    return _strict


def install() -> None:
    """Arm the harness (idempotent). ``DFTPU_LEAK_CHECK=strict`` makes
    query-end survivors raise instead of only being counted."""
    global _installed, _strict
    if _installed:
        return
    _installed = True
    _strict = os.environ.get("DFTPU_LEAK_CHECK", "").lower() == "strict"
    artifact = os.environ.get("DFTPU_LEAK_CHECK_ARTIFACT")
    if artifact:
        atexit.register(_dump_artifact, artifact)


def reset() -> None:
    """Drop all tracked state (tests)."""
    global _unmatched_releases
    with _lock:
        _live.clear()
        del _leaks[:]
        _counts.clear()
        _unmatched_releases = 0


def _stack() -> list:
    # drop the two harness frames (note_acquire + _stack)
    return traceback.format_list(
        traceback.extract_stack(limit=_STACK_LIMIT)[:-2]
    )


def _bump(kind: str, field: str, n: int = 1) -> None:
    c = _counts.setdefault(
        kind, {"acquired": 0, "released": 0, "leaked": 0}
    )
    c[field] += n


def note_acquire(kind: str, key, query_id=None, tag=None) -> None:
    """A tracked resource came alive. ``key`` must be hashable and
    unique among live resources of ``kind``; ``query_id`` attributes it
    to a query sweep; ``tag`` is a free-form creation-site label."""
    if not _installed:
        return
    rec = {
        "kind": kind,
        "key": key,
        "query_id": query_id,
        "tag": tag,
        "thread": threading.current_thread().name,
        "stack": _stack(),
    }
    with _lock:
        global _seq
        _seq += 1
        rec["seq"] = _seq
        _live[(kind, key)] = rec
        _bump(kind, "acquired")


def note_release(kind: str, key) -> None:
    """A tracked resource was released (idempotent: unmatched releases
    are counted, not errors — release paths are deliberately
    re-entrant)."""
    if not _installed:
        return
    global _unmatched_releases
    with _lock:
        if _live.pop((kind, key), None) is None:
            _unmatched_releases += 1
        else:
            _bump(kind, "released")


def note_transfer(kind: str, key, query_id=None) -> None:
    """Ownership moved (e.g. a handle was parked in a structure owned by
    another query, or detached to process lifetime with
    ``query_id=None``): re-attribute without re-stacking."""
    if not _installed:
        return
    with _lock:
        rec = _live.get((kind, key))
        if rec is not None:
            rec["query_id"] = query_id


def sweep_query(query_id) -> list:
    """Query end: every live resource attributed to ``query_id`` is a
    leak. -> the flagged records (also kept in ``leaks()``, counted into
    ``dftpu_leaked_resources{kind}``; raises under strict mode)."""
    if not _installed or query_id is None:
        return []
    with _lock:
        flagged = [
            rec for (kind, key), rec in _live.items()
            if rec.get("query_id") == query_id
        ]
        for rec in flagged:
            _live.pop((rec["kind"], rec["key"]), None)
            rec["leaked_at"] = f"sweep_query({query_id})"
            _bump(rec["kind"], "leaked")
            if len(_leaks) < _MAX_LEAK_RECORDS:
                _leaks.append(rec)
    if flagged:
        _emit_telemetry(flagged)
        if _strict:
            raise ResourceLeakError(
                f"{len(flagged)} resource(s) survived query end for "
                f"query {query_id}: "
                + ", ".join(
                    f"{r['kind']}:{r['key']!r}" for r in flagged[:5]
                ),
                flagged,
            )
    return flagged


def _emit_telemetry(flagged: list) -> None:
    """Best-effort ``dftpu_leaked_resources{kind}`` counters + a
    structured event — leak OBSERVABILITY must never fail the query."""
    per_kind: dict = {}
    for rec in flagged:
        per_kind[rec["kind"]] = per_kind.get(rec["kind"], 0) + 1
    try:
        from datafusion_distributed_tpu.runtime.telemetry import (
            DEFAULT_REGISTRY,
        )

        c = DEFAULT_REGISTRY.counter(
            "dftpu_leaked_resources",
            "Tracked resources still live when their owning query ended "
            "(DFTPU_LEAK_CHECK harness).",
            labels=("kind",),
        )
        for kind, n in per_kind.items():
            c.inc(n, kind=kind)
    except Exception:
        pass
    try:
        from datafusion_distributed_tpu.runtime.eventlog import log_event

        log_event("resources_leaked", **per_kind)
    except Exception:
        pass


def live(query_id=None, kind=None) -> list:
    """Snapshot of live tracked resources, optionally filtered."""
    with _lock:
        return [
            dict(rec) for rec in _live.values()
            if (query_id is None or rec.get("query_id") == query_id)
            and (kind is None or rec["kind"] == kind)
        ]


def leaks() -> list:
    """Records flagged by past sweeps (bounded)."""
    with _lock:
        return [dict(r) for r in _leaks]


def assert_clean(exclude_unattributed: bool = False) -> None:
    """Raise `ResourceLeakError` if any tracked resource is live (the
    test-facing zero-leak gate). ``exclude_unattributed=True`` ignores
    process-lifetime resources acquired without a query attribution
    (catalog tables, recovery checkpoints)."""
    with _lock:
        survivors = [
            dict(rec) for rec in _live.values()
            if not (exclude_unattributed and rec.get("query_id") is None)
        ]
    if survivors:
        lines = [
            f"  {r['kind']}:{r['key']!r} (query={r['query_id']}, "
            f"tag={r['tag']})"
            for r in survivors[:10]
        ]
        raise ResourceLeakError(
            f"{len(survivors)} tracked resource(s) still live:\n"
            + "\n".join(lines),
            survivors,
        )


def _static_model():
    """The DECLARED model from tools/check_resource_lifecycle.py, or
    None outside a repo checkout — same importlib-spec loading seam
    lockcheck uses for the static lock graph."""
    path = os.path.join(_REPO_ROOT, "tools",
                        "check_resource_lifecycle.py")
    if not os.path.exists(path):
        return None
    try:
        import importlib.util
        import sys

        spec = importlib.util.spec_from_file_location(
            "_dftpu_resource_lint", path
        )
        mod = importlib.util.module_from_spec(spec)
        # dataclass creation inside the tool resolves its defining
        # module through sys.modules — register before exec
        sys.modules["_dftpu_resource_lint"] = mod
        try:
            spec.loader.exec_module(mod)
            return mod.declared_model_json()
        finally:
            sys.modules.pop("_dftpu_resource_lint", None)
    except Exception:
        return None


def report(include_static: bool = True) -> dict:
    """Merged observed-vs-declared view: live resources, flagged leaks,
    per-kind totals, and the static model's declared lifecycles."""
    with _lock:
        out = {
            "installed": _installed,
            "strict": _strict,
            "live": [dict(r) for r in _live.values()],
            "leaks": [dict(r) for r in _leaks],
            "counts": {k: dict(v) for k, v in _counts.items()},
            "unmatched_releases": _unmatched_releases,
        }
    if include_static:
        out["declared_model"] = _static_model()
    return out


def _dump_artifact(path: str) -> None:
    import json

    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(report(), f, indent=2)
    except OSError:
        pass  # artifact write must never fail the exiting process
