"""Cross-process shared-memory segment pool for the co-located data plane.

The reference exchanges Arrow batches between workers over Arrow Flight
even when producer and consumer share a host; Zerrow (PAPERS.md) shows
the shape this module implements instead: co-located processes exchange
buffers BY REFERENCE through named shared-memory segments, so a
same-host hop costs one encode + one mmap read instead of
encode -> gRPC frame -> decode with the payload on the wire.

Segments are plain files under a tmpfs directory (``/dev/shm`` when the
platform has one — file-backed mmap there never touches disk), framed
EXACTLY like PR 15's spill files (runtime/spill.py):

    magic b"DFSP" | u32 version | u32 capacity | u64 payload length |
    Arrow IPC stream payload (runtime/codec.encode_table)

Sharing the frame is the composition contract: a spilled entry IS a
valid segment, so `publish_file` serves a spill file by hardlink
without a decode/re-encode round trip, and a consumer refaults either
through the same `decode_table(capacity=...)` path.

Cross-process refcounts live on the filesystem, not in any process:
each segment ``<name>.seg`` has a sidecar ``<name>.refs/`` directory
holding one token file per outstanding reference. `publish` creates the
segment with one token (transferred to the consumer inside the S-frame
of the transfer stream); `acquire` adds a token for an additional
reader; `release` unlinks a specific token and, at zero tokens, unlinks
the segment — whichever process drops the last reference reclaims it,
exactly the TableStore's refcounted-release discipline one level down.

Failure classification: a torn/vanished segment raises `SegmentError`.
Consumers DEGRADE on it — the transfer client marks the shm plane
broken for that connection and re-pulls over the wire path — so a lost
segment costs a retry, never a wrong result or a failed query.

Locking contract (tools/check_concurrency.py): the pool lock guards
only the in-process counters/bookkeeping; `publish` / `publish_file` /
`open_segment` are REGISTERED BLOCKING CALLS (DFTPU205) — segment I/O
never runs under the pool lock (the spill-manager shape: decide locked,
do I/O unlocked, account locked).
"""

from __future__ import annotations

import os
import socket
import tempfile
import threading
import uuid
from struct import error as _struct_error
from typing import Optional

from datafusion_distributed_tpu.runtime import leakcheck as _leakcheck

from datafusion_distributed_tpu.runtime.spill import (
    _HEADER,
    _MAGIC,
    _VERSION,
)

#: env override for the pool root (tests point it at a tmpdir; a
#: deployment without /dev/shm points it at any shared tmpfs)
SHM_DIR_ENV = "DFTPU_SHM_DIR"


class SegmentError(RuntimeError):
    """A segment is torn, missing, or unreadable. Consumers degrade to
    the wire path (retryable), never fail the query on it."""


def _default_root() -> str:
    root = os.environ.get(SHM_DIR_ENV)
    if root:
        return root
    if os.path.isdir("/dev/shm"):
        return "/dev/shm"
    return tempfile.gettempdir()


# -- directory-addressed segment access (consumer side) ----------------------
# A consumer reads segments out of the PRODUCER's pool directory (the
# S-frame carries {dir, seg, token}), so the read/refcount half works on
# any (dir, name) pair — no pool instance required on the reading side.


def open_segment_at(d: str, name: str) -> tuple[bytes, int]:
    """Read the segment ``name`` in pool directory ``d``; -> (Arrow IPC
    payload, capacity). Raises `SegmentError` on a missing or torn
    segment — the consumer's degrade-to-wire signal. BLOCKING (tmpfs
    read); never call under a lock."""
    path = os.path.join(d, f"{name}.seg")
    try:
        with open(path, "rb") as f:
            head = f.read(_HEADER.size)
            if len(head) != _HEADER.size:
                raise SegmentError(f"torn segment header {name}")
            magic, version, cap, plen = _HEADER.unpack(head)
            if magic != _MAGIC or version != _VERSION:
                raise SegmentError(f"bad segment frame {name}")
            payload = f.read(plen)
            if len(payload) != plen:
                raise SegmentError(f"torn segment payload {name}")
    except OSError as e:
        raise SegmentError(f"segment {name} unreadable: {e}") from e
    return payload, cap


def acquire_at(d: str, name: str) -> str:  # acquires: shm-segment
    """Add a reference to a live segment (broadcast fan-out); -> the new
    token. Only valid while an existing reference is held."""
    if not os.path.exists(os.path.join(d, f"{name}.seg")):
        raise SegmentError(f"segment {name} is gone")
    token = uuid.uuid4().hex
    refs = os.path.join(d, f"{name}.refs")
    os.makedirs(refs, exist_ok=True)
    with open(os.path.join(refs, token), "wb"):
        pass
    if _leakcheck.enabled():
        _leakcheck.note_acquire("shm-segment", (name, token),
                                tag="acquire_at")
    return token


def release_at(d: str, name: str, token: str) -> None:  # releases: shm-segment
    """Drop one reference; the LAST release unlinks the segment.
    Idempotent per token and safe on an already-torn segment (the
    `segment_lost` degradation path releases what it failed to read)."""
    if _leakcheck.enabled():
        _leakcheck.note_release("shm-segment", (name, token))
    refs = os.path.join(d, f"{name}.refs")
    try:
        os.unlink(os.path.join(refs, token))
    except OSError:
        pass  # token already dropped (double release)
    try:
        remaining = os.listdir(refs)
    except OSError:
        remaining = None  # refs dir already reclaimed
    if not remaining:
        try:
            os.unlink(os.path.join(d, f"{name}.seg"))
        except OSError:
            pass
        try:
            os.rmdir(refs)
        except OSError:
            pass


class SegmentPool:
    """Owns one segment directory (lazily created under the shm root)
    and its publish/acquire/release lifecycle. Thread-safe: concurrent
    publishes from stream-serving threads touch disjoint files; only the
    counters share the lock."""

    def __init__(self, root: Optional[str] = None):
        self._root = root
        self._dir: Optional[str] = None  # guarded-by: _lock
        self._lock = threading.Lock()
        self.published = 0  # guarded-by: _lock
        self.published_bytes = 0  # guarded-by: _lock
        self.opened = 0  # guarded-by: _lock
        self.opened_bytes = 0  # guarded-by: _lock
        self.linked = 0  # guarded-by: _lock
        self.lost = 0  # guarded-by: _lock

    def _ensure_dir(self) -> str:
        with self._lock:
            if self._dir is None:
                self._dir = tempfile.mkdtemp(
                    prefix="dftpu-seg-", dir=self._root or _default_root()
                )
            return self._dir

    # -- host classification -------------------------------------------------
    def descriptor(self) -> dict:
        """The pool's identity a client ships in a transfer request so
        the server can classify the hop: same hostname AND a reachable
        pool directory => co-located, serve segments; anything else =>
        remote, serve wire frames."""
        return {"host": socket.gethostname(), "dir": self._ensure_dir()}

    @staticmethod
    def same_host(desc: Optional[dict]) -> bool:
        """Whether ``desc`` (a peer's `descriptor()`) names THIS host —
        the remote/co-located hop classification. When the descriptor
        carries a pool directory it must also be reachable from here.
        Conservative on any doubt: a misclassified-remote hop only costs
        wire bytes, a misclassified-local one would fail reads (and even
        that degrades through `SegmentError`, never wrong results)."""
        if not isinstance(desc, dict):
            return False
        try:
            if desc.get("host") != socket.gethostname():
                return False
            d = desc.get("dir")
            return True if d is None else os.path.isdir(d)
        except OSError:
            return False

    # -- blocking I/O entry points (never call under a lock) -----------------
    def publish(self, payload, capacity: int = 0) -> tuple[str, str]:  # acquires: shm-segment
        """Write an `encode_table` payload as a named segment with ONE
        reference token; -> (name, token). The token transfers to the
        consumer (ride it in the S-frame); whoever holds it releases.
        BLOCKING (tmpfs write) — registered with the DFTPU205 lint."""
        d = self._ensure_dir()
        name = uuid.uuid4().hex
        tmp = os.path.join(d, f"{name}.tmp")
        try:
            with open(tmp, "wb") as f:
                f.write(_HEADER.pack(_MAGIC, _VERSION, int(capacity),
                                     len(payload)))
                f.write(payload)
            token = self._add_ref(name)
            # rename AFTER the token exists: a name is never visible
            # without a reference holding it alive
            os.rename(tmp, os.path.join(d, f"{name}.seg"))
        except OSError as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise SegmentError(f"segment publish failed: {e}") from e
        with self._lock:
            self.published += 1
            self.published_bytes += len(payload)
        return name, token

    def publish_file(self, path: str) -> tuple[str, str]:  # acquires: shm-segment
        """Serve an existing DFSP-framed file (a SpillManager slot) as a
        segment WITHOUT decoding it: hardlink into the pool (same
        filesystem), byte-copy fallback across devices. -> (name, token).
        BLOCKING (link/copy + header read) — registered with the
        DFTPU205 lint."""
        d = self._ensure_dir()
        name = uuid.uuid4().hex
        seg = os.path.join(d, f"{name}.seg")
        try:
            with open(path, "rb") as f:
                magic, version, _cap, _plen = _HEADER.unpack(
                    f.read(_HEADER.size)
                )
            if magic != _MAGIC or version != _VERSION:
                raise SegmentError(f"{path} is not a DFSP-framed file")
            token = self._add_ref(name)
            try:
                os.link(path, seg)
                linked = True
            except OSError:
                # cross-device (spill dir on disk, pool on tmpfs): copy
                import shutil

                shutil.copyfile(path, seg)
                linked = False
        except (OSError, _struct_error) as e:
            self._drop_ref_files(name)
            raise SegmentError(f"segment link failed: {e}") from e
        with self._lock:
            self.published += 1
            if linked:
                self.linked += 1
        return name, token

    def open_segment(self, name: str) -> tuple[bytes, int]:
        """Read a segment's Arrow IPC payload; -> (payload, capacity).
        The caller still holds its reference — read then `release`.
        Raises `SegmentError` on a missing or torn segment (the consumer
        degrades to the wire path). BLOCKING (tmpfs read) — registered
        with the DFTPU205 lint."""
        try:
            payload, cap = open_segment_at(self._ensure_dir(), name)
        except SegmentError:
            with self._lock:
                self.lost += 1
            raise
        with self._lock:
            self.opened += 1
            self.opened_bytes += len(payload)
        return payload, cap

    # -- cross-process refcounts ---------------------------------------------
    def _add_ref(self, name: str) -> str:
        token = uuid.uuid4().hex
        refs = os.path.join(self._ensure_dir(), f"{name}.refs")
        os.makedirs(refs, exist_ok=True)
        with open(os.path.join(refs, token), "wb"):
            pass
        if _leakcheck.enabled():
            _leakcheck.note_acquire("shm-segment", (name, token),
                                    tag="SegmentPool.publish")
        return token

    def acquire(self, name: str) -> str:  # acquires: shm-segment
        """Add a reference for an additional reader (broadcast fan-out);
        -> the new token. Only valid while holding an existing
        reference — acquire-after-last-release is a protocol error."""
        return acquire_at(self._ensure_dir(), name)

    def release(self, name: str, token: str) -> None:  # releases: shm-segment
        """Drop one reference; the LAST release unlinks the segment."""
        release_at(self._ensure_dir(), name, token)

    def _drop_ref_files(self, name: str) -> None:
        refs = os.path.join(self._ensure_dir(), f"{name}.refs")
        try:
            for t in os.listdir(refs):
                if _leakcheck.enabled():
                    _leakcheck.note_release("shm-segment", (name, t))
                try:
                    os.unlink(os.path.join(refs, t))
                except OSError:
                    pass
            os.rmdir(refs)
        except OSError:
            pass

    # -- observability / lifecycle -------------------------------------------
    def live_segments(self) -> int:
        """Segments currently in the pool DIRECTORY (filesystem is the
        cross-process ground truth, not this instance's counters) — the
        zero-leak gate reads 0 here once every stream drained."""
        with self._lock:
            d = self._dir
        if d is None:
            return 0
        try:
            return sum(1 for n in os.listdir(d) if n.endswith(".seg"))
        except OSError:
            return 0

    def stats(self) -> dict:
        with self._lock:
            out = {
                "published": self.published,
                "published_bytes": self.published_bytes,
                "opened": self.opened,
                "opened_bytes": self.opened_bytes,
                "linked": self.linked,
                "lost": self.lost,
            }
        out["live_segments"] = self.live_segments()
        return out

    def shutdown(self) -> None:
        """Reclaim the pool directory (process teardown / test cleanup):
        the backstop for references a dead consumer never released."""
        with self._lock:
            d, self._dir = self._dir, None
        if d is None:
            return
        if _leakcheck.enabled():
            # the rmtree reclaims every surviving token file wholesale
            try:
                for refs in os.listdir(d):
                    if not refs.endswith(".refs"):
                        continue
                    name = refs[: -len(".refs")]
                    for t in os.listdir(os.path.join(d, refs)):
                        _leakcheck.note_release("shm-segment", (name, t))
            except OSError:
                pass
        import shutil

        shutil.rmtree(d, ignore_errors=True)
