"""Streaming data plane: budgeted, cancellable chunk streams between
workers and the coordinator.

The reference's WorkerConnectionPool multiplexes a partition range per
stream, demuxes into per-partition channels, and backpressures on a 64 MiB
byte budget (`/root/reference/src/worker/worker_connection_pool.rs:243-308`);
tasks execute their partitions concurrently
(`/root/reference/src/worker/impl_execute_task.rs:80-114`). The TPU host
tier's analogue: a task's (device-resident) output is sliced into row
chunks; one puller thread per task feeds a shared bounded buffer whose
in-flight bytes never exceed the budget; the consumer drains chunks and can
cancel the remaining production early (a satisfied LIMIT stops the wire).

In-mesh exchanges never touch this: they are single-program collectives.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from datafusion_distributed_tpu.ops.table import Table


class StreamBudget:
    """Bounds the BYTES of chunks produced but not yet consumed (the
    connection-buffer budget role). Producers block in acquire() until the
    consumer releases; a chunk larger than the whole budget is admitted
    alone (large-but-valid rows must stream through, never deadlock)."""

    def __init__(self, budget_bytes: int):
        self.budget = max(int(budget_bytes), 1)
        self._cv = threading.Condition()
        self._in_flight = 0  # guarded-by: _cv
        self.peak_in_flight = 0  # guarded-by: _cv

    def acquire(self, nbytes: int, cancel: threading.Event) -> bool:
        with self._cv:
            while (
                self._in_flight > 0
                and self._in_flight + nbytes > self.budget
            ):
                if cancel.is_set():
                    return False
                self._cv.wait(timeout=0.05)
            if cancel.is_set():
                return False
            self._in_flight += nbytes
            self.peak_in_flight = max(self.peak_in_flight, self._in_flight)
            return True

    def release(self, nbytes: int) -> None:
        with self._cv:
            self._in_flight -= nbytes
            self._cv.notify_all()


@dataclass
class StreamStats:
    """Per-stage streaming telemetry (surfaced via Coordinator.metrics).
    ``rows_per_s``/``bytes_per_s`` are the reference LoadInfo's velocity
    fields (`worker.proto` LoadInfo, `sampler.rs:30-42`)."""

    bytes_streamed: int = 0
    chunks: int = 0
    peak_in_flight: int = 0
    early_exit: bool = False
    rows: int = 0
    elapsed_s: float = 0.0
    rows_per_s: float = 0.0
    bytes_per_s: float = 0.0
    extra: dict = field(default_factory=dict)


def stream_stage_chunks(
    pullers: list[Callable[[threading.Event], Iterator[tuple[Table, int]]]],
    budget_bytes: int,
    row_target: Optional[int] = None,
    max_concurrent: Optional[int] = None,
    on_progress: Optional[Callable[[int, int, int, int], None]] = None,
    payload_rows: Optional[Callable] = None,
    on_chunk: Optional[Callable] = None,
) -> tuple[list[list], StreamStats]:
    """Run one chunk stream per producer task concurrently under a shared
    byte budget; -> (per-task chunk lists, stats).

    ``row_target``: stop pulling once this many TOTAL rows arrived (the
    downstream LIMIT's fetch+skip) — remaining production is cancelled and
    its bytes never cross the wire.

    ``max_concurrent``: at most this many pullers EXECUTE at once (the
    cluster's worker count — a single in-process worker must not run every
    producer task simultaneously; matches `_run_stage_tasks`' thread-pool
    policy). Each puller materializes its task's output on dispatch, so
    this bounds peak device-side concurrency, not just host chunks.

    ``on_progress(done_pullers, total_pullers, rows, bytes)``: called in
    the consumer thread after every puller COMPLETION with the rows/bytes
    contributed by the completed pullers only — the reference's
    mid-execution LoadInfo stream (`sampler.rs:30-42`); an adaptive
    coordinator extrapolates the NEXT stage's sizing from these partial
    per-task samples (rows from still-running pullers are excluded so
    `rows * total/done` is an unbiased estimate).

    ``on_chunk(payload)``: called in the consumer thread for EVERY chunk
    as it arrives — the per-column half of the reference's LoadInfo
    (NDV %% / null %% sampled from in-flight batches, `sampler.rs:30-42`);
    the adaptive coordinator feeds a mid-stream column sampler from it.
    """
    import queue as _q

    if payload_rows is None:
        payload_rows = lambda p: int(p.num_rows)  # noqa: E731
    t_start = time.perf_counter()
    budget = StreamBudget(budget_bytes)
    cancel = threading.Event()
    out_q: _q.Queue = _q.Queue()
    chunks: list[list[Table]] = [[] for _ in pullers]
    stats = StreamStats()
    gate = (
        threading.Semaphore(max_concurrent)
        if max_concurrent is not None and max_concurrent < len(pullers)
        else None
    )

    def run(i: int, pull) -> None:
        held = False
        try:
            if gate is not None:
                gate.acquire()
                held = True
            if cancel.is_set():  # satisfied LIMIT: never dispatch the task
                return
            for chunk, nbytes in pull(cancel):
                if not budget.acquire(nbytes, cancel):
                    break
                out_q.put(("chunk", i, chunk, nbytes))
        except BaseException as e:  # propagate to the consumer
            out_q.put(("error", i, e, 0))
        finally:
            if held:
                gate.release()
            out_q.put(("done", i, None, 0))

    threads = [
        threading.Thread(target=run, args=(i, p), daemon=True)
        for i, p in enumerate(pullers)
    ]
    for t in threads:
        t.start()
    live = len(pullers)
    error: Optional[BaseException] = None
    rows_per = [0] * len(pullers)
    bytes_per = [0] * len(pullers)
    done_rows = 0
    done_bytes = 0
    while live:
        kind, i, payload, nbytes = out_q.get()
        if kind == "done":
            live -= 1
            done_rows += rows_per[i]
            done_bytes += bytes_per[i]
            if on_progress is not None:
                on_progress(len(pullers) - live, len(pullers),
                            done_rows, done_bytes)
            continue
        if kind == "error":
            # first error wins, EXCEPT that a fatal (non-retryable) error
            # displaces a retryable one: once the fault-tolerant pullers
            # exhausted their retries, the query-semantic failure is the
            # actionable diagnosis — a sibling's transport hiccup that
            # happened to arrive first must not mask it
            from datafusion_distributed_tpu.runtime.errors import (
                is_retryable,
            )

            if error is None or (
                is_retryable(error) and not is_retryable(payload)
            ):
                error = payload
            cancel.set()
            continue
        budget.release(nbytes)
        if cancel.is_set():
            continue  # late chunk after cancellation: drop
        chunks[i].append(payload)
        if on_chunk is not None:
            try:
                on_chunk(payload)
            except Exception:
                pass  # sampling must never fail the stream
        stats.chunks += 1
        stats.bytes_streamed += nbytes
        pr = payload_rows(payload)
        stats.rows += pr
        rows_per[i] += pr
        bytes_per[i] += nbytes
        if row_target is not None and stats.rows >= row_target:
            stats.early_exit = True
            cancel.set()
    for t in threads:
        t.join(timeout=5.0)
    if error is not None:
        raise error
    stats.peak_in_flight = budget.peak_in_flight
    stats.elapsed_s = max(time.perf_counter() - t_start, 1e-9)
    stats.rows_per_s = stats.rows / stats.elapsed_s
    stats.bytes_per_s = stats.bytes_streamed / stats.elapsed_s
    return chunks, stats
