"""Streaming data plane: budgeted, cancellable chunk streams between
workers and the coordinator.

The reference's WorkerConnectionPool multiplexes a partition range per
stream, demuxes into per-partition channels, and backpressures on a 64 MiB
byte budget (`/root/reference/src/worker/worker_connection_pool.rs:243-308`);
tasks execute their partitions concurrently
(`/root/reference/src/worker/impl_execute_task.rs:80-114`). The TPU host
tier's analogue: a task's (device-resident) output is sliced into row
chunks; one puller thread per task feeds a shared bounded buffer whose
in-flight bytes never exceed the budget; the consumer drains chunks and can
cancel the remaining production early (a satisfied LIMIT stops the wire).

Two consumer shapes share the machinery:

- `stream_stage_chunks`: collect-then-return — every puller's chunks are
  gathered and handed back at once (the materialized planes).
- `stream_partition_chunks` + `PartitionFeed`: incremental demux — chunks
  arrive tagged (partition, producer, seq) and become visible to waiting
  consumers the moment they land, with per-partition completion tracking.
  This is the PIPELINED shuffle plane's transport: consumer tasks start on
  their partition as soon as it closes instead of waiting for the whole
  boundary (`StreamScanExec` is the consumer-side leaf).

In-mesh exchanges never touch this: they are single-program collectives.
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from datafusion_distributed_tpu.runtime import leakcheck as _leakcheck
from datafusion_distributed_tpu.ops.table import Table, concat_tables
from datafusion_distributed_tpu.plan.physical import (
    DistributedTaskContext,
    ExecContext,
    ExecutionPlan,
)


class CancelSignal(threading.Event):
    """threading.Event whose ``set()`` also fires registered wake hooks.

    The stream machinery blocks producers inside `StreamBudget.acquire`
    (a Condition wait); a plain Event's ``set()`` cannot wake them, which
    is why acquire historically polled with a 50 ms timeout. Binding the
    cancel to the budget (`StreamBudget.bind_cancel`) registers the
    budget's notify as a hook, so cancellation wakes blocked producers
    IMMEDIATELY and the poll timeout goes away."""

    def __init__(self):
        super().__init__()
        self._hook_lock = threading.Lock()
        self._hooks: list = []  # guarded-by: _hook_lock

    def add_hook(self, fn) -> None:
        with self._hook_lock:
            self._hooks.append(fn)
            already = self.is_set()
        if already:  # set() may have raced the registration: fire now
            fn()

    def set(self) -> None:
        super().set()
        with self._hook_lock:
            hooks = list(self._hooks)
        for fn in hooks:
            fn()


class StreamBudget:
    """Bounds the BYTES of chunks produced but not yet consumed (the
    connection-buffer budget role). Producers block in acquire() until the
    consumer releases; a chunk larger than the whole budget is admitted
    alone (large-but-valid rows must stream through, never deadlock).

    ``pressure``: optional callable — the destination worker stores'
    memory-pressure probe (TableStore.under_pressure). While it reads
    True, producers with chunks still in flight BLOCK even when the
    stream's own budget has room: the stream degrades to trickle pace
    (one chunk at a time) so a pipelined shuffle slows down instead of
    overrunning an enforced worker memory budget. Like the byte budget,
    pressure never blocks a producer with ZERO bytes in flight —
    guaranteed progress, so a store pinned over budget by live
    consumers can still drain. A bound CancelSignal wakes blocked
    producers immediately either way (cancel-notify); pressure-clear is
    observed at the 50 ms poll."""

    def __init__(self, budget_bytes: int, pressure=None):
        self.budget = max(int(budget_bytes), 1)
        self.pressure = pressure
        self._cv = threading.Condition()
        self._in_flight = 0  # guarded-by: _cv
        self.peak_in_flight = 0  # guarded-by: _cv
        self.pressure_waits = 0  # guarded-by: _cv
        # cancel events whose set() notifies _cv (bind_cancel): acquire
        # may then wait WITHOUT a poll timeout — a blocked producer wakes
        # at cancellation latency instead of the next 50 ms tick
        self._bound = weakref.WeakSet()  # guarded-by: _cv

    def bind_cancel(self, cancel: "CancelSignal") -> None:
        """Register ``cancel`` to notify blocked acquirers on set()."""
        with self._cv:
            self._bound.add(cancel)
        cancel.add_hook(self._wake_all)

    def _wake_all(self) -> None:
        with self._cv:
            self._cv.notify_all()

    def _under_pressure(self) -> bool:
        if self.pressure is None:
            return False
        try:
            return bool(self.pressure())
        except Exception:
            return False  # a broken probe must never wedge the stream

    def acquire(self, nbytes: int, cancel: threading.Event) -> bool:
        with self._cv:
            # a bound CancelSignal notifies this condition on set(), so
            # the wait needs no poll timeout; an unbound plain Event —
            # or an installed pressure probe, which nothing notifies —
            # keeps the 50 ms poll as the progress check
            timeout = (
                None if cancel in self._bound and self.pressure is None
                else 0.05
            )
            noted_pressure = False
            while self._in_flight > 0 and (
                self._in_flight + nbytes > self.budget
                or self._under_pressure()
            ):
                if not noted_pressure and (
                    self._in_flight + nbytes <= self.budget
                ):
                    # blocked by store pressure alone: count it once per
                    # acquire (the backpressure-engaged signal)
                    self.pressure_waits += 1
                    noted_pressure = True
                if cancel.is_set():
                    return False
                self._cv.wait(timeout=timeout)
            if cancel.is_set():
                return False
            self._in_flight += nbytes
            self.peak_in_flight = max(self.peak_in_flight, self._in_flight)
            return True

    def release(self, nbytes: int) -> None:
        with self._cv:
            self._in_flight -= nbytes
            self._cv.notify_all()


@dataclass
class StreamStats:
    """Per-stage streaming telemetry (surfaced via Coordinator.metrics).
    ``rows_per_s``/``bytes_per_s`` are the reference LoadInfo's velocity
    fields (`worker.proto` LoadInfo, `sampler.rs:30-42`)."""

    bytes_streamed: int = 0
    chunks: int = 0
    peak_in_flight: int = 0
    early_exit: bool = False
    rows: int = 0
    elapsed_s: float = 0.0
    rows_per_s: float = 0.0
    bytes_per_s: float = 0.0
    extra: dict = field(default_factory=dict)


def _note_leaked_pullers(count: int) -> None:
    """A puller thread outlived its join window: count it into the
    process telemetry registry (`dftpu_stream_pullers_leaked_total`) and
    the always-on structured event log, so a hung producer shows up as a
    visible signal instead of a slow thread leak. Best-effort — leak
    OBSERVABILITY must never fail the stream that already completed."""
    try:
        from datafusion_distributed_tpu.runtime.telemetry import (
            DEFAULT_REGISTRY,
        )

        DEFAULT_REGISTRY.counter(
            "dftpu_stream_pullers_leaked",
            "Stream puller threads abandoned after the join timeout "
            "(a hung producer task the stream stopped waiting for).",
        ).inc(count)
    except Exception:
        pass
    try:
        from datafusion_distributed_tpu.runtime.eventlog import log_event

        log_event("stream_pullers_leaked", count=count)
    except Exception:
        pass


def _join_pullers(threads, stats: StreamStats,
                  timeout_s: float = 5.0) -> None:
    """Join puller threads with a bounded per-stream budget; stragglers
    are ABANDONED (daemon threads — a hung worker execute cannot be
    interrupted from Python) but now counted instead of silently leaked:
    `stats.extra["pullers_leaked"]` + telemetry + a structured event."""
    deadline = time.monotonic() + timeout_s
    for t in threads:
        t.join(timeout=max(deadline - time.monotonic(), 0.0))
    leaked = sum(1 for t in threads if t.is_alive())
    if leaked:
        stats.extra["pullers_leaked"] = leaked
        _note_leaked_pullers(leaked)


def stream_stage_chunks(
    pullers: list[Callable[[threading.Event], Iterator[tuple[Table, int]]]],
    budget_bytes: int,
    row_target: Optional[int] = None,
    max_concurrent: Optional[int] = None,
    on_progress: Optional[Callable[[int, int, int, int], None]] = None,
    payload_rows: Optional[Callable] = None,
    on_chunk: Optional[Callable] = None,
    pressure: Optional[Callable[[], bool]] = None,
) -> tuple[list[list], StreamStats]:
    """Run one chunk stream per producer task concurrently under a shared
    byte budget; -> (per-task chunk lists, stats).

    ``row_target``: stop pulling once this many TOTAL rows arrived (the
    downstream LIMIT's fetch+skip) — remaining production is cancelled and
    its bytes never cross the wire.

    ``max_concurrent``: at most this many pullers EXECUTE at once (the
    cluster's worker count — a single in-process worker must not run every
    producer task simultaneously; matches `_run_stage_tasks`' thread-pool
    policy). Each puller materializes its task's output on dispatch, so
    this bounds peak device-side concurrency, not just host chunks.

    ``on_progress(done_pullers, total_pullers, rows, bytes)``: called in
    the consumer thread after every puller COMPLETION with the rows/bytes
    contributed by the completed pullers only — the reference's
    mid-execution LoadInfo stream (`sampler.rs:30-42`); an adaptive
    coordinator extrapolates the NEXT stage's sizing from these partial
    per-task samples (rows from still-running pullers are excluded so
    `rows * total/done` is an unbiased estimate).

    ``on_chunk(payload)``: called in the consumer thread for EVERY chunk
    as it arrives — the per-column half of the reference's LoadInfo
    (NDV %% / null %% sampled from in-flight batches, `sampler.rs:30-42`);
    the adaptive coordinator feeds a mid-stream column sampler from it.

    ``pressure``: destination-store memory-pressure probe
    (StreamBudget's producer backpressure — see its docstring).
    """
    import queue as _q

    if payload_rows is None:
        payload_rows = lambda p: int(p.num_rows)  # noqa: E731
    t_start = time.perf_counter()
    budget = StreamBudget(budget_bytes, pressure=pressure)
    cancel = CancelSignal()
    budget.bind_cancel(cancel)
    out_q: _q.Queue = _q.Queue()
    chunks: list[list[Table]] = [[] for _ in pullers]
    stats = StreamStats()
    gate = (
        threading.Semaphore(max_concurrent)
        if max_concurrent is not None and max_concurrent < len(pullers)
        else None
    )

    def run(i: int, pull) -> None:
        held = False
        if _leakcheck.enabled():
            _leakcheck.note_acquire("stream-puller", (id(out_q), i),
                                    tag="stream_stage_chunks")
        try:
            if gate is not None:
                gate.acquire()
                held = True
            if cancel.is_set():  # satisfied LIMIT: never dispatch the task
                return
            for chunk, nbytes in pull(cancel):
                if not budget.acquire(nbytes, cancel):
                    break
                out_q.put(("chunk", i, chunk, nbytes))
        except BaseException as e:  # propagate to the consumer
            out_q.put(("error", i, e, 0))
        finally:
            if held:
                gate.release()
            # an abandoned puller (join timeout) stays live in the leak
            # harness until its thread actually exits — leaked-while-hung,
            # self-releasing, matching the telemetry counter's intent
            if _leakcheck.enabled():
                _leakcheck.note_release("stream-puller", (id(out_q), i))
            out_q.put(("done", i, None, 0))

    threads = [
        threading.Thread(target=run, args=(i, p), daemon=True)
        for i, p in enumerate(pullers)
    ]
    for t in threads:
        t.start()
    live = len(pullers)
    error: Optional[BaseException] = None
    rows_per = [0] * len(pullers)
    bytes_per = [0] * len(pullers)
    done_rows = 0
    done_bytes = 0
    while live:
        kind, i, payload, nbytes = out_q.get()
        if kind == "done":
            live -= 1
            done_rows += rows_per[i]
            done_bytes += bytes_per[i]
            if on_progress is not None:
                on_progress(len(pullers) - live, len(pullers),
                            done_rows, done_bytes)
            continue
        if kind == "error":
            # first error wins, EXCEPT that a fatal (non-retryable) error
            # displaces a retryable one: once the fault-tolerant pullers
            # exhausted their retries, the query-semantic failure is the
            # actionable diagnosis — a sibling's transport hiccup that
            # happened to arrive first must not mask it
            from datafusion_distributed_tpu.runtime.errors import (
                is_retryable,
            )

            if error is None or (
                is_retryable(error) and not is_retryable(payload)
            ):
                error = payload
            cancel.set()
            continue
        budget.release(nbytes)
        if cancel.is_set():
            continue  # late chunk after cancellation: drop
        chunks[i].append(payload)
        if on_chunk is not None:
            try:
                on_chunk(payload)
            except Exception:
                pass  # sampling must never fail the stream
        stats.chunks += 1
        stats.bytes_streamed += nbytes
        pr = payload_rows(payload)
        stats.rows += pr
        rows_per[i] += pr
        bytes_per[i] += nbytes
        if row_target is not None and stats.rows >= row_target:
            stats.early_exit = True
            cancel.set()
    _join_pullers(threads, stats)
    if error is not None:
        raise error
    stats.peak_in_flight = budget.peak_in_flight
    if budget.pressure_waits:
        stats.extra["pressure_waits"] = budget.pressure_waits
    stats.elapsed_s = max(time.perf_counter() - t_start, 1e-9)
    stats.rows_per_s = stats.rows / stats.elapsed_s
    stats.bytes_per_s = stats.bytes_streamed / stats.elapsed_s
    return chunks, stats


# ---------------------------------------------------------------------------
# pipelined shuffle plane: incremental per-(task, partition) demux
# ---------------------------------------------------------------------------


def _feed_cancel_error():
    from datafusion_distributed_tpu.runtime.errors import TaskCancelledError

    return TaskCancelledError(
        "pipelined partition feed cancelled: the query was cancelled "
        "while waiting for producer slices"
    )


class PartitionFeed:
    """Consumer-side incremental buffer of a pipelined shuffle boundary.

    Producer task i's multiplexed stream yields (partition, chunk) pairs
    in ASCENDING partition order (`Worker.execute_task_partitions` walks
    [part_lo, part_hi)); the feed demuxes arrivals into per-partition
    chunk lists tagged (producer, seq). Partition p is COMPLETE once
    every producer has either finished or moved past p — at which point
    `wait_partition(p)` returns p's chunks in deterministic
    (producer, seq) order, which is EXACTLY the order the materialized
    plane's collect-then-concat produces (producer-major, yield order
    within a producer), so the pipelined and materialized planes build
    byte-identical consumer slices.

    Waits honor an optional ``cancelled`` callable (the coordinator's
    per-query cancel predicate) so a consumer blocked on a partition of a
    cancelled query unwinds instead of waiting for producers that will
    never finish."""

    def __init__(self, num_partitions: int, num_producers: int):
        self.num_partitions = int(num_partitions)
        self.num_producers = int(num_producers)
        self._cv = threading.Condition()
        #: per partition: list of (producer_index, seq, Table)
        self._chunks: list[list] = [
            [] for _ in range(self.num_partitions)
        ]  # guarded-by: _cv
        #: per producer: highest partition id it has emitted so far
        self._frontier = [-1] * self.num_producers  # guarded-by: _cv
        self._seq = [0] * self.num_producers  # guarded-by: _cv
        self._done = [False] * self.num_producers  # guarded-by: _cv
        self._first = False  # guarded-by: _cv
        self._complete = False  # guarded-by: _cv
        self._error: Optional[BaseException] = None  # guarded-by: _cv
        self._end_s: Optional[float] = None  # guarded-by: _cv
        self._on_complete: list = []  # guarded-by: _cv
        self.stats: Optional[StreamStats] = None  # guarded-by: _cv
        #: per-partition rows/bytes landed so far — the live skew
        #: histogram the runtime-adaptivity layer reads to spot a hot
        #: destination while (and after) the shuffle streams
        #: (runtime/adaptivity.py detect_skew)
        self.partition_rows = [0] * self.num_partitions  # guarded-by: _cv
        self.partition_bytes = [0] * self.num_partitions  # guarded-by: _cv

    # -- producer side (driven by stream_partition_chunks) -------------------
    def add(self, producer: int, partition: int, chunk: Table,
            nbytes: int = 0) -> None:
        with self._cv:
            self._chunks[partition].append(
                (producer, self._seq[producer], chunk)
            )
            self._seq[producer] += 1
            self._frontier[producer] = max(
                self._frontier[producer], partition
            )
            self.partition_rows[partition] += int(chunk.num_rows)
            self.partition_bytes[partition] += int(nbytes)
            self._first = True
            self._cv.notify_all()

    def producer_done(self, producer: int) -> None:
        with self._cv:
            self._done[producer] = True
            self._cv.notify_all()

    def fail(self, error: BaseException) -> None:
        """Record a failure (idempotent). Mirrors the stream loops'
        first-error-wins-except-fatal-displaces-retryable rule: once the
        pullers exhausted their retries, the query-semantic failure is
        the actionable diagnosis and must not be masked by a sibling's
        transport hiccup that landed first."""
        from datafusion_distributed_tpu.runtime.errors import is_retryable

        with self._cv:
            if self._error is None or (
                is_retryable(self._error) and not is_retryable(error)
            ):
                self._error = error
            self._end_s = self._end_s or time.monotonic()
            self._cv.notify_all()

    def finish(self, stats: StreamStats) -> None:
        with self._cv:
            self.stats = stats
            self._complete = True
            self._end_s = time.monotonic()
            callbacks = list(self._on_complete)
            self._on_complete.clear()
            end = self._end_s
            self._cv.notify_all()
        for cb in callbacks:  # outside the lock: callbacks may take locks
            cb(end)

    def on_complete(self, cb: Callable[[float], None]) -> None:
        """Register ``cb(end_monotonic_s)`` to fire when the feed
        completes successfully (immediately if it already has). A failed
        feed never fires — matching the materialized plane, which records
        no stage span for a failed materialization."""
        with self._cv:
            if not self._complete:
                self._on_complete.append(cb)
                return
            end = self._end_s
        cb(end)

    # -- consumer side -------------------------------------------------------
    def _partition_ready_locked(self, p: int) -> bool:
        if self._complete:
            return True
        return all(
            self._done[i] or self._frontier[i] > p
            for i in range(self.num_producers)
        )

    def _wait_locked(self, pred, cancelled: Optional[Callable[[], bool]]):
        """Block until ``pred()`` or the feed errors; the caller holds
        `_cv`. ``cancelled`` is polled at a coarse interval as the
        backstop for cancellations that never reach the feed itself."""
        while True:
            if self._error is not None:
                raise self._error
            if pred():
                return
            if cancelled is not None and cancelled():
                raise _feed_cancel_error()
            self._cv.wait(timeout=0.25 if cancelled is not None
                          else None)

    def wait_first_chunk(
        self, cancelled: Optional[Callable[[], bool]] = None
    ) -> None:
        """Block until the first slice landed (the stage-DAG scheduler's
        consumer-release point) — or the feed completed empty/errored."""
        with self._cv:
            self._wait_locked(
                lambda: self._first or self._complete, cancelled
            )

    def wait_partition(
        self, p: int, cancelled: Optional[Callable[[], bool]] = None
    ) -> list[Table]:
        """Chunks of partition ``p`` in deterministic (producer, seq)
        order, blocking until the partition is complete."""
        with self._cv:
            self._wait_locked(
                lambda: self._partition_ready_locked(p), cancelled
            )
            parts = sorted(self._chunks[p], key=lambda e: (e[0], e[1]))
            # consumed exactly once per partition; drop the raw refs so
            # the feed does not pin chunk views past their concat
            self._chunks[p] = []
        return [c for _i, _s, c in parts]

    def wait_complete(
        self, cancelled: Optional[Callable[[], bool]] = None
    ) -> StreamStats:
        with self._cv:
            self._wait_locked(lambda: self._complete, cancelled)
            return self.stats

    def partition_histogram(self) -> tuple[list, list]:
        """Point-in-time copy of the per-partition (rows, bytes) landed
        so far — complete once the feed finished."""
        with self._cv:
            return list(self.partition_rows), list(self.partition_bytes)

    @property
    def error(self) -> Optional[BaseException]:
        with self._cv:
            return self._error


def stream_partition_chunks(
    pullers: list,
    budget_bytes: int,
    feed: PartitionFeed,
    max_concurrent: Optional[int] = None,
    on_chunk: Optional[Callable] = None,
    should_cancel: Optional[Callable[[], bool]] = None,
    pressure: Optional[Callable[[], bool]] = None,
) -> StreamStats:
    """Incremental variant of `stream_stage_chunks` for per-(task,
    partition) streams: each puller yields ((partition, chunk), est_bytes)
    and every arrival is demuxed into ``feed`` IMMEDIATELY (budget bytes
    released on demux — the feed's accumulation is the same memory the
    materialized plane would hold). On success the feed is finished with
    the stream stats; on failure it is failed with the first error (fatal
    displaces retryable, as in stream_stage_chunks) and the error
    re-raises. ``should_cancel``: external cancel predicate (the
    per-query cancel) polled in the consumer loop. ``pressure``:
    destination-store memory-pressure probe — producers slow to trickle
    pace while the worker stores are over their enforced budget."""
    import queue as _q

    t_start = time.perf_counter()
    budget = StreamBudget(budget_bytes, pressure=pressure)
    cancel = CancelSignal()
    budget.bind_cancel(cancel)
    out_q: _q.Queue = _q.Queue()
    stats = StreamStats()
    gate = (
        threading.Semaphore(max_concurrent)
        if max_concurrent is not None and max_concurrent < len(pullers)
        else None
    )

    def run(i: int, pull) -> None:
        held = False
        if _leakcheck.enabled():
            _leakcheck.note_acquire("stream-puller", (id(out_q), i),
                                    tag="stream_partition_chunks")
        try:
            if gate is not None:
                gate.acquire()
                held = True
            if cancel.is_set():
                return
            for payload, nbytes in pull(cancel):
                if not budget.acquire(nbytes, cancel):
                    break
                out_q.put(("chunk", i, payload, nbytes))
        except BaseException as e:
            out_q.put(("error", i, e, 0))
        finally:
            if held:
                gate.release()
            if _leakcheck.enabled():
                _leakcheck.note_release("stream-puller", (id(out_q), i))
            out_q.put(("done", i, None, 0))

    threads = [
        threading.Thread(target=run, args=(i, p), daemon=True,
                         name="dftpu-pipelined-pull")
        for i, p in enumerate(pullers)
    ]
    for t in threads:
        t.start()
    live = len(pullers)
    error: Optional[BaseException] = None
    while live:
        try:
            kind, i, payload, nbytes = out_q.get(timeout=0.25)
        except _q.Empty:
            if should_cancel is not None and should_cancel():
                cancel.set()
            continue
        if kind == "done":
            live -= 1
            feed.producer_done(i)
            continue
        if kind == "error":
            from datafusion_distributed_tpu.runtime.errors import (
                is_retryable,
            )

            if error is None or (
                is_retryable(error) and not is_retryable(payload)
            ):
                error = payload
            # fail the feed NOW, not at loop end: the failed producer's
            # trailing "done" would otherwise mark its unfinished
            # partitions complete and a consumer mid-wait could build a
            # silently truncated slice in the drain window (the error
            # message precedes the done message in the queue, so waiters
            # observe the failure first)
            feed.fail(payload)
            cancel.set()
            continue
        budget.release(nbytes)
        if cancel.is_set():
            continue  # late chunk after cancellation: drop
        p, chunk = payload
        feed.add(i, p, chunk, nbytes=nbytes)
        if on_chunk is not None:
            try:
                on_chunk(chunk)
            except Exception:
                pass  # sampling must never fail the stream
        stats.chunks += 1
        stats.bytes_streamed += nbytes
        stats.rows += int(chunk.num_rows)
        if should_cancel is not None and should_cancel():
            cancel.set()
    _join_pullers(threads, stats)
    stats.peak_in_flight = budget.peak_in_flight
    if budget.pressure_waits:
        stats.extra["pressure_waits"] = budget.pressure_waits
    stats.elapsed_s = max(time.perf_counter() - t_start, 1e-9)
    stats.rows_per_s = stats.rows / stats.elapsed_s
    stats.bytes_per_s = stats.bytes_streamed / stats.elapsed_s
    if error is not None:
        feed.fail(error)
        raise error
    if cancel.is_set():
        # cancelled WITHOUT a puller error (external should_cancel):
        # in-flight chunks were dropped above, so the feed must FAIL —
        # finishing it would let a consumer that already passed its
        # cancel checkpoint build a silently TRUNCATED partition and
        # record the stream as complete
        cancelled = _feed_cancel_error()
        feed.fail(cancelled)
        raise cancelled
    feed.finish(stats)
    return stats


class StreamScanExec(ExecutionPlan):
    """Consumer-side leaf of a PIPELINED shuffle boundary.

    Holds a live `PartitionFeed` instead of materialized tables: the
    stage-DAG scheduler releases the consumer stage on FIRST SLICE, and
    each consumer task's dispatch (`_task_specialized`) resolves this
    node into a pinned MemoryScan by waiting for ITS partition only — so
    consumer task j starts executing the moment partition j closes, while
    partitions j+1.. are still streaming. Never crosses the wire (task
    specialization replaces it before encode; the codec has no entry for
    it by design, so an accidental ship fails loudly).

    Byte identity with the materialized plane: `task_slice` builds each
    partition's table with the SAME chunk order ((producer, seq) — the
    materialized collect's producer-major order) and the SAME capacity
    arithmetic (live rows rounded up to 8), so the consumer stage's
    compiled programs and results are identical across planes."""

    def __init__(self, feed: PartitionFeed, schema,
                 dictionaries: Optional[dict] = None,
                 capacity_hint: int = 0,
                 cancelled: Optional[Callable[[], bool]] = None):
        super().__init__()
        self.feed = feed
        self._schema = schema
        self.dictionaries = dictionaries
        self.capacity_hint = int(capacity_hint)
        self._cancelled = cancelled
        self._cv = threading.Condition()
        self._slices: dict = {}  # partition -> Table; guarded-by: _cv
        #: partitions a thread is currently building (claim protocol:
        #: feed chunks drain exactly once, so a concurrent second
        #: builder — a hedged re-dispatch of the same consumer task —
        #: must WAIT for the first build, never build from the drained
        #: feed and install an empty slice)
        self._building: set = set()  # guarded-by: _cv

    @property
    def num_partitions(self) -> int:
        return self.feed.num_partitions

    # -- tree ---------------------------------------------------------------
    def children(self):
        return []

    def with_new_children(self, children):
        assert not children
        return self

    def schema(self):
        return self._schema

    def output_capacity(self):
        return max(self.capacity_hint, 8)

    # -- data plane ---------------------------------------------------------
    def task_slice(self, partition: int) -> Table:
        """The consumer slice for ``partition``, built exactly like the
        materialized plane's (concat in (producer, seq) order, capacity =
        live rows rounded to 8, schema-typed empty fallback). Built
        EXACTLY ONCE (the feed's chunks drain on first take); concurrent
        callers — task retries, a hedged re-dispatch of the same
        consumer task — wait for the first build and observe the same
        table object."""
        with self._cv:
            while True:
                hit = self._slices.get(partition)
                if hit is not None:
                    return hit
                if partition not in self._building:
                    self._building.add(partition)
                    break
                # another thread is building this slice: wait for its
                # install (timeout so an external cancel still unwinds)
                if self._cancelled is not None and self._cancelled():
                    raise _feed_cancel_error()
                self._cv.wait(
                    timeout=0.25 if self._cancelled is not None else None
                )
        try:
            chunks = self.feed.wait_partition(partition, self._cancelled)
            if chunks:
                rows = sum(int(t.num_rows) for t in chunks)
                cap = max(-(-rows // 8) * 8, 8)
                built = concat_tables(chunks, capacity=cap)
            else:
                built = Table.empty(self._schema, 8, self.dictionaries)
        except BaseException:
            with self._cv:
                # release the claim so a retry (or the hedge sibling)
                # can surface the feed's error instead of hanging
                self._building.discard(partition)
                self._cv.notify_all()
            raise
        with self._cv:
            self._building.discard(partition)
            self._slices[partition] = built
            self._cv.notify_all()
        return built

    def all_slices(self) -> list[Table]:
        """Every partition's slice in partition order (the IsolatedArm
        sole-consumer pull and the direct-execution fallback)."""
        return [self.task_slice(p) for p in range(self.num_partitions)]

    def load(self, task: DistributedTaskContext) -> Table:
        """In-process fallback (a stage executed without task
        specialization): mirror MemoryScanExec.load semantics."""
        if task.task_index >= self.num_partitions:
            return Table.empty(self._schema, 8, self.dictionaries)
        return self.task_slice(task.task_index)

    def _execute(self, ctx: ExecContext) -> Table:
        return ctx.inputs[self.node_id]

    def display(self):
        return (
            f"StreamScan partitions={self.num_partitions} "
            f"producers={self.feed.num_producers}"
        )
