"""Coordinator: stage-wise distributed execution across workers.

The reference's `DistributedExec`/`QueryCoordinator` assign worker URLs per
task, ship task-specialized plans over a coordinator channel, then stream
results through the exchange network (`/root/reference/src/coordinator/`,
SURVEY.md §3.2). This is the host-runtime tier of the TPU design:

  in-mesh   -> runtime/mesh_executor.py (one SPMD program, collectives)
  cross-mesh/host -> THIS: each stage's tasks run on workers; the coordinator
  materializes stage outputs and performs the exchange semantics between
  stages (the DCN hop).

Stages execute bottom-up: every exchange boundary's producer subtree is
shipped to workers task-by-task (round-robin routing, the reference's
routed_urls default), executed, and the exchange (shuffle regroup /
broadcast / coalesce) is applied to the collected outputs; the boundary then
becomes an in-memory scan for the consumer stage — the Pending->Ready flip
of `Stage::Local -> Stage::Remote`.
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu.ops.hash import hash_columns
from datafusion_distributed_tpu.ops.table import Table, concat_tables
from datafusion_distributed_tpu.plan.exchanges import (
    BroadcastExchangeExec,
    CoalesceExchangeExec,
    IsolatedArmExec,
    PartitionReplicatedExec,
    RangeShuffleExchangeExec,
    ShuffleExchangeExec,
)
from datafusion_distributed_tpu.plan.physical import (
    DistributedTaskContext,
    ExecutionPlan,
    MemoryScanExec,
)
from datafusion_distributed_tpu.runtime.codec import TableStore, encode_plan
from datafusion_distributed_tpu.runtime.errors import (
    TaskCancelledError,
    TaskTimeoutError,
    WorkerError,
    WorkerUnavailableError,
    is_retryable,
)
from datafusion_distributed_tpu.runtime.metrics import (
    FaultCounters,
    MetricsStore,
)
from datafusion_distributed_tpu.runtime.streams import StreamScanExec
from datafusion_distributed_tpu.runtime.tracing import (
    DEFAULT_TRACE_STORE,
    NULL_TRACER,
    TRACE_CTX_KEY,
    resolve_tracing_mode,
    table_nbytes,
)
from datafusion_distributed_tpu.runtime.worker import (
    TaskKey,
    Worker,
    call_with_deadline,
)


#: fault-tolerance knobs and their defaults, settable per session via
#: `SET distributed.<knob> = <value>` (sql/context.py plumbs
#: distributed_options into Coordinator.config_options). Timeouts of 0
#: mean "no deadline". task_timeout_s bounds one ATTEMPT: on the bulk
#: plane that is execution + result transfer (gRPC wire deadlines span
#: the whole call), on the streaming planes it is the wait for the FIRST
#: chunk (which contains the execution; later chunks slice an already-
#: materialized output) — size it for the slowest legitimate task
#: including its result, not just its compute.
FAULT_TOLERANCE_DEFAULTS = {
    "max_task_retries": 2,
    "task_retry_backoff_s": 0.05,
    "task_timeout_s": 0.0,
    "dispatch_timeout_s": 0.0,
    "quarantine_threshold": 3,
    "quarantine_seconds": 30.0,
}

#: straggler-hedging knobs (`SET distributed.hedging` etc.): when a
#: task's attempt outlives max(sketch-p<hedge_quantile>, hedge_floor_s)
#: the coordinator speculatively re-dispatches it to a different healthy
#: worker; first completed attempt wins, the loser is cancelled and its
#: staged slices released. hedge_budget bounds IN-FLIGHT speculative
#: attempts cluster-wide (runtime/metrics.py HedgeBudget) so a cold
#: sketch or a uniformly slow stage cannot stampede the cluster with
#: doubled load. Off by default: hedging burns spare capacity for tail
#: latency — a serving-tier tradeoff the operator opts into.
HEDGING_DEFAULTS = {
    "hedging": False,
    "hedge_quantile": 0.99,
    "hedge_floor_s": 0.05,
    "hedge_budget": 2,
}

#: stage-DAG scheduler knobs (`SET distributed.stage_parallelism`):
#: bounded in-flight budget for CONCURRENT STAGES — how many independent
#: exchange subtrees may materialize at once. 0 = auto (the worker
#: count); 1 = the sequential depth-first order (pre-scheduler
#: behavior, byte-identical results by design at any setting).
SCHEDULER_DEFAULTS = {
    "stage_parallelism": 0,
}

#: pipelined-shuffle knob (`SET distributed.pipelined_shuffle`, default
#: on): shuffle boundaries on the coordinator-mediated partition-stream
#: plane stream producer slices into a live PartitionFeed and the
#: consumer stage releases on FIRST SLICE instead of stage-complete —
#: each consumer task then blocks only for ITS partition
#: (runtime/streams.py StreamScanExec). Results are byte-identical to
#: the materialized plane by construction (same chunk order, same
#: capacity arithmetic). Engages only under the stage-DAG scheduler
#: (stage_parallelism > 1 — `= 1` keeps the documented pre-scheduler
#: materialized behavior) and only without a checkpointer (checkpoints
#: snapshot materialized frontiers).
PIPELINE_DEFAULTS = {
    "pipelined_shuffle": True,
}

#: single lookup for every `SET distributed.*` knob default the
#: coordinator reads through _opt_int/_opt_float
_OPTION_DEFAULTS = {
    **FAULT_TOLERANCE_DEFAULTS, **SCHEDULER_DEFAULTS, **HEDGING_DEFAULTS,
}


def _terminal(exc: WorkerError) -> WorkerError:
    """Mark an instance of a retryable class as NOT retryable (cluster-wide
    conditions like 'no healthy workers' that no re-dispatch can fix)."""
    exc.retryable = False
    return exc


#: serializes lazy HealthTracker creation: stage fan-out threads may record
#: their first failures concurrently, and a lost race would drop a failure
#: on an orphan tracker (threshold-1 quarantines silently missed)
_HEALTH_INIT_LOCK = threading.Lock()

#: same role for the lazily-created HedgeBudget: two concurrent tasks
#: each minting a budget would double the in-flight bound
_HEDGE_INIT_LOCK = threading.Lock()


class _EitherSet:
    """Duck-typed cancel handle merging two events: ``is_set()`` when
    EITHER is. Lets a hedge attempt hand workers/chaos ONE pollable
    object combining the per-query cancel (a sibling failed / the caller
    cancelled) with the attempt's private loser-cancel (it lost the
    hedge race). Members may be None or nested _EitherSets."""

    __slots__ = ("_a", "_b")

    def __init__(self, a, b):
        self._a = a
        self._b = b

    def is_set(self) -> bool:
        return (self._a is not None and self._a.is_set()) or (
            self._b is not None and self._b.is_set()
        )


class _RetryState:
    """Per-task retry bookkeeping: attempt count + the urls of workers
    whose attempts already failed (the re-dispatch routes around them)."""

    __slots__ = ("attempt", "excluded")

    def __init__(self) -> None:
        self.attempt = 0
        self.excluded: set[str] = set()


class WorkerResolver:
    """Cluster membership (the reference's WorkerResolver: get_urls)."""

    def get_urls(self) -> list[str]:
        raise NotImplementedError


class ChannelResolver:
    """URL -> worker channel (the reference's ChannelResolver)."""

    def get_worker(self, url: str) -> Worker:
        raise NotImplementedError


class InMemoryCluster(WorkerResolver, ChannelResolver):
    """N in-process workers (the InMemoryChannelResolver fake cluster used by
    the reference's whole TPC suite, `src/test_utils/`)."""

    def __init__(self, num_workers: int, ttl_seconds: float = 600.0):
        self.workers = {
            f"mem://worker-{i}": Worker(f"mem://worker-{i}", ttl_seconds)
            for i in range(num_workers)
        }
        for w in self.workers.values():
            # peers resolve each other through the cluster itself (the
            # in-memory duplex-pipe analogue, `in_memory_channel_resolver.rs`)
            w.peer_channels = self

    def get_urls(self) -> list[str]:
        return list(self.workers.keys())

    def get_worker(self, url: str) -> Worker:
        return self.workers[url]


class DynamicCluster(WorkerResolver, ChannelResolver):
    """Epoch-versioned MUTABLE cluster membership (the reference's
    WorkerResolver as a dynamic layer, SURVEY §1): workers `add_worker`/
    `remove_worker`/`drain_worker` at any time — including mid-query — and
    every mutation bumps the monotonically increasing `membership_epoch`
    the coordinator keys its per-membership caches on.

    Three membership roles:

      active    listed by `get_urls()` — eligible for new task dispatch
      draining  NOT listed by `get_urls()` (no new tasks) but still
                resolvable via `get_worker` so in-flight tasks finish and
                staged peer-producer plans keep serving pulls; removed by
                `finish_drains()` only once EMPTY (zero registry entries,
                zero staged TableStore slices)
      departed  `get_worker` raises the retryable WorkerUnavailableError —
                the coordinator's retry machinery re-routes/re-stages the
                affected work onto survivors

    `remove_worker` models an abrupt leave (process death): the worker's
    registry and shipment store are released, as the dying process would
    release them — so leak accounting stays exact across churn."""

    def __init__(self, num_workers: int = 0, ttl_seconds: float = 600.0,
                 worker_factory: Optional[Callable[[str], Worker]] = None):
        self._lock = threading.RLock()
        self._epoch = 0  # guarded-by: _lock
        self._active: dict[str, Worker] = {}  # guarded-by: _lock
        self._draining: dict[str, Worker] = {}  # guarded-by: _lock
        self._departed: set[str] = set()  # guarded-by: _lock
        self._ttl = ttl_seconds
        self._factory = worker_factory or (
            lambda url: Worker(url, ttl_seconds)
        )
        for i in range(num_workers):
            self.add_worker(f"mem://worker-{i}")

    # -- resolver surface ---------------------------------------------------
    @property
    def membership_epoch(self) -> int:
        with self._lock:
            return self._epoch

    def get_urls(self) -> list[str]:
        with self._lock:
            return list(self._active.keys())

    def get_worker(self, url: str) -> Worker:
        with self._lock:
            w = self._active.get(url) or self._draining.get(url)
            if w is not None:
                return w
        raise WorkerUnavailableError(
            f"worker {url} is not in the cluster membership"
            + (" (departed)" if url in self._departed else ""),
            worker_url=url,
        )

    # -- membership mutation -------------------------------------------------
    def add_worker(self, worker) -> Worker:
        """Add ``worker`` (a Worker instance or a url for the factory).
        A joining worker is immediately eligible for new dispatches —
        including later stages of an already-running query."""
        w = worker if isinstance(worker, Worker) else self._factory(worker)
        with self._lock:
            if w.url in self._active or w.url in self._draining:
                raise ValueError(f"worker {w.url} already in the cluster")
            # peers resolve each other through the cluster itself, so a
            # joiner can serve AND issue peer pulls right away
            w.peer_channels = self
            self._active[w.url] = w
            self._departed.discard(w.url)
            self._epoch += 1
        return w

    def remove_worker(self, url: str, release: bool = True) -> None:
        """Abrupt leave: the url stops resolving NOW. ``release`` frees the
        worker's registry + shipment store the way its dying process would
        (in-flight coordinator attempts against it fail retryably)."""
        with self._lock:
            w = self._active.pop(url, None) or self._draining.pop(url, None)
            if w is None:
                raise KeyError(f"worker {url} not in the cluster")
            self._departed.add(url)
            self._epoch += 1
        if release:
            w.registry.clear()
            w.table_store.tables.clear()

    def drain_worker(self, url: str) -> None:
        """Graceful half of leave: accept no NEW tasks (the url drops out
        of `get_urls()`), keep serving in-flight work and staged peer
        producers, and become removable only once empty."""
        with self._lock:
            w = self._active.pop(url, None)
            if w is None:
                if url in self._draining:
                    return  # already draining
                raise KeyError(f"worker {url} not in the active membership")
            self._draining[url] = w
            self._epoch += 1

    # -- drain accounting ----------------------------------------------------
    def in_flight(self, url: str) -> int:
        """Tasks the worker still holds: registry entries (staged or
        executing) — zero plus an empty shipment store means drained."""
        with self._lock:
            w = self._active.get(url) or self._draining.get(url)
        return 0 if w is None else len(w.registry)

    def is_drained(self, url: str) -> bool:
        with self._lock:
            w = self._draining.get(url)
        return (
            w is not None
            and len(w.registry) == 0
            and not w.table_store.tables
        )

    def finish_drains(self) -> list[str]:
        """Remove every draining worker that reached empty; -> the removed
        urls. A draining worker still holding tasks/slices stays — the
        'removed only when empty' contract."""
        removed = []
        with self._lock:
            for url, w in list(self._draining.items()):
                if len(w.registry) == 0 and not w.table_store.tables:
                    del self._draining[url]
                    self._departed.add(url)
                    self._epoch += 1
                    removed.append(url)
        return removed

    def wait_drained(self, url: str, timeout_s: float = 10.0,
                     poll_s: float = 0.01) -> bool:
        """Block until ``url`` is drained (then remove it) or the timeout
        elapses; -> whether it drained."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if self.is_drained(url):
                self.finish_drains()
                return True
            _time.sleep(poll_s)
        return False

    # -- introspection -------------------------------------------------------
    @property
    def workers(self) -> dict:
        """url -> Worker for every member still owning resources (active +
        draining) — the InMemoryCluster-compatible leak-check surface."""
        with self._lock:
            return {**self._active, **self._draining}

    def is_departed(self, url: str) -> bool:
        with self._lock:
            return url in self._departed

    def membership_snapshot(self) -> dict:
        with self._lock:
            return {
                "epoch": self._epoch,
                "active": list(self._active.keys()),
                "draining": list(self._draining.keys()),
                "departed": sorted(self._departed),
            }


@dataclass
class Coordinator:
    resolver: WorkerResolver
    channels: ChannelResolver
    route_tasks: Optional[Callable] = None  # custom routing hook
    collect_metrics: bool = True
    metrics: dict = field(default_factory=dict)  # TaskKey -> worker metrics
    # (query_id, stage_id) -> streaming-plane stats (bytes/chunks/early_exit)
    stream_metrics: dict = field(default_factory=dict)  # per-query: swept-by sweep_query
    # `SET distributed.*` options propagated to every worker with the plan
    # (the config-over-headers flow, `config_extension_ext.rs:1-82`)
    config_options: dict = field(default_factory=dict)
    # user headers forwarded verbatim (`passthrough_headers.rs`)
    passthrough_headers: dict = field(default_factory=dict)
    # reject workers whose version differs (rolling-upgrade safety — the
    # reference's GetWorkerInfo + with_version, `worker_service.rs:175-179`)
    expected_version: Optional[str] = None
    # per-task execute-latency sketch, mergeable across queries
    latency: "object" = None
    # worker circuit breakers (runtime/health.py), created on first failure
    # and persistent across queries on this coordinator — a worker
    # quarantined by one query stays routed-around for the next
    health: "object" = None
    # retry/quarantine/timeout counters (runtime/metrics.py FaultCounters)
    faults: FaultCounters = field(default_factory=FaultCounters)
    # per-stage scheduler spans + query walls (runtime/metrics.py), the
    # observability surface of the stage-DAG scheduler: explain_analyze
    # renders them as a critical-path summary whose overlap factor
    # (sum stage wall / query wall) proves inter-stage overlap
    stage_metrics: MetricsStore = field(default_factory=MetricsStore)
    # -- multi-query serving hooks (runtime/serving.py) ---------------------
    # external stage scheduler: an object with submit(fn, cost_hint=0) ->
    # concurrent.futures.Future. When set, stage jobs (and the root stage)
    # run on the GLOBAL cross-query pool under its fair-share policy
    # instead of a per-query ThreadPoolExecutor — the generalization of
    # the per-query stage-DAG scheduler to the whole serving tier
    stage_pool: "object" = None
    # pre-installed per-query cancel event: lets an async QueryHandle
    # cancel a query BEFORE and DURING execute() without racing the
    # event's creation (execute reuses this one when present)
    cancel_event: "object" = None
    # called with the query_id after every execute() completes (success,
    # failure, or cancellation): the serving tier sweeps per-query chaos/
    # metrics state here so a long-lived process sheds resolved queries
    on_query_end: Optional[Callable[[str], None]] = None
    # distributed-tracing store (runtime/tracing.py). The process-wide
    # default backs `ctx.last_trace()` / `QueryHandle.trace()` /
    # explain_analyze's profile fold; per-query Tracers hang on
    # `self._tracer` for the execute's duration (NULL_TRACER when
    # `SET distributed.tracing` is off — the always-cheap-when-off path)
    trace_store: "object" = None
    # in-flight speculative-attempt budget (runtime/metrics.py
    # HedgeBudget), shared across every per-query coordinator under the
    # serving tier so the hedge stampede bound is cluster-wide; created
    # lazily on the first hedge decision otherwise
    hedges: "object" = None
    # per-query checkpoint facade (runtime/checkpoint.py
    # QueryCheckpointer): when set, every materialized (MemoryScan)
    # exchange boundary snapshots its consumer slices on completion and
    # restores them — fingerprint-validated — on a resumed execute
    checkpoints: "object" = None
    # cross-query result/sub-plan cache (runtime/result_cache.py
    # ResultCache): when set, materialized exchange boundaries save
    # their frontier under the subtree's pre-hoist fingerprint and a
    # LATER query sharing that prefix restores it instead of
    # re-executing the producer stage (checkpoint restore, which is
    # intra-query and validates against worker slices, wins first)
    result_cache: "object" = None
    # measured peak staged bytes attributed to this coordinator's
    # executes across the workers' TableStores (harvested by
    # sweep_query): the MEASURED side of the serving tier's
    # estimate-vs-reality admission loop
    staged_peak_bytes: int = 0

    #: declarative concurrency model (tools/check_concurrency.py): these
    #: per-execute caches are shared by sibling-stage fan-out threads and
    #: every write outside execute's fresh-reset must hold the named
    #: lock. (`metrics`/`stream_metrics`/`_peer_shipped` are deliberately
    #: NOT declared: they are keyed per task and rely on GIL-atomic
    #: single-op dict/list mutation, snapshotted via list() in C —
    #: see sweep_query.)
    _GUARDED_BY = {
        "_span_shipped": "_span_lock",
        "_span_ok_cache": "_span_lock",
        "_peer_url_map": "_peer_heal_lock",
        "_peer_stale": "_peer_heal_lock",
    }

    def _tr(self):
        """The current query's tracer (NULL_TRACER outside execute or with
        tracing off): one unconditional accessor so every instrumentation
        site stays a plain call, never a branch tree."""
        return getattr(self, "_tracer", NULL_TRACER)

    def _event(self, name: str, **attrs) -> None:
        """One fault-path transition, fanned to BOTH sinks: the query's
        trace-event stream (visible when `SET distributed.tracing` is
        on) and the always-on structured event log
        (runtime/eventlog.py), stamped with this query's id — so logs,
        traces, and the `dftpu_faults` counters correlate on the same
        query/stage/task ids instead of the old trace-only asymmetry."""
        self._tr().event(name, **attrs)
        from datafusion_distributed_tpu.runtime.eventlog import log_event

        log_event(name, query_id=getattr(self, "last_query_id", None),
                  **attrs)

    def last_query_trace(self):
        """The most recent query's QueryTrace on this coordinator (None
        without tracing). Naming convention across surfaces:
        ``*query_trace()`` returns the QueryTrace object,
        ``trace()``/``last_trace()`` (QueryHandle / SessionContext)
        return the exported Chrome trace-event dict."""
        qid = getattr(self, "last_query_id", None)
        store = self.trace_store or DEFAULT_TRACE_STORE
        return store.get(qid) if qid else None

    def overlap_factor(self, query_id: Optional[str] = None):
        """sum(stage wall) / query wall for ``query_id`` (default: most
        recent). >1.0 means independent stages genuinely overlapped."""
        return self.stage_metrics.stage_schedule_summary(query_id).get(
            "overlap_factor"
        )

    def execute(self, plan: ExecutionPlan) -> Table:
        """Run a distributed plan (exchange-staged) across the workers and
        return the (replicated) root result."""
        from datafusion_distributed_tpu.plan.verify import (
            enforce_verification,
        )
        from datafusion_distributed_tpu.runtime.metrics import LatencySketch

        # static verification BEFORE any dispatch (plan/verify.py): a
        # malformed staged plan is rejected here — the cheapest point — so
        # no worker compiles/executes against it. Memoized on the plan
        # object, so the retry loops' re-submissions verify once.
        enforce_verification(plan, options=self.config_options,
                             context="coordinator pre-dispatch")
        if self.latency is None:
            self.latency = LatencySketch()
        if self.expected_version is not None:
            self._check_worker_versions()
        query_id = uuid.uuid4().hex
        # stamp the submitted plan object with its query id so
        # explain_analyze can bind the stage-schedule block to THIS
        # query's spans (a long-lived coordinator holds spans for many)
        plan._last_query_id = query_id
        self.last_query_id = query_id
        # push the enforced worker memory budget (when configured) to the
        # in-process workers BEFORE the first dispatch: dispatch encodes
        # stage slices into the destination store ahead of set_plan, so
        # the budget must be live by then (gRPC workers apply it from the
        # shipped task config instead). Not a trace-relevant key — knob
        # flips never recompile.
        self._apply_worker_budgets()
        # distributed tracing (runtime/tracing.py): NULL_TRACER when off
        trace_store = self.trace_store or DEFAULT_TRACE_STORE
        try:
            sample_rate = float(
                self.config_options.get("tracing_sample_rate", 0.125)
            )
        except (TypeError, ValueError):
            sample_rate = 0.125
        self._tracer = trace_store.begin(
            query_id, resolve_tracing_mode(self.config_options),
            sample_rate=sample_rate,
        )
        # fresh per execute: stage ids repeat across queries, and a stale
        # hint map would stamp the PREVIOUS query's planner estimates
        # onto this query's stage spans
        self._stage_span_hints = {}
        # producer tasks shipped but never coordinator-executed (peer data
        # plane): released at query end — the reference's query-end EOS
        # notifier role (`query_coordinator.rs:188-192`)
        self._peer_shipped: list = []
        # (query_id, stage_id) -> (prepared producer plan, t_prod, ttl):
        # the re-ship source when a worker holding a shipped peer-producer
        # plan departs the membership mid-query (_heal_departed_peers)
        self._peer_plan_registry: dict = {}  # per-query: swept-by sweep_query
        # accumulated ACROSS heal passes (healing is incremental — each
        # failing consumer heals when IT retries, possibly long after the
        # pass that moved a producer): producer key tuple -> the url now
        # serving it, and the set of shipped copies whose on-worker plan
        # pre-dates a spec rewrite and must be refreshed before trusted
        self._peer_url_map: dict = {}  # per-query: swept-by sweep_query
        self._peer_stale: set = set()  # per-query: swept-by sweep_query
        # per-query caches (span plans are keyed by query_id; the plan-walk
        # verdicts key by object id which is only stable within a query).
        # The lock serializes span check-and-ship: concurrent stage tasks
        # of one span must not double-ship (double SPMD execution + a
        # leaked first shipment).
        self._span_shipped: dict = {}
        self._span_ok_cache: dict = {}
        import time as _time
        import threading as _threading

        self._span_lock = _threading.Lock()
        self._peer_heal_lock = _threading.Lock()
        # per-query cancel event: the FIRST fatal error sets it, and every
        # dispatch/execute path checks it before doing work — a failed
        # sibling stage/task cancels in-flight and not-yet-submitted work
        # instead of leaving orphaned tasks running (and their staged
        # TableStore slices leaking until TTL). FRESH per execute: the
        # overflow-retry loops re-enter execute() on this same object, and
        # a stale set event would abort every retry as cancelled. The
        # separate `cancel_event` field (the serving tier's
        # QueryHandle.cancel surface) is a read-only cancel REQUEST this
        # coordinator never sets — _check_cancelled honors both, so an
        # external cancel reaches any execute attempt without being
        # conflated with one attempt's internal teardown.
        self._cancel_event = _threading.Event()
        # hedge-attempt threads spawned this execute (appends are
        # GIL-atomic single-op list mutations like _peer_shipped, so no
        # lock is declared): joined in the finally below so every
        # loser's cleanup lands before the query resolves — the leak
        # gates observe a quiesced store, never a racing release
        self._hedge_threads: list = []
        # pipelined-shuffle feeder threads (one per pipelined boundary;
        # GIL-atomic appends like _hedge_threads): joined in the finally
        # so producer-side cleanup (task invalidation, staged-slice
        # release inside the pull retry loops) lands before the query
        # resolves and the leak gates observe a quiesced store
        self._stream_feeds: list = []
        # one `query_resumed` event per execute, on the first restore
        self._resume_traced = False
        if self.checkpoints is not None:
            # stamp this execute in the checkpoint session and
            # fingerprint the pristine exchange subtrees (restore keys)
            try:
                self.checkpoints.begin_execute(plan)
            except Exception:
                self.checkpoints = None  # never fail the query for it
        if self.result_cache is not None:
            # stamp this execute's pre-hoist exchange fingerprints so
            # boundaries can restore frontiers a PRIOR query produced
            # (cross-query sub-plan sharing, runtime/result_cache.py)
            try:
                self.result_cache.begin_query(query_id, plan)
            except Exception:
                self.result_cache = None  # never fail the query for it
        # pin this query's spans against the shared store's LRU for as
        # long as it runs (runtime/metrics.py begin/finish_query)
        self.stage_metrics.begin_query(query_id)
        q_t0 = _time.monotonic()
        tracer = self._tracer
        qspan = tracer.start_span("query", "query", query_id=query_id)
        if tracer.active:
            tracer.trace.root_id = qspan.span_id
        try:
            resolved = self._materialize_exchanges(plan, query_id)
            # the root stage: a single consumer task — routed through the
            # global serving pool when one is installed, so even a
            # single-stage query's heavy consumer competes under the
            # fair-share policy instead of bypassing it on this thread
            r_sub = _time.monotonic()
            if self.stage_pool is not None:
                fut = self.stage_pool.submit(
                    lambda: (_time.monotonic(), self._run_stage_task(
                        resolved, query_id, -1, 0, 1
                    ))
                )
                r_t0, out = fut.result()
            else:
                r_t0 = r_sub
                out = self._run_stage_task(
                    resolved, query_id, stage_id=-1, task_number=0,
                    task_count=1,
                )
            r_t1 = _time.monotonic()
            self.stage_metrics.record_stage_span(
                query_id, -1, r_sub, r_t0, r_t1, plane="root"
            )
            self._trace_stage_span(-1, r_sub, r_t0, r_t1, "root")
            self.stage_metrics.record_query_wall(
                query_id, r_t1 - q_t0
            )
            return out
        except BaseException:
            self._signal_cancel()
            raise
        finally:
            # drain hedge attempts FIRST: a loser's thread owns releasing
            # its staged slices, and the cancel plumbing (interruptible
            # chaos delays, gRPC wire deadlines, per-attempt events)
            # makes these joins short on cancellable surfaces. A loser
            # mid-compute on a surface with NO cancel parameter (plain
            # in-process Worker) cannot be interrupted from Python — a
            # final-stage straggler can then hold query COMPLETION (not
            # the result) until it finishes or the join budget expires;
            # `task_timeout_s` bounds that wall when set
            for t in self._hedge_threads:
                t.join(timeout=30.0)
            # drain pipelined feeders: on success they already finished
            # (the root stage consumed every partition); on failure the
            # cancel event stops their pullers at the next checkpoint —
            # either way their per-task cleanup runs before the query
            # resolves
            for t in self._stream_feeds:
                t.join(timeout=30.0)
            # release THIS query's shipped peer producers promptly (their
            # per-entry TTL is only the crash backstop, not the release
            # path — DFTPU301/307); sweep_query re-runs the same helper
            # idempotently for direct _peer_boundary users
            self._release_peer_tasks(query_id)
            # close the trace AFTER the peer sweep so last-drop worker
            # spans (peer producers report at query end) still splice
            tracer.end_span(qspan)
            trace_store.finish(query_id)
            self._tracer = NULL_TRACER
            self.stage_metrics.finish_query(query_id)
            if self.on_query_end is not None:
                try:
                    self.on_query_end(query_id)
                except Exception:
                    pass  # sweep hook must not mask the query's error

    def _apply_worker_budgets(self) -> None:
        """Apply `distributed.worker_memory_budget_bytes` (when present
        in the session config) to every reachable in-process worker
        store. Best-effort and idempotent; absent knob leaves env-set
        budgets untouched."""
        budget = self.config_options.get("worker_memory_budget_bytes")
        if budget is None:
            return
        try:
            urls = self.resolver.get_urls()
        except Exception:
            return
        for url in urls:
            try:
                store = getattr(self.channels.get_worker(url),
                                "table_store", None)
                if store is not None and hasattr(store, "set_budget"):
                    store.set_budget(budget)
            except Exception:
                pass  # a departed/wire worker: config ships it instead

    def _store_pressure_probe(self):
        """Producer-backpressure probe over the live workers' stores
        (None when no store exposes one — wire transports): True while
        ANY destination store is over its enforced budget, which the
        stream planes' StreamBudget turns into trickle-paced producers
        instead of a budget overrun."""
        try:
            urls = list(self.resolver.get_urls())
        except Exception:
            return None
        stores = []
        for url in urls:
            try:
                store = getattr(self.channels.get_worker(url),
                                "table_store", None)
            except Exception:
                continue
            if store is not None and hasattr(store, "under_pressure"):
                stores.append(store)
        if not stores:
            return None

        def probe() -> bool:
            return any(s.under_pressure() for s in stores)

        return probe

    def _release_peer_tasks(self, query_id: str) -> None:
        """Release every shipped peer-producer task belonging to
        ``query_id`` and forget it. Idempotent — released entries are
        removed from ``_peer_shipped``, so execute's finally and
        ``sweep_query`` can both call this (the latter covers direct
        ``_peer_boundary`` users that never enter execute)."""
        shipped = getattr(self, "_peer_shipped", None)
        if not shipped:
            return  # coordinator never executed (or nothing shipped)
        remaining = []
        for worker, key in list(shipped):
            if key.query_id != query_id:
                remaining.append((worker, key))
                continue
            try:
                # peer producers report metrics at query end (the
                # last-drop metrics flush rides no coordinator stream
                # to observe earlier)
                self._record_task_progress(worker, key)
            except Exception:
                pass
            try:
                if hasattr(worker, "release_task"):
                    worker.release_task(key)
                else:
                    worker.registry.invalidate(key)
            except Exception:
                pass  # cleanup must not mask the query's own error
        shipped[:] = remaining

    def sweep_query(self, query_id: str) -> None:
        """Drop THIS query's accumulated per-task/stream metrics — the
        unbounded per-query dicts a long-lived serving coordinator would
        otherwise grow forever (stage spans are separately LRU-bounded in
        MetricsStore and stay for explain_analyze). Callers that want the
        data harvest it before sweeping; the serving tier calls this from
        `on_query_end` once the QueryHandle captured its summary.
        Also harvests the query's per-store staging attribution into
        `staged_peak_bytes` (summed across workers, maxed across this
        coordinator's executes) — the measured peak the serving tier
        re-costs admission with."""
        peak = 0
        try:
            urls = list(self.resolver.get_urls())
        except Exception:
            urls = []
        for url in urls:
            try:
                store = getattr(self.channels.get_worker(url),
                                "table_store", None)
                if store is not None and hasattr(
                    store, "sweep_query_attribution"
                ):
                    peak += store.sweep_query_attribution(query_id)
            except Exception:
                pass  # departed worker: its attribution died with it
        if peak > self.staged_peak_bytes:
            self.staged_peak_bytes = peak
        if self.result_cache is not None:
            # shed this execute's sub-plan fingerprint map (the cached
            # frontiers themselves stay — they are the cross-query point)
            try:
                self.result_cache.end_query(query_id)
            except Exception:
                pass
        # list() snapshots are taken in C (no GIL release) so sweeping one
        # query never races another in-flight query's inserts
        for key in [k for k in list(self.metrics) if k.query_id == query_id]:
            self.metrics.pop(key, None)
        for key in [
            k for k in list(self.stream_metrics) if k[0] == query_id
        ]:
            self.stream_metrics.pop(key, None)
        # peer-plane state: release any still-shipped producer tasks
        # (re-entrant no-op after execute's finally), then drop the
        # query's re-ship plans and heal bookkeeping — a reused
        # coordinator otherwise grows these forever (DFTPU307)
        self._release_peer_tasks(query_id)
        plans = getattr(self, "_peer_plan_registry", None)
        if plans:
            for k in [k for k in list(plans) if k[0] == query_id]:
                plans.pop(k, None)
        heal_lock = getattr(self, "_peer_heal_lock", None)
        if heal_lock is not None:
            with self._peer_heal_lock:
                url_map = getattr(self, "_peer_url_map", None) or {}
                for k in [k for k in list(url_map) if k[0] == query_id]:
                    url_map.pop(k, None)
                stale = getattr(self, "_peer_stale", None) or set()
                for k in [k for k in list(stale) if k[0] == query_id]:
                    stale.discard(k)
        spans = getattr(self, "_span_shipped", None)
        ok = getattr(self, "_span_ok_cache", None)
        if spans or ok:
            with self._span_lock:
                for k in [k for k in (spans or ()) if k[0] == query_id]:
                    spans.pop(k, None)
                # DFTPU201 fix: the ok-cache shares the span lock with
                # the shipment map — sweeping it unlocked raced
                # _try_dispatch_span's check-then-insert
                for k in [k for k in (ok or ()) if k[0] == query_id]:
                    ok.pop(k, None)
        # query end is the leak-harness checkpoint: any tracked resource
        # still attributed to this query is a leak
        from datafusion_distributed_tpu.runtime import leakcheck

        leakcheck.sweep_query(query_id)

    def _check_worker_versions(self) -> None:
        from datafusion_distributed_tpu.runtime.errors import WorkerError

        for url in self.resolver.get_urls():
            info = self.channels.get_worker(url).get_info()
            v = info.get("version")
            if v != self.expected_version:
                raise WorkerError(
                    f"version skew: worker {url} runs {v!r}, coordinator "
                    f"expects {self.expected_version!r}",
                    worker_url=url,
                )

    # -- stage materialization ----------------------------------------------
    def _materialize_exchanges(
        self, plan: ExecutionPlan, query_id: str
    ) -> ExecutionPlan:
        """Materialize every exchange boundary, bottom-up.

        Two schedulers produce byte-identical results:

        - `stage_parallelism > 1` (default: the worker count): the stage-
          DAG scheduler — one pass builds the dependency graph of
          exchange subtrees (planner/distributed.py build_stage_dag),
          then every dependency-free stage is submitted to a bounded pool
          concurrently and consumers release as their feeds materialize.
          Sibling subtrees — a hash join's build and probe sides, the
          producer stages of every co-shuffled group, union branches —
          overlap across the cluster instead of idling the worker pool
          between them (the reference's concurrent async fan-out,
          `query_coordinator.rs:140-222`).
        - `stage_parallelism = 1`, or a plan build_stage_dag cannot
          schedule: the sequential depth-first recursion (pre-scheduler
          behavior).
        """
        par = self._stage_parallelism()
        dag = None
        if par > 1 or self.stage_pool is not None:
            from datafusion_distributed_tpu.planner.distributed import (
                build_stage_dag,
            )

            dag = build_stage_dag(plan)
        tr = self._tr()
        if tr.active and dag is not None:
            # planner stage cost hints become span attributes: the stage
            # spans recorded later pick these up by stage id
            self._stage_span_hints = {
                sid: n.span_attrs() for sid, n in dag.nodes.items()
            }
        if dag is None or (
            len(dag.nodes) <= 1 and self.stage_pool is None
        ):
            # a global serving pool routes even single-stage plans through
            # the DAG path so every stage competes under the fair-share
            # policy; without one a single stage gains nothing from it
            with tr.span("schedule", "schedule", mode="sequential"):
                return self._materialize_exchanges_sequential(
                    plan, query_id
                )
        with tr.span("schedule", "schedule", mode="dag",
                     stages=len(dag.nodes), parallelism=par):
            return self._materialize_exchanges_dag(plan, query_id, dag, par)

    def _stage_parallelism(self) -> int:
        """`SET distributed.stage_parallelism`: the in-flight stage budget
        (memory control — every in-flight stage holds its producer outputs).
        0/unset = auto: the LIVE worker count at query start (task routing
        inside each stage re-resolves membership per dispatch, so joiners
        still receive tasks even though the stage budget is fixed)."""
        n = self._opt_int("stage_parallelism")
        if n <= 0:
            n = self._live_worker_count()
        return n

    # -- membership awareness -------------------------------------------------
    def _membership_token(self, urls=None):
        """Cache key for everything derived from cluster membership. An
        epoch-versioned resolver (DynamicCluster) keys by its monotonic
        `membership_epoch`; static resolvers key by the url tuple itself,
        so even a user mutating `InMemoryCluster.workers` between
        dispatches invalidates the derived caches."""
        ep = getattr(self.resolver, "membership_epoch", None)
        if isinstance(ep, int):
            return ("epoch", ep)
        if urls is None:
            try:
                urls = self.resolver.get_urls()
            except Exception:
                urls = []
        return ("urls", tuple(urls))

    def _note_membership(self, urls=None):
        """Observe the current membership; on a CHANGE, prune
        health/quarantine state for workers that departed — a shrunk or
        grown cluster must not carry breaker state for endpoints that no
        longer exist. Per-membership caches (peer capability, mesh span
        width) are not cleared here — each stores the token it was
        computed under and is ignored on mismatch, so a slow probe racing
        a membership change can only install a verdict stamped with its
        own stale token, never poison the new epoch."""
        tok = self._membership_token(urls)
        if tok == getattr(self, "_membership_seen", None):
            return tok
        self._membership_seen = tok
        self._event(
            "membership_change",
            epoch=tok[1] if tok[0] == "epoch" else None,
        )
        if self.health is not None:
            for _u in self.health.prune(self._full_membership_urls()):
                self.faults.bump("health_entries_pruned")
        return tok

    def _full_membership_urls(self) -> list[str]:
        """Active + draining urls — the set that still owns resources.
        Draining workers keep their health state (they are finishing
        work); only truly departed workers are pruned."""
        snap = getattr(self.resolver, "membership_snapshot", None)
        if callable(snap):
            try:
                s = snap()
                return list(s.get("active", ())) + list(
                    s.get("draining", ())
                )
            except Exception:
                pass
        try:
            return self.resolver.get_urls()
        except Exception:
            return []

    def _live_worker_count(self) -> int:
        try:
            urls = self.resolver.get_urls()
        except Exception:
            return 1
        self._note_membership(urls)
        return max(len(urls), 1)

    def _zero_copy(self) -> bool:
        """`SET distributed.zero_copy` (default on): the view-based data
        plane — host-view regroup/chunking and buffer-sharing staging."""
        from datafusion_distributed_tpu.ops.table import zero_copy_enabled

        return zero_copy_enabled(self.config_options)

    def _materialize_exchanges_sequential(
        self, plan: ExecutionPlan, query_id: str
    ) -> ExecutionPlan:
        children = [
            self._materialize_exchanges_sequential(c, query_id)
            for c in plan.children()
        ]
        if children:
            plan = self._bailout_multiway(
                self._widen_bailed_out_merge(
                    plan.with_new_children(children)
                ),
                query_id,
            )
        if not getattr(plan, "is_exchange", False):
            return plan
        import time as _time

        t0 = _time.monotonic()
        scan = self._materialize_exchange_node(
            plan, plan.children()[0], query_id
        )
        sid = plan.stage_id if plan.stage_id is not None else 0
        if isinstance(scan, StreamScanExec):
            # pipelined boundary reached through the sequential fallback
            # (e.g. an unschedulable hand-built plan at parallelism > 1):
            # the span records at feed completion like the DAG path
            scan.feed.on_complete(
                lambda end_s, s=sid, t=t0:
                self._record_stage_span(query_id, s, t, t, end_s)
            )
        else:
            self._record_stage_span(query_id, sid, t0, t0,
                                    _time.monotonic())
        return scan

    def _materialize_exchanges_dag(
        self, plan: ExecutionPlan, query_id: str, dag, parallelism: int
    ) -> ExecutionPlan:
        """Event-driven stage scheduler: submit every dependency-free stage
        to a bounded pool, release consumers as their feeds materialize.
        All DAG bookkeeping runs on THIS thread (no lock needed); stage
        jobs only materialize their own exchange. The first fatal error
        sets the per-query cancel event — in-flight stages abort at their
        next dispatch/execute checkpoint and release their staged slices,
        not-yet-ready stages never submit — and the error re-raises after
        the in-flight jobs drained (deterministic teardown)."""
        import concurrent.futures as cf
        import time as _time

        nodes = dag.nodes
        resolved: dict = {}  # stage_id -> consumer-side scan

        def resolve(node: ExecutionPlan) -> ExecutionPlan:
            # rebuild `node`'s subtree with every frontier exchange
            # replaced by its materialized scan (never descends past an
            # exchange boundary — nested exchanges live inside their
            # consumer's already-resolved subtree)
            if getattr(node, "is_exchange", False):
                return resolved[node.stage_id]
            children = [resolve(c) for c in node.children()]
            if not children:
                return node
            return self._bailout_multiway(
                self._widen_bailed_out_merge(
                    node.with_new_children(children)
                ),
                query_id,
            )

        waiting = {sid: set(n.deps) for sid, n in nodes.items()}
        consumers = dag.consumers_map()
        first_error: Optional[BaseException] = None
        first_cancel: Optional[BaseException] = None

        def job(exchange, submit_s):
            self._check_cancelled()
            t0 = _time.monotonic()
            producer = resolve(exchange.children()[0])
            scan = self._materialize_exchange_node(
                exchange, producer, query_id
            )
            if isinstance(scan, StreamScanExec):
                # pipelined boundary: the job resolved at FIRST SLICE —
                # consumers release now while producers keep streaming.
                # The stage span is recorded by the feed at COMPLETION
                # (same submit/start as the materialized plane would
                # use), so overlap-factor/explain_analyze keep covering
                # the stage's true production window.
                sid = (exchange.stage_id
                       if exchange.stage_id is not None else 0)
                scan.feed.on_complete(
                    lambda end_s, s=sid, sub=submit_s, t=t0:
                    self._record_stage_span(query_id, s, sub, t, end_s)
                )
                return scan, submit_s, t0, None
            return scan, submit_s, t0, _time.monotonic()

        # the stage jobs' executor: a per-query bounded pool, or — under
        # the serving tier — the GLOBAL cross-query scheduler installed as
        # `stage_pool`, whose fair-share policy decides which query's
        # ready stage gets the next slot (runtime/serving.py). Either way
        # this thread keeps all DAG bookkeeping; only the job placement
        # policy changes.
        ext = self.stage_pool
        pool = None
        if ext is None:
            pool = cf.ThreadPoolExecutor(
                max_workers=parallelism, thread_name_prefix="dftpu-stage"
            )
        try:
            futs: dict = {}
            # ready-but-unsubmitted stage ids: with the EXTERNAL pool the
            # per-query `stage_parallelism` budget still bounds THIS
            # query's in-flight stages (its documented memory-control
            # role — every in-flight stage holds its producer outputs);
            # the global pool's slots bound the tier, not the query. The
            # internal pool needs no backlog: max_workers IS the bound.
            backlog: list = []

            def submit(sid: int) -> None:
                node = nodes[sid]
                sub_t = _time.monotonic()
                if ext is not None:
                    fut = ext.submit(
                        lambda e=node.exchange, t=sub_t: job(e, t),
                        cost_hint=node.est_bytes,
                    )
                else:
                    fut = pool.submit(job, node.exchange, sub_t)
                futs[fut] = sid

            def enqueue(sid: int) -> None:
                if ext is not None and len(futs) >= parallelism:
                    backlog.append(sid)
                else:
                    submit(sid)

            for sid in sorted(
                s for s, deps in waiting.items() if not deps
            ):
                enqueue(sid)
            replan_active = False
            while futs:
                done, _ = cf.wait(
                    list(futs), return_when=cf.FIRST_COMPLETED
                )
                for f in sorted(done, key=lambda f: futs[f]):
                    sid = futs.pop(f)
                    try:
                        scan, sub_s, t0, t1 = f.result()
                    except TaskCancelledError as e:
                        if first_cancel is None:
                            first_cancel = e
                        continue
                    except BaseException as e:
                        if first_error is None:
                            first_error = e
                        self._signal_cancel()
                        continue
                    resolved[sid] = scan
                    if t1 is not None:  # pipelined spans record at feed
                        self._record_stage_span(query_id, sid, sub_s, t0,
                                                t1)
                    # closed-loop re-cost: a stage whose measured output
                    # cardinality diverged far from its estimate rescales
                    # the not-yet-submitted downstream frontier, so the
                    # backlog promotion below dispatches cheapest-first
                    # on CORRECTED bytes (scheduling only — plan
                    # structure and results are untouched)
                    if self._maybe_replan(
                        query_id, sid, nodes, scan,
                        set(futs.values()) | set(resolved),
                    ):
                        replan_active = True
                    for c in sorted(consumers.get(sid, ())):
                        waiting[c].discard(sid)
                        if not waiting[c] and first_error is None and (
                            not self._cancelled()
                        ):
                            enqueue(c)
                # freed budget: promote backlogged ready stages (in
                # deterministic stage-id order; after a replan, in
                # deterministic corrected-cost order)
                if backlog and first_error is None and not self._cancelled():
                    if replan_active:
                        backlog.sort(
                            key=lambda s: (int(nodes[s].est_bytes or 0), s)
                        )
                    else:
                        backlog.sort()
                    while backlog and len(futs) < parallelism:
                        submit(backlog.pop(0))
        finally:
            if pool is not None:
                pool.shutdown(wait=True)
        if first_error is not None:
            raise first_error
        if first_cancel is not None:
            # only cancellations surfaced: something upstream (another
            # thread sharing this coordinator) set the event — propagate
            raise first_cancel
        # a cancel can land in the window where every in-flight job
        # completes cleanly: downstream stages are then silently skipped
        # (the enqueue gate), futs drains, and neither error slot is set —
        # resolving the partial frontier would KeyError on a stage that
        # never ran. Surface the cancel like a job would have.
        self._check_cancelled()
        return resolve(plan)

    def _record_stage_span(self, query_id: str, stage_id: int,
                           submit_s: float, start_s: float,
                           end_s: float) -> None:
        sm = self.stream_metrics.get((query_id, stage_id))
        plane = (sm.get("plane", "stream") if sm else "bulk")
        self.stage_metrics.record_stage_span(
            query_id, stage_id, submit_s, start_s, end_s, plane=plane
        )
        self._trace_stage_span(stage_id, submit_s, start_s, end_s, plane)

    def _trace_stage_span(self, stage_id: int, submit_s: float,
                          start_s: float, end_s: float,
                          plane: str) -> None:
        """Record a stage's trace span under the pre-reserved stage span
        id (task spans created while the stage ran already parent to it);
        planner cost hints (StageDagNode.span_attrs) ride as attributes."""
        tr = self._tr()
        if not tr.active:
            return
        attrs = dict(getattr(self, "_stage_span_hints", {}).get(
            stage_id, ()
        ))
        attrs.update(
            stage=stage_id, plane=plane,
            queue_s=round(max(start_s - submit_s, 0.0), 6),
        )
        tr.finish_reserved(
            ("stage", stage_id),
            "root" if stage_id == -1 else f"stage {stage_id}",
            "stage", submit_s, end_s, **attrs,
        )

    # -- per-query cancellation ---------------------------------------------
    def _cancelled(self) -> bool:
        """Whether this query should stop: the per-execute internal event
        (a sibling stage/task failed fatally) OR the externally-owned
        cancel request (serving-tier QueryHandle.cancel)."""
        ev = getattr(self, "_cancel_event", None)
        if ev is not None and ev.is_set():
            return True
        ext = self.cancel_event
        return ext is not None and ext.is_set()

    def _check_cancelled(self) -> None:
        """Raise if this query's cancel event is set (a sibling stage or
        task already failed fatally, or an external cancel request).
        Checked at every dispatch/execute boundary so orphaned work stops
        instead of running to completion against a query that can no
        longer succeed."""
        if self._cancelled():
            self._event("task_cancelled")
            raise TaskCancelledError(
                "query cancelled: a sibling stage/task failed or the "
                "caller cancelled"
            )

    def _signal_cancel(self) -> None:
        ev = getattr(self, "_cancel_event", None)
        if ev is not None:
            if not ev.is_set():
                self._event("query_cancel")
            ev.set()

    def _materialize_exchange_node(
        self, plan: ExecutionPlan, producer: ExecutionPlan, query_id: str
    ) -> ExecutionPlan:
        """Materialize ONE exchange whose producer subtree is fully
        resolved (every nested boundary already a scan): run the producer
        stage through the appropriate data plane and return the
        consumer-side scan."""
        stage_id = plan.stage_id if plan.stage_id is not None else 0
        t_prod = self._producer_task_count(plan, producer)
        tr = self._tr()
        with tr.span("exchange", "exchange",
                     parent=tr.reserved_id(("stage", stage_id)),
                     stage=stage_id, exchange=type(plan).__name__,
                     producer_tasks=t_prod):
            restored = self._restore_stage_checkpoint(
                plan, producer, query_id, stage_id
            )
            if restored is not None:
                return restored
            restored = self._restore_subplan_cache(
                plan, producer, query_id, stage_id
            )
            if restored is not None:
                return restored
            scan = self._materialize_exchange_body(
                plan, producer, query_id, stage_id, t_prod
            )
            self._save_stage_checkpoint(query_id, stage_id, t_prod, scan)
            self._save_subplan_cache(query_id, stage_id, t_prod, scan)
            return scan

    # -- query checkpoint/resume (runtime/checkpoint.py) ---------------------
    def _checkpoint_eligible(self) -> bool:
        """Whether this coordinator's stage lattices are deterministic
        enough to snapshot/restore (the AdaptiveCoordinator re-derives
        consumer counts from runtime LoadInfo and opts out)."""
        return True

    def _restore_stage_checkpoint(self, plan, producer, query_id: str,
                                  stage_id: int):
        """Consumer-side scan rebuilt from a valid stage checkpoint, or
        None (no checkpointer / miss / fingerprint mismatch / staged-
        slice loss — the latter two re-execute the stage, whose own
        producers still restore from THEIR checkpoints: the partially-
        lost-frontier heal)."""
        ck = self.checkpoints
        if ck is None or not self._checkpoint_eligible():
            return None
        hit, reason = ck.restore(stage_id)
        if hit is None:
            if reason == "fp_mismatch":
                self.faults.bump("checkpoint_fp_mismatch")
                self._event("checkpoint_fp_mismatch", stage=stage_id)
            elif reason == "slice_lost":
                self.faults.bump("checkpoint_slices_lost")
                self._event("checkpoint_slices_lost", stage=stage_id)
            return None
        slices, replicated, pinned, _t_prod = hit
        scan = MemoryScanExec(slices, producer.schema(), pinned=pinned,
                              replicated=replicated)
        self.faults.bump("checkpoint_stages_restored")
        if not self._resume_traced:
            # first restored stage of this execute: the query is resuming
            self._resume_traced = True
            self.faults.bump("queries_resumed")
            self._event("query_resumed", stage=stage_id)
        self.stream_metrics[(query_id, stage_id)] = {
            "plane": "checkpoint",
            "coordinator_bytes": 0,
            "partitions": len(slices),
        }
        self._seed_consumer_scan(plan, scan)
        return scan

    def _save_stage_checkpoint(self, query_id: str, stage_id: int,
                               t_prod: int, scan) -> None:
        """Snapshot a just-materialized boundary. Only MemoryScan results
        checkpoint — a peer-plane boundary's data never materialized on
        the coordinator (its producers re-ship through the peer-heal
        path instead)."""
        ck = self.checkpoints
        if ck is None or not self._checkpoint_eligible():
            return
        if type(scan) is not MemoryScanExec:
            return
        if getattr(scan, "bailout_raw_rows", False):
            # a bailed-out boundary carries raw rows at a widened
            # capacity; a restore could not re-derive the consumer-side
            # merge widening (the annotation dies with the scan), so
            # this stage re-executes instead of restoring
            return
        staged = ck.save(stage_id, list(scan.tasks), scan.replicated,
                         scan.pinned, t_prod)
        if staged is not None:
            self.faults.bump("checkpoint_stages_saved")
            self._event(
                "checkpoint_saved", stage=stage_id,
                slices=len(scan.tasks), bytes=staged,
            )

    # -- cross-query sub-plan cache (runtime/result_cache.py) -----------------
    def _restore_subplan_cache(self, plan, producer, query_id: str,
                               stage_id: int):
        """Consumer-side scan rebuilt from a frontier a PRIOR query
        cached under this exchange subtree's pre-hoist fingerprint, or
        None. Slices come from the cache's own store (never a worker),
        so a restore is correct under any membership churn. Shares the
        checkpoint tier's eligibility gate: an adaptive coordinator's
        runtime-derived lattices opt out of both."""
        rc = self.result_cache
        if rc is None or not self._checkpoint_eligible():
            return None
        try:
            hit = rc.restore_subplan(query_id, stage_id)
        except Exception:
            return None  # cache trouble must never fail the query
        if hit is None:
            return None
        slices, replicated, pinned, _t_prod = hit
        scan = MemoryScanExec(slices, producer.schema(), pinned=pinned,
                              replicated=replicated)
        self.faults.bump("subplan_cache_stages_restored")
        self._event("subplan_cache_restored", stage=stage_id,
                    slices=len(slices))
        self.stream_metrics[(query_id, stage_id)] = {
            "plane": "result-cache",
            "coordinator_bytes": 0,
            "partitions": len(slices),
        }
        self._seed_consumer_scan(plan, scan)
        return scan

    def _save_subplan_cache(self, query_id: str, stage_id: int,
                            t_prod: int, scan) -> None:
        """Offer a just-materialized boundary to the cross-query cache.
        Same guards as `_save_stage_checkpoint`: only MemoryScan
        results (a peer-plane boundary never materialized here) and
        never a bailed-out boundary (its widened-capacity annotation
        dies with the scan)."""
        rc = self.result_cache
        if rc is None or not self._checkpoint_eligible():
            return
        if type(scan) is not MemoryScanExec:
            return
        if getattr(scan, "bailout_raw_rows", False):
            return
        try:
            staged = rc.save_subplan(
                query_id, stage_id, list(scan.tasks), scan.replicated,
                scan.pinned, t_prod,
            )
        except Exception:
            return
        if staged is not None:
            self._event("subplan_cache_saved", stage=stage_id,
                        slices=len(scan.tasks), bytes=staged)

    def _materialize_exchange_body(
        self, plan: ExecutionPlan, producer: ExecutionPlan, query_id: str,
        stage_id: int, t_prod: int,
    ) -> ExecutionPlan:
        if self._peer_plane_enabled(plan):
            scan = self._peer_boundary(plan, producer, query_id, stage_id,
                                       t_prod)
            if scan is not None:
                self._seed_consumer_scan(plan, scan)
                return scan
        if isinstance(plan, PartitionReplicatedExec):
            # producer is replicated: one task's output carries everything
            outputs = [
                self._run_stage_task(producer, query_id, stage_id, 0, t_prod)
            ]
        elif isinstance(
            plan, (CoalesceExchangeExec, BroadcastExchangeExec)
        ) and not (
            isinstance(plan, CoalesceExchangeExec) and plan.num_consumers > 1
        ):
            # N:1 coalesce / broadcast: the STREAMING data plane — chunked,
            # budget-bounded, LIMIT-aware (see _stream_stage_coalesced)
            merged = self._stream_stage_coalesced(
                plan, producer, query_id, stage_id, t_prod
            )
            scan = MemoryScanExec([merged], producer.schema(),
                                  replicated=True)
            self._seed_consumer_scan(plan, scan)
            return scan
        elif (
            isinstance(plan, ShuffleExchangeExec)
            and self._partition_streams_enabled(plan)
        ):
            if self._pipelined_shuffle_enabled(plan):
                # PIPELINED shuffle: producers stream partition slices
                # into a live feed and this boundary resolves at FIRST
                # SLICE — the consumer stage's tasks block only for
                # their own partition (runtime/streams.py StreamScanExec)
                scan = self._shuffle_stage_pipelined(
                    plan, producer, query_id, stage_id, t_prod
                )
                self._seed_consumer_scan(plan, scan)
                return scan
            # partition-range data plane: each producer serves its hash-
            # partitioned output over ONE multiplexed stream; the hashing
            # runs on the workers and the coordinator only demuxes
            slices = self._shuffle_stage_partition_streams(
                plan, producer, query_id, stage_id, t_prod
            )
            scan = MemoryScanExec(slices, producer.schema())
            self._seed_consumer_scan(plan, scan)
            return scan
        else:
            if isinstance(plan, ShuffleExchangeExec) and not isinstance(
                plan, RangeShuffleExchangeExec
            ):
                # skew-aware split (runtime/adaptivity.py): a hot
                # producer slice — typically a hot hash partition left
                # by the upstream shuffle — fans out over contiguous
                # row-range views before the tasks dispatch. Plain hash
                # shuffles only: their regroup is producer-major with
                # stable within-producer order, so contiguous sub-views
                # reproduce the exact row order of the unsplit task.
                producer, t_prod = self._adapt_split_skew(
                    producer, query_id, stage_id, t_prod
                )
            outputs = self._run_stage_tasks(
                producer, query_id, stage_id, t_prod
            )
        if isinstance(plan, ShuffleExchangeExec) and not isinstance(
            plan, RangeShuffleExchangeExec
        ):
            from datafusion_distributed_tpu.ops.table import round_up_pow2
            from datafusion_distributed_tpu.planner.statistics import (
                row_width,
            )

            sm = self.stream_metrics.get((query_id, stage_id)) or {}
            if sm.get("partial_agg_bailout"):
                # a bail-out invalidates the planner's capacity
                # arithmetic too: the push-down pass sized this
                # exchange's padded per-destination capacity from the
                # partial's slot count, but after the swap RAW rows
                # cross the boundary. Padded capacities are shapes, not
                # hints — regrouping at the stale capacity is a hard
                # concat overflow — so widen to the worst-case
                # per-destination share (every row on one destination)
                # before the regroup.
                total = sum(int(o.num_rows) for o in outputs)
                need = round_up_pow2(
                    -(-total // max(len(outputs), 1))
                )
                if need > int(plan.per_dest_capacity):
                    plan.per_dest_capacity = need
                    sm["bailout_capacity_widened"] = need
            # bulk plane: the exchange moved the producers' LIVE rows
            # through the coordinator (padded capacities are device
            # buffers, not wire bytes here)
            self._record_exchange_bytes(
                plan, query_id, stage_id,
                sum(int(o.num_rows) for o in outputs)
                * row_width(producer.schema()),
                "unary" if self._data_plane() == "unary" else "bulk",
                rows=sum(int(o.num_rows) for o in outputs),
            )
            # consumer-count decision + regroup are overridable together:
            # the adaptive coordinator defers co-shuffled siblings so a
            # join stage's feeds agree on ONE adapted count
            scan = self._finish_shuffle(plan, outputs, producer)
            if sm.get("partial_agg_bailout"):
                # flag the consumer-side scan: RAW rows live in these
                # slices, so the merge aggregate above must re-derive
                # its table size from the slice capacity instead of the
                # stale partial-rows prediction (_widen_bailed_out_merge
                # picks this up when the consumer tree resolves)
                scan.bailout_raw_rows = True
            self._seed_consumer_scan(plan, scan)
            return scan
        t = self._consumer_task_count(plan, outputs)
        if isinstance(plan, RangeShuffleExchangeExec):
            # host tier can range-partition EXACTLY: sort the concatenated
            # producer output once and hand out contiguous slices (the
            # mesh tier's sample-splitter approximation is only needed
            # where no task sees the whole dataset)
            slices = _range_regroup(outputs, plan.sort_keys, t)
        elif isinstance(plan, CoalesceExchangeExec) and (
            plan.num_consumers > 1
        ):
            # true N:M coalesce: consumer j gets the contiguous producer
            # group [j*g, (j+1)*g) (network_coalesce.rs div_ceil arithmetic)
            m = plan.num_consumers
            g = -(-len(outputs) // m)
            slices = []
            for j in range(t):
                group = outputs[j * g: (j + 1) * g] if j < m else []
                if group:
                    slices.append(
                        concat_tables(
                            group, capacity=sum(o.capacity for o in group)
                        )
                    )
                else:  # short/absent group: empty stream
                    ref = outputs[0]
                    slices.append(Table(ref.names, ref.columns,
                                        jnp.zeros((), jnp.int32)))
        elif isinstance(plan, PartitionReplicatedExec):
            # producer is replicated: each consumer keeps its modulo slice of
            # task 0's output
            slices = _mod_slices(outputs[0], t)
        else:
            raise NotImplementedError(type(plan).__name__)
        scan = MemoryScanExec(slices, producer.schema())
        self._seed_consumer_scan(plan, scan)
        return scan

    def _seed_consumer_scan(self, exchange, scan) -> None:
        """Hook: the consumer-side scan for `exchange` was just built (the
        AdaptiveCoordinator seeds it with mid-execution LoadInfo)."""

    def _producer_progress(self, stage_id: int, done: int, total: int,
                           rows: int, width: int) -> None:
        """Hook: `done`/`total` producer tasks of stage `stage_id` have
        completed with `rows` total output rows so far (the reference's
        LoadInfo stream, `sampler.rs:30-42`). Called while the remaining
        producers are still executing."""

    def _chunk_observer(self, stage_id: int):
        """Hook: per-chunk observer for stage output in flight (the
        per-column half of the LoadInfo stream). None = no sampling; the
        AdaptiveCoordinator returns a ColumnStreamSampler.observe."""
        return None

    # -- data-plane selection ------------------------------------------------
    def _data_plane(self) -> str:
        """`SET distributed.data_plane` (default ``auto``): which
        cross-process plane serves exchange boundaries. ``auto`` keeps
        the existing ladder (peer pulls -> partition streams -> bulk);
        ``stream``/``shm`` force every shuffle through the streaming
        TransferPartitions RPC (shm additionally offering the co-located
        segment plane); ``unary`` forces the bulk whole-table plane.
        Plane choice is EXECUTION routing only — never traced, never
        part of the plan fingerprint — so toggling it recompiles
        nothing and must not change a single result byte."""
        return str(self.config_options.get("data_plane", "auto")).lower()

    def _forced_plane_label(self, default: str) -> str:
        """Telemetry label for an exchange: the forced plane name when
        `data_plane` is pinned to stream/shm, else the ladder's own
        label — so `dftpu_exchange_bytes{plane=...}` separates forced
        planes from auto routing."""
        plane = self._data_plane()
        return plane if plane in ("stream", "shm") else default

    # -- peer-to-peer data plane ---------------------------------------------
    def _peer_plane_enabled(self, exchange) -> bool:
        """Default plane for shuffle/broadcast/N:M-coalesce boundaries when
        every worker offers the partition-stream surface: consumer tasks
        pull straight from producer workers and the coordinator only ships
        plans (`prepare_static_plan.rs:10-56` + `worker_connection_pool.rs`).
        N:1 coalesce keeps the coordinator-streamed plane — there the
        coordinator itself is the consumer (the reference's head stage runs
        on the coordinator). RangeShuffle keeps the host plane for its exact
        global sort. `SET distributed.peer_shuffle = false` restores the
        coordinator-mediated plane everywhere."""
        if self._data_plane() != "auto":
            # a forced plane (unary/stream/shm) routes every boundary
            # through the coordinator-mediated paths the toggle names;
            # peer pulls would bypass the selection
            return False
        if not bool(self.config_options.get("peer_shuffle", True)):
            return False
        if isinstance(exchange, RangeShuffleExchangeExec):
            return False
        eligible = isinstance(
            exchange, (ShuffleExchangeExec, BroadcastExchangeExec)
        ) or (
            isinstance(exchange, CoalesceExchangeExec)
            and exchange.num_consumers > 1
        )
        if not eligible:
            return False
        return self._workers_peer_capable()

    def _workers_peer_capable(self) -> bool:
        """Capability probe cached PER MEMBERSHIP TOKEN — the verdict is
        stored WITH the token it was computed under and ignored on
        mismatch, so a worker added after the first dispatch is probed,
        not assumed, and a slow probe racing a membership change cannot
        install a stale verdict for the new epoch. Probing every worker
        per boundary would put O(stages x workers) resolver calls on the
        dispatch path, but a stale verdict on a mutated cluster either
        fails at consumer load time or silently degrades the plane.

        Checks the data-plane surface AND actual peer WIRING
        (`Worker.peer_capable` / the gRPC GetInfo flag): a user-built
        cluster of plain Worker(url) objects without peer_channels must
        keep the coordinator-mediated plane, not fail at consumer load
        time. A single-worker cluster is always capable (every pull
        short-circuits to the local bypass)."""
        urls = self.resolver.get_urls()
        tok = self._note_membership(urls)
        cached = getattr(self, "_peer_capable", None)
        if cached is not None and cached[0] == tok:
            return cached[1]
        workers = []
        for u in urls:
            try:
                workers.append(self.channels.get_worker(u))
            except WorkerUnavailableError:
                # departed between listing and probe (this runs at
                # boundary materialization, OUTSIDE the dispatch retry
                # loops — an escape here would fail the query, not
                # reroute it): judge the survivors; the token is already
                # stale, so the next boundary re-probes the new epoch
                continue
        verdict = all(
            hasattr(w, "execute_task_partitions") for w in workers
        ) and (
            len(urls) <= 1
            or all(getattr(w, "peer_capable", False) for w in workers)
        )
        self._peer_capable = (tok, verdict)
        return verdict

    def _peer_boundary(
        self, exchange, producer: ExecutionPlan, query_id: str,
        stage_id: int, t_prod: int,
    ):
        """Ship the producer stage's task plans to their workers WITHOUT
        executing them, and return the consumer-side peer scan. Row bytes
        for this boundary never touch the coordinator; producers execute
        lazily on the first consumer pull (pending->ready without a
        coordinator materialization step)."""
        from datafusion_distributed_tpu.runtime.peer import (
            PeerShuffleScanExec,
            group_pulls,
            shuffle_pulls,
        )

        prepared = self._prepare_stage_plan(producer)
        # peer producers are first PULLED when their consumer stage runs;
        # on a deep plan that can be far beyond the worker registry's
        # idle-TTL default, so ship them with a query-lifetime TTL (the
        # query-end sweep, not the TTI cache, owns their cleanup)
        peer_ttl = float(self.config_options.get("peer_task_ttl", 3600.0))
        # retained for the membership-churn path: a producer shipped here
        # whose worker later LEAVES is re-shipped from this prepared plan
        # onto a survivor (_heal_departed_peers)
        self._peer_plan_registry[(query_id, stage_id)] = (
            prepared, t_prod, peer_ttl
        )
        producers = []  # (key_obj, url)
        for i in range(t_prod):
            worker, key, plan_obj, _store = self._dispatch_task_with_retry(
                prepared, query_id, stage_id, i, t_prod, ttl=peer_ttl
            )
            self._peer_shipped.append((worker, key))
            producers.append(
                ((key.query_id, key.stage_id, key.task_number), worker.url)
            )
        budget = int(self.config_options.get(
            "worker_connection_buffer_budget_bytes", 64 << 20
        ))
        chunk_rows = int(self.config_options.get("stream_chunk_rows", 65536))
        schema = producer.schema()
        dicts = _leaf_dictionaries(producer, schema)
        if isinstance(exchange, ShuffleExchangeExec):
            t_cons = exchange.num_tasks
            scan = PeerShuffleScanExec(
                shuffle_pulls(producers, t_cons), exchange.key_names,
                t_cons, exchange.per_dest_capacity, schema, dicts,
                budget_bytes=budget, chunk_rows=chunk_rows,
                capacity_hint=t_prod * exchange.per_dest_capacity,
            )
        elif isinstance(exchange, BroadcastExchangeExec):
            t_cons = max(exchange.num_tasks, 1)
            scan = PeerShuffleScanExec(
                shuffle_pulls(producers, t_cons), [], t_cons, 0, schema,
                dicts, replicated=True, budget_bytes=budget,
                chunk_rows=chunk_rows,
                capacity_hint=producer.output_capacity() * max(t_prod, 1),
            )
        else:  # N:M coalesce
            t_cons = exchange.num_consumers
            scan = PeerShuffleScanExec(
                group_pulls(producers, t_cons), [], 1, 0, schema, dicts,
                budget_bytes=budget, chunk_rows=chunk_rows,
                capacity_hint=exchange.output_capacity(),
            )
        self.stream_metrics[(query_id, stage_id)] = {
            "plane": "peer",
            "coordinator_bytes": 0,
            "producers": t_prod,
            "partitions": t_cons,
        }
        return scan

    def _heal_departed_peers(self, stage_plan, query_id) -> int:
        """Membership-churn recovery for the peer data plane: producer
        tasks whose worker LEFT the membership (neither active nor
        draining) are re-shipped onto survivors from the prepared plans
        retained at boundary time, and every pull spec naming them is
        rewritten to the survivor — so the failing consumer's next attempt
        pulls from live endpoints.

        The heal is TRANSITIVE: registered peer stages are processed
        bottom-up (ascending stage id — `_prepare` stamps producers before
        consumers), so when a re-shipped producer's own plan pulls from an
        earlier departed producer, it ships with already-healed specs; and
        a producer still sitting on a LIVE worker whose shipped copy names
        a departed upstream is REFRESHED in place (same key, same worker —
        its consumers' specs keep pointing at it). Original scan nodes are
        mutated (task specialization copies pull lists per dispatch), so
        every retrying sibling task sees the healed specs; the heal lock
        serializes concurrent retries, and a second pass finds everything
        reachable and no-ops. -> producer tasks re-shipped."""
        from datafusion_distributed_tpu.runtime.codec import (
            collect_table_ids,
        )
        from datafusion_distributed_tpu.runtime.peer import (
            PeerShuffleScanExec,
            reroute_pulls,
        )

        plans = getattr(self, "_peer_plan_registry", None)
        if not plans:
            return 0

        def peer_scans(plan):
            return plan.collect(
                lambda n: isinstance(n, PeerShuffleScanExec)
            )

        if getattr(self, "_peer_heal_lock", None) is None:
            # direct-call safety (tests invoke the heal without execute)
            self._peer_heal_lock = threading.Lock()
        healed = 0
        # acquired by its field name, not a local alias: the concurrency
        # lint resolves `with self._peer_heal_lock` as holding the lock
        # that guards _peer_url_map/_peer_stale (DFTPU201)
        with self._peer_heal_lock:
            # url_map/stale accumulate ACROSS heal passes for the query
            # (direct-call safety: tests invoke the heal without execute)
            url_map = getattr(self, "_peer_url_map", None)
            if url_map is None:
                url_map = self._peer_url_map = {}
            stale = getattr(self, "_peer_stale", None)
            if stale is None:
                stale = self._peer_stale = set()
            reachable = set(self._full_membership_urls())
            if not url_map and not stale and all(
                w.url in reachable for w, _ in self._peer_shipped
            ):
                # nothing ever moved and every shipped worker is still a
                # member: the heal is a no-op. This runs on EVERY
                # retryable failure (plain fault chaos included), so skip
                # the per-stage plan walks before sibling retries convoy
                # behind the lock
                return 0
            # latest shipped location of every peer producer task
            loc: dict = {}
            for w, k in self._peer_shipped:
                loc[(k.query_id, k.stage_id, k.task_number)] = (w, k)
            for qid, sid in sorted(plans, key=lambda e: e[1]):
                prepared, t_prod, ttl = plans[(qid, sid)]
                if sum(
                    reroute_pulls(s, url_map) for s in peer_scans(prepared)
                ):
                    # this pass changed the stage's specs: every shipped
                    # copy now pre-dates them and must be refreshed (or
                    # re-shipped) before its consumers can trust it — the
                    # mark persists across passes so copies whose workers
                    # are busy THIS pass still refresh on a later one
                    stale.update((qid, sid, i) for i in range(t_prod))
                for i in range(t_prod):
                    ko = (qid, sid, i)
                    held = loc.get(ko)
                    if held is None:
                        continue
                    worker, key = held
                    if worker.url not in reachable:
                        # departed: re-ship onto a survivor (the prepared
                        # plan's own specs were healed just above)
                        worker, key, _po, _st = (
                            self._dispatch_task_with_retry(
                                prepared, qid, sid, i, t_prod, ttl=ttl
                            )
                        )
                        self._peer_shipped.append((worker, key))
                        loc[ko] = (worker, key)
                        url_map[ko] = worker.url
                        stale.discard(ko)
                        self.faults.bump("peer_producers_reshipped")
                        healed += 1
                    elif ko in stale:
                        # live worker, stale shipped copy (its pulls named
                        # a departed upstream): refresh in place so the
                        # worker-held plan pulls from the survivors —
                        # consumers keep addressing this same (key, url).
                        # No pre-invalidate: registry.put evicts the
                        # displaced entry atomically, so a concurrent
                        # consumer pull never sees a "no plan" gap, and a
                        # failed refresh leaves the old copy registered
                        plan_obj = encode_plan(
                            _task_specialized(prepared, i),
                            worker.table_store,
                        )
                        try:
                            worker.set_plan(
                                key, plan_obj, t_prod,
                                config=self.config_options,
                                headers=self.passthrough_headers,
                                ttl=ttl,
                            )
                        except BaseException as e:
                            worker.table_store.remove(
                                collect_table_ids(plan_obj)
                            )
                            if not getattr(e, "retryable", False):
                                raise
                            # transient refresh failure (the heal runs
                            # inside the callers' failure-handling branch,
                            # OUTSIDE their retry loops — an escape here
                            # would fail the query): fall back to a full
                            # re-ship, which retries/reroutes internally.
                            # The old copy stays registered but unreferenced
                            # once url_map points its consumers at the
                            # re-shipped location; the query-end sweep
                            # releases it.
                            worker, key, _po, _st = (
                                self._dispatch_task_with_retry(
                                    prepared, qid, sid, i, t_prod, ttl=ttl
                                )
                            )
                            self._peer_shipped.append((worker, key))
                            loc[ko] = (worker, key)
                            url_map[ko] = worker.url
                            stale.discard(ko)
                            self.faults.bump("peer_producers_reshipped")
                            healed += 1
                            continue
                        stale.discard(ko)
                        self.faults.bump("peer_producers_refreshed")
            if url_map:
                # the ACCUMULATED map, not just this pass's additions: a
                # consumer whose specs were pinned before an earlier pass
                # moved a producer heals here on its own retry
                for s in peer_scans(stage_plan):
                    reroute_pulls(s, url_map)
        if healed:
            self._event("peer_heal", reshipped=healed)
        return healed

    # -- partition-range data plane ------------------------------------------
    def _partition_streams_enabled(self, exchange) -> bool:
        """Shuffle via worker-side partitioning + multiplexed partition
        streams when every worker offers the surface. The adaptive
        coordinator overrides to False: it resizes consumer task counts
        from exact materialized outputs, while a partition stream fixes
        the partition count in the request."""
        if self._data_plane() == "unary":
            # forced unary: the bulk whole-table plane, the byte-identity
            # baseline every streaming plane is gated against
            return False
        try:
            return all(
                hasattr(self.channels.get_worker(u),
                        "execute_task_partitions")
                for u in self.resolver.get_urls()
            )
        except Exception:
            return False

    # NOTE: AdaptiveCoordinator overrides _checkpoint_eligible to False —
    # its consumer lattices derive from runtime LoadInfo and cannot be
    # re-derived at restore time (see the override below).

    def _partition_stream_pullers(self, exchange, prepared, query_id,
                                  stage_id, t_prod, chunk_rows,
                                  trace_parent):
        """One multiplexed partition-range puller per producer task —
        SHARED by the materialized and pipelined shuffle planes: the
        pull protocol (partition-range request shape, retry/reroute/
        heal/hedge wrapping, trace parenting) must stay identical across
        planes or their byte-identity contract drifts. Each puller
        yields ((partition, chunk), est_bytes)."""
        t_cons = exchange.num_tasks
        plane = self._data_plane()
        wire_mode = str(
            self.config_options.get("wire_compression", "auto")
        ).lower()
        use_transfer = plane in ("stream", "shm")

        def make_puller(task_number: int):
            def body(worker, key, cancel):
                if use_transfer and hasattr(worker, "transfer_partitions"):
                    # forced stream/shm plane: the streaming
                    # TransferPartitions RPC — same request shape and
                    # yield contract (the server delegates to
                    # execute_task_partitions), so retries reroute
                    # through _pull_task_with_retry unchanged. After a
                    # SegmentError the client marks shm broken and the
                    # re-pull lands here again, wire-only.
                    it = worker.transfer_partitions(
                        key, exchange.key_names, t_cons, 0, t_cons,
                        per_dest_capacity=exchange.per_dest_capacity,
                        chunk_rows=chunk_rows, cancel=cancel,
                        wire_compression=wire_mode,
                        shm=(plane == "shm"),
                    )
                else:
                    it = worker.execute_task_partitions(
                        key, exchange.key_names, t_cons, 0, t_cons,
                        per_dest_capacity=exchange.per_dest_capacity,
                        chunk_rows=chunk_rows, cancel=cancel,
                    )
                for p, piece, est in it:
                    yield (p, piece), est

            def pull(cancel):
                yield from self._pull_task_with_retry(
                    prepared, query_id, stage_id, task_number, t_prod,
                    body, cancel, trace_parent=trace_parent,
                )

            return pull

        return [make_puller(i) for i in range(t_prod)]

    def _shuffle_stage_partition_streams(
        self, exchange, producer: ExecutionPlan, query_id: str,
        stage_id: int, t_prod: int,
    ) -> list[Table]:
        """One multiplexed stream per producer task carrying the FULL
        partition range [0, t_consumer); chunks arrive tagged with their
        partition id and are demuxed into consumer slices under the shared
        byte budget (the reference's WorkerConnectionPool demux +
        64 MiB budget, `worker_connection_pool.rs:243-308`). The hash/
        bucket work runs on the producers, not the coordinator."""
        from datafusion_distributed_tpu.runtime.streams import (
            stream_stage_chunks,
        )

        t_cons = exchange.num_tasks
        budget = int(self.config_options.get(
            "worker_connection_buffer_budget_bytes", 64 << 20
        ))
        chunk_rows = int(self.config_options.get("stream_chunk_rows", 65536))
        prepared = self._prepare_stage_plan(producer)
        obs = self._chunk_observer(stage_id)
        plane_label = self._forced_plane_label("partition-stream")
        tr = self._tr()
        with tr.span("transfer", "transfer", stage=stage_id,
                     plane=plane_label) as xfer:
            chunks, stats = stream_stage_chunks(
                self._partition_stream_pullers(
                    exchange, prepared, query_id, stage_id, t_prod,
                    chunk_rows, xfer.span_id,
                ),
                budget,
                max_concurrent=max(len(self.resolver.get_urls()), 1),
                payload_rows=lambda pr: int(pr[1].num_rows),
                on_chunk=(lambda pr: obs(pr[1])) if obs is not None
                else None,
                pressure=self._store_pressure_probe(),
            )
            xfer.set(bytes=stats.bytes_streamed, rows=stats.rows,
                     chunks=stats.chunks)
        self.stream_metrics[(query_id, stage_id)] = {
            "bytes_streamed": stats.bytes_streamed,
            "chunks": stats.chunks,
            "peak_in_flight": stats.peak_in_flight,
            "early_exit": stats.early_exit,
            "rows": stats.rows,
            "partitions": t_cons,
            "rows_per_s": round(stats.rows_per_s, 1),
            "bytes_per_s": round(stats.bytes_per_s, 1),
        }
        self._record_exchange_bytes(
            exchange, query_id, stage_id, stats.bytes_streamed,
            plane_label,
        )
        parts: list[list[Table]] = [[] for _ in range(t_cons)]
        for per in chunks:
            for p, tbl in per:
                parts[p].append(tbl)
        schema = producer.schema()
        slices = []
        for plist in parts:
            if plist:
                rows = sum(int(t.num_rows) for t in plist)
                cap = max(-(-rows // 8) * 8, 8)
                slices.append(concat_tables(plist, capacity=cap))
            else:
                slices.append(Table.empty(
                    schema, 8, _leaf_dictionaries(producer, schema)
                ))
        return slices

    # -- pipelined shuffle plane ---------------------------------------------
    def _pipelined_shuffle_enabled(self, exchange) -> bool:
        """`SET distributed.pipelined_shuffle` (default on): stream the
        shuffle's partition slices into a live PartitionFeed and release
        the consumer stage at first slice. Requires the stage-DAG
        scheduler (`stage_parallelism > 1` — `= 1` is the documented
        materialized pre-scheduler behavior, the byte-identity baseline)
        and no checkpointer (checkpoints snapshot MATERIALIZED
        MemoryScan frontiers; a live feed has nothing restorable)."""
        import os as _os

        from datafusion_distributed_tpu.ops.table import parse_bool_knob

        # env override wins over session config (the whole-suite A/B
        # escape hatch, mirroring DFTPU_ZERO_COPY)
        v = _os.environ.get("DFTPU_PIPELINED_SHUFFLE")
        if v is None:
            v = self.config_options.get(
                "pipelined_shuffle",
                PIPELINE_DEFAULTS["pipelined_shuffle"],
            )
        try:
            enabled = parse_bool_knob(v)
        except Exception:
            enabled = bool(v)
        if not enabled:
            return False
        if self.checkpoints is not None:
            return False
        return self._stage_parallelism() > 1

    def _shuffle_stage_pipelined(
        self, exchange, producer: ExecutionPlan, query_id: str,
        stage_id: int, t_prod: int,
    ) -> "StreamScanExec":
        """Pipelined variant of `_shuffle_stage_partition_streams`: the
        same per-producer multiplexed partition streams (same pullers,
        same retry/hedge machinery, same shared byte budget), but demuxed
        INCREMENTALLY into a `PartitionFeed` by a background feeder
        thread. This method returns a `StreamScanExec` as soon as the
        first slice lands — the boundary flips pending->ready while
        producers are still emitting, and each consumer task's dispatch
        blocks only until ITS partition closes. Byte identity with the
        materialized plane holds because the feed preserves the exact
        (producer, seq) merge order and capacity arithmetic."""
        import threading as _threading

        from datafusion_distributed_tpu.runtime.streams import (
            PartitionFeed,
            stream_partition_chunks,
        )

        t_cons = exchange.num_tasks
        budget = int(self.config_options.get(
            "worker_connection_buffer_budget_bytes", 64 << 20
        ))
        chunk_rows = int(self.config_options.get("stream_chunk_rows", 65536))
        prepared = self._prepare_stage_plan(producer)
        schema = producer.schema()
        dicts = _leaf_dictionaries(producer, schema)
        feed = PartitionFeed(t_cons, t_prod)
        obs = self._chunk_observer(stage_id)
        tr = self._tr()
        # explicit start/end (no context manager): the transfer span
        # covers the stream's full production window and is closed by the
        # feeder thread at completion
        plane_label = self._forced_plane_label("pipelined")
        xfer = tr.start_span(
            "transfer", "transfer",
            parent=tr.reserved_id(("stage", stage_id)),
            stage=stage_id, plane=plane_label,
        )
        pullers = self._partition_stream_pullers(
            exchange, prepared, query_id, stage_id, t_prod, chunk_rows,
            xfer.span_id,
        )
        # visible immediately (plane attribution for stage spans recorded
        # at first slice); the feeder overwrites with the full stats at
        # completion
        self.stream_metrics[(query_id, stage_id)] = {
            "plane": plane_label,
            "partitions": t_cons,
            "producers": t_prod,
        }
        max_conc = max(len(self.resolver.get_urls()), 1)
        pressure_probe = self._store_pressure_probe()

        def run_feed() -> None:
            try:
                stats = stream_partition_chunks(
                    pullers, budget, feed,
                    max_concurrent=max_conc,
                    on_chunk=obs,
                    should_cancel=self._cancelled,
                    pressure=pressure_probe,
                )
            except BaseException as e:
                # idempotent hardening: stream_partition_chunks fails
                # the feed on its own error paths, but an exception from
                # OUTSIDE them (a demux bug, a bad partition id) must
                # also reach blocked consumers or an un-cancelled query
                # would hang in wait_partition forever
                feed.fail(e)
                tr.end_span(xfer.set(error=type(e).__name__))
                return
            tr.end_span(xfer.set(
                bytes=stats.bytes_streamed, rows=stats.rows,
                chunks=stats.chunks,
            ))
            self.stream_metrics[(query_id, stage_id)] = {
                "plane": plane_label,
                "bytes_streamed": stats.bytes_streamed,
                "chunks": stats.chunks,
                "peak_in_flight": stats.peak_in_flight,
                "early_exit": stats.early_exit,
                "rows": stats.rows,
                "partitions": t_cons,
                "producers": t_prod,
                "rows_per_s": round(stats.rows_per_s, 1),
                "bytes_per_s": round(stats.bytes_per_s, 1),
                "pullers_leaked": stats.extra.get("pullers_leaked", 0),
            }
            self._record_exchange_bytes(
                exchange, query_id, stage_id, stats.bytes_streamed,
                plane_label,
            )

        t = _threading.Thread(target=run_feed, daemon=True,
                              name="dftpu-pipelined-feed")
        if not hasattr(self, "_stream_feeds"):
            # direct-call safety (tests materialize without execute)
            self._stream_feeds = []
        self._stream_feeds.append(t)
        t.start()
        # consumer release point: the first slice proves data is flowing
        # (and surfaces an immediate producer failure HERE, on the stage
        # job, exactly where the materialized plane would raise it)
        feed.wait_first_chunk(self._cancelled)
        return StreamScanExec(
            feed, schema, dicts,
            capacity_hint=t_prod * exchange.per_dest_capacity,
            cancelled=self._cancelled,
        )

    def _record_exchange_bytes(self, exchange, query_id: str,
                               stage_id: int, measured: int,
                               plane: str, rows: Optional[int] = None) -> None:
        """Predicted-vs-measured exchange accounting (the partial-agg
        push-down feedback loop): the planner pass stamps
        `predicted_exchange_bytes` on shuffles it rewrote from sampled
        key-distribution statistics; the coordinator records both sides
        into the process telemetry registry and the per-stage stream
        metrics, so `dftpu_exchange_bytes` / `dftpu_exchange_predicted_
        bytes` expose how good the prediction was. Host-side only, after
        the stream resolved — never in traced code (DFTPU110)."""
        predicted = getattr(exchange, "predicted_exchange_bytes", None)
        sm = self.stream_metrics.setdefault(
            (query_id, stage_id), {"plane": plane}
        )
        sm["exchange_bytes"] = int(measured)
        if rows is not None:
            # bulk-plane measured output rows (the streaming planes
            # record theirs from StreamStats) — what the mid-query
            # replan compares against StageDagNode.est_rows
            sm["rows"] = int(rows)
        if predicted is not None:
            sm["predicted_exchange_bytes"] = int(predicted)
        try:
            from datafusion_distributed_tpu.runtime.telemetry import (
                DEFAULT_REGISTRY,
            )

            DEFAULT_REGISTRY.counter(
                "dftpu_exchange_bytes",
                "Measured bytes crossing shuffle exchange boundaries.",
                labels=("plane",),
            ).inc(int(measured), plane=plane)
            if predicted is not None:
                DEFAULT_REGISTRY.counter(
                    "dftpu_exchange_predicted_bytes",
                    "Planner-predicted exchange bytes for shuffles "
                    "rewritten by the partial-aggregate push-down.",
                    labels=("plane",),
                ).inc(int(predicted), plane=plane)
        except Exception:
            pass  # telemetry must never fail the exchange

    # -- task-count policy ---------------------------------------------------
    def _producer_task_count(self, exchange, producer) -> int:
        """How many tasks to run for the producer stage: the lattice-stamped
        count when present, else the exchange's planned count — never more
        than the data slices available in its scans (an earlier exchange may
        have produced fewer consumer slices than the planned task count),
        never fewer than an isolated arm's pinned index needs."""
        from datafusion_distributed_tpu.runtime.peer import (
            PeerShuffleScanExec,
        )

        planned = getattr(exchange, "producer_tasks", None)
        if planned is None:
            planned = exchange.num_tasks
        scans = [
            n for n in producer.collect(lambda n: not n.children())
            if isinstance(n, MemoryScanExec) and not n.pinned
        ]
        # isolated union arms pin work to specific task indices; running
        # fewer tasks than the highest assignment would silently drop arms
        # (task specialization ships them as empty scans)
        arms = producer.collect(lambda n: isinstance(n, IsolatedArmExec))
        # a peer scan INSIDE an arm is wholly pulled by the arm's one task
        # (pull_all) — it must not constrain the stage width
        in_arm_peer = {
            id(n)
            for a in arms
            for n in a.collect(lambda n: isinstance(n, PeerShuffleScanExec))
        }
        peer_scans = [
            n for n in producer.collect(
                lambda n: isinstance(n, PeerShuffleScanExec)
            )
            if n.pinned_task is None and id(n) not in in_arm_peer
        ]
        # pipelined-shuffle feeds (StreamScanExec): one partition per
        # consumer task, and — like peer pull specs — every partition is
        # a CONSUMPTION OBLIGATION: running fewer tasks than partitions
        # would silently drop the untaken ones' rows
        in_arm_stream = {
            id(n)
            for a in arms
            for n in a.collect(lambda n: isinstance(n, StreamScanExec))
        }
        stream_scans = [
            n for n in producer.collect(
                lambda n: isinstance(n, StreamScanExec)
            )
            if id(n) not in in_arm_stream
        ]
        need = 1 + max((a.assigned_task for a in arms), default=-1)
        partitioned = [s for s in scans if not s.replicated]
        partitioned_peer = [s for s in peer_scans if not s.replicated]
        # a partitioned peer scan's partitions are pull obligations, not
        # just available slices: running fewer tasks than pull-spec lists
        # would leave partitions unpulled (silent row loss)
        need = max(
            need,
            max((len(s.pulls_per_task) for s in partitioned_peer), default=0),
            max((s.num_partitions for s in stream_scans), default=0),
        )
        slice_counts = [len(s.tasks) for s in partitioned] + [
            len(s.pulls_per_task) for s in partitioned_peer
        ] + [s.num_partitions for s in stream_scans]
        if slice_counts:
            t = min(planned, max(slice_counts))
        elif scans or peer_scans:
            # all inputs replicated: every task would compute the identical
            # result — run the stage ONCE (the reference co-locates
            # single-task stages the same way, prepare_dynamic_plan.rs:86-96)
            t = 1
        else:
            t = planned
        return min(max(planned, need), max(t, need))

    def _consumer_task_count(self, exchange, outputs) -> int:
        """Static mode: the planned count (AdaptiveCoordinator recomputes
        from exact materialized bytes)."""
        return exchange.num_tasks

    def _finish_shuffle(self, exchange, outputs, producer) -> MemoryScanExec:
        """Decide the consumer task count and regroup a hash shuffle's
        producer outputs into consumer slices."""
        t = self._consumer_task_count(exchange, outputs)
        slices = _shuffle_regroup(
            outputs, exchange.key_names, t, exchange.per_dest_capacity,
            zero_copy=self._zero_copy(),
        )
        return MemoryScanExec(slices, producer.schema())

    # -- closed-loop runtime adaptivity --------------------------------------
    def _adaptivity(self):
        """Runtime-adaptivity knobs (runtime/adaptivity.py), re-parsed
        per decision so `SET skew_split_factor` etc. between queries
        take effect without rebuilding the coordinator. None of them is
        trace-relevant: toggling recompiles nothing."""
        from datafusion_distributed_tpu.runtime.adaptivity import (
            AdaptivitySettings,
        )

        return AdaptivitySettings.from_options(self.config_options)

    def _adapt_split_skew(self, producer, query_id: str, stage_id: int,
                          task_count: int):
        """Skew-aware repartitioning on the bulk shuffle plane: when one
        producer task's input slice carries `skew_split_factor` x the
        median rows (the signature of a hot hash partition produced by
        the upstream exchange — the same histogram PartitionFeed records
        on the streaming plane), split that task into contiguous
        row-range views (`ops.table.slice_view` over one `host_view`
        rebind, the PR 8 zero-copy primitives) so idle workers share the
        hot rows. Returns the (possibly rewritten) producer and task
        count.

        Byte-identity argument: `_shuffle_regroup` walks producers in
        list order with a STABLE within-producer order, so replacing
        task j by sub-views whose concatenation is exactly task j's row
        order reproduces identical per-destination rows in identical
        order — only task boundaries (and padding capacities) change.
        Eligibility is conservative: exactly one un-pinned partitioned
        MemoryScan (every other leaf replicated), reached from the stage
        root through row-order-preserving nodes only (filter/projection/
        coalesce/sampler, or a hash join via its PROBE child — emission
        is probe-major)."""
        settings = self._adaptivity()
        if not settings.skew_enabled or task_count < 2:
            return producer, task_count
        from datafusion_distributed_tpu.ops.table import (
            host_view,
            slice_view,
        )
        from datafusion_distributed_tpu.runtime.adaptivity import (
            detect_skew,
            note_skew_split,
            split_ranges,
        )

        leaves = producer.collect(lambda n: not n.children())
        scans = [n for n in leaves if isinstance(n, MemoryScanExec)]
        if len(scans) != len(leaves):
            return producer, task_count  # stream/peer/parquet leaves
        candidates = [
            s for s in scans if not s.pinned and not s.replicated
        ]
        if len(candidates) != 1:
            return producer, task_count
        scan = candidates[0]
        if len(scan.tasks) != task_count:
            return producer, task_count
        if producer.collect(lambda n: isinstance(n, IsolatedArmExec)):
            return producer, task_count
        if not self._skew_splittable(producer, scan):
            return producer, task_count
        counts = [int(t.num_rows) for t in scan.tasks]
        rep = detect_skew(counts, settings.skew_split_factor,
                          settings.skew_split_min_rows)
        if rep is None:
            return producer, task_count
        k = min(
            -(-rep.rows // max(int(rep.median), 1)),
            max(self._live_worker_count(), 2),
            8,  # fan-out ceiling: dispatch overhead grows per sub-task
            rep.rows,
        )
        if k < 2:
            return producer, task_count
        host = host_view(scan.tasks[rep.partition])
        subs = [
            slice_view(host, lo, cnt)
            for lo, cnt in split_ranges(rep.rows, k)
        ]
        new_tasks = (
            list(scan.tasks[:rep.partition]) + subs
            + list(scan.tasks[rep.partition + 1:])
        )
        new_scan = MemoryScanExec(new_tasks, scan._schema)

        def swap(node):
            if node is scan:
                return new_scan
            children = [swap(c) for c in node.children()]
            return node.with_new_children(children) if children else node

        note_skew_split(query_id, stage_id, rep.partition, rep.rows, k,
                        rep.median)
        sm = self.stream_metrics.setdefault(
            (query_id, stage_id), {"plane": "bulk"}
        )
        sm["skew_splits"] = sm.get("skew_splits", 0) + 1
        sm["skew_partition_rows"] = rep.rows
        return swap(producer), task_count + k - 1

    def _skew_splittable(self, producer, scan) -> bool:
        """Whether the path from the stage root to `scan` preserves
        per-row order under a contiguous split of the scan's task axis:
        only row-wise nodes, and hash joins entered via the probe child
        (their emission is probe-major; the build side must then hang
        off replicated scans, which the candidate filter guarantees)."""
        from datafusion_distributed_tpu.plan.joins import HashJoinExec
        from datafusion_distributed_tpu.plan.physical import (
            CoalescePartitionsExec,
            FilterExec,
            ProjectionExec,
        )
        from datafusion_distributed_tpu.planner.adaptive import SamplerExec

        def path_ok(node) -> bool:
            if node is scan:
                return True
            if isinstance(node, (FilterExec, ProjectionExec,
                                 CoalescePartitionsExec, SamplerExec)):
                return path_ok(node.children()[0])
            if isinstance(node, HashJoinExec):
                return path_ok(node.probe)
            return False

        return path_ok(producer)

    def _bailout_probe(self, producer, query_id: str, stage_id: int,
                       task_count: int):
        """When the stage carries a pushed-down partial aggregate the
        planner stamped as a bail-out candidate
        (planner/distributed.py `_partial_agg_pushdown_pass`), return a
        closure that judges task 0's measured reduction ratio and — when
        it exceeds `partial_agg_bailout_ratio`, i.e. the sampled-NDV
        prediction was wrong and the partial barely reduced — returns a
        producer with the partial swapped for `PartialPassthroughExec`
        for the remaining tasks (grounding: *Partial Partial
        Aggregates*). None when the stage has no candidate or its input
        rows are not measurable host-side.

        Input rows come from the partitioned scans' task-0 slices, so
        the probe only engages when every node under the partial is
        row-wise (a filter UNDERCOUNTS the true ratio — conservative:
        it can only make the bail-out rarer, never spurious)."""
        settings = self._adaptivity()
        if not settings.bailout_enabled or task_count < 2:
            return None
        from datafusion_distributed_tpu.plan.physical import (
            CoalescePartitionsExec,
            FilterExec,
            HashAggregateExec,
            PartialPassthroughExec,
            ProjectionExec,
        )
        from datafusion_distributed_tpu.planner.adaptive import SamplerExec
        from datafusion_distributed_tpu.runtime.adaptivity import (
            note_partial_agg_bailout,
        )

        partials = producer.collect(
            lambda n: isinstance(n, HashAggregateExec)
            and n.mode == "partial"
            and getattr(n, "bailout_candidate", False)
        )
        if len(partials) != 1:
            return None
        partial = partials[0]
        allowed = (FilterExec, ProjectionExec, CoalescePartitionsExec,
                   SamplerExec, MemoryScanExec)
        subtree = partial.child.collect(lambda n: True)
        if any(not isinstance(n, allowed) for n in subtree):
            return None  # joins/unions below: scan rows ≠ agg input rows
        scans = [
            n for n in subtree
            if isinstance(n, MemoryScanExec)
            and not n.pinned and not n.replicated and n.tasks
        ]
        rows_in = sum(int(s.tasks[0].num_rows) for s in scans)
        if rows_in <= 0:
            return None

        def judge(out0: Table):
            rows_out = int(out0.num_rows)
            ratio = rows_out / rows_in
            if ratio < settings.partial_agg_bailout_ratio:
                return None
            passthrough = PartialPassthroughExec(
                partial.group_names, partial.aggs, partial.child
            )

            def swap(node):
                if node is partial:
                    return passthrough
                children = [swap(c) for c in node.children()]
                return (node.with_new_children(children)
                        if children else node)

            note_partial_agg_bailout(
                query_id, stage_id, rows_in, rows_out, ratio,
                getattr(partial, "predicted_partial_rows", 0),
            )
            sm = self.stream_metrics.setdefault(
                (query_id, stage_id), {"plane": "bulk"}
            )
            sm["partial_agg_bailout"] = True
            sm["partial_agg_ratio"] = round(ratio, 4)
            return swap(producer)

        return judge

    @staticmethod
    def _widen_bailed_out_merge(node):
        """Consumer-side half of the bail-out: after the swap, RAW rows
        crossed the exchange, so the planner's consumer merge table —
        sized from the same predicted partial rows that the probe just
        disproved — is stale exactly like the exchange capacity was.
        When an aggregate sits directly on a bailed-out boundary's scan
        (the push-down pass builds `final(shuffle(partial))`, so the
        scan IS its direct child once the exchange resolves), rebuild
        it with the constructor's input-bound default (2x the slice
        capacity: load factor <= 0.5 even with every row distinct),
        never below the planner's own sizing. Deterministic — the same
        bail-out decision always yields the same widened shape."""
        from datafusion_distributed_tpu.plan.physical import (
            HashAggregateExec,
        )

        if not isinstance(node, HashAggregateExec):
            return node
        if not any(getattr(c, "bailout_raw_rows", False)
                   for c in node.children()):
            return node
        rebuilt = HashAggregateExec(node.mode, node.group_names,
                                    node.aggs, node.children()[0])
        if rebuilt.num_slots <= int(node.num_slots):
            return node
        for attr in node._PRESERVED_ANNOTATIONS:
            setattr(rebuilt, attr, getattr(node, attr, None))
        return rebuilt

    def _bailout_multiway(self, node, query_id: str):
        """Multiway half of the bail-out: once a fused stage's build
        boundaries resolve to materialized MemoryScans, their row counts
        are MEASURED, not estimated. If any measured build outgrew the
        hash table the planner captured for its step (per-task load
        factor would exceed 0.5 — the bound the binary constructor sizes
        to), the fused stage is swapped back to its binary chain with
        ``rederive=True`` so every join re-sizes from the resolved
        children. Output bytes are unchanged either way — the chain is
        the fused stage's reference semantics — only the sizing and
        kernel choice differ. Capacity paddings never trigger this:
        only actual materialized rows count, so the peer/stream planes
        (whose rows never cross the coordinator) simply never bail —
        the same measurability rule _maybe_replan follows.
        Deterministic: the same measured rows always bail the same
        stages."""
        from datafusion_distributed_tpu.plan.joins import (
            MultiwayHashJoinExec,
        )

        if not isinstance(node, MultiwayHashJoinExec):
            return node
        if not getattr(node, "multiway_bailout_candidate", False):
            return node

        def measured_rows(build):
            # the per-task build table: replicated scans load the full
            # table on every task, partitioned scans one shard each
            if not isinstance(build, MemoryScanExec) or not build.tasks:
                return None
            if getattr(build, "replicated", False):
                return int(build.tasks[0].num_rows)
            return max(int(t.num_rows) for t in build.tasks)

        worst = 0
        slots = 0
        for build, step in zip(node.builds, node.steps):
            rows = measured_rows(build)
            if rows is not None and 2 * rows > int(step.num_slots):
                worst = max(worst, rows)
                slots = int(step.num_slots)
        if not worst:
            return node
        from datafusion_distributed_tpu.runtime.adaptivity import (
            note_multiway_bailout,
        )

        note_multiway_bailout(query_id, len(node.steps), worst, slots)
        return node.to_binary_chain(rederive=True)

    def _maybe_replan(self, query_id: str, stage_id: int, nodes, scan,
                      submitted) -> bool:
        """Mid-query re-cost: when stage `stage_id`'s measured output
        cardinality diverges from its `StageDagNode.est_rows` by
        `replan_cardinality_factor`, scale the estimates of every
        transitively-dependent NOT-YET-SUBMITTED stage by the measured
        ratio — the backlog promotion then dispatches the unstarted
        frontier cheapest-first on corrected bytes, and the serving
        tier's fair-share pool sees corrected cost hints (submit reads
        `node.est_bytes` at submit time). Scheduling only: stage plans
        are byte-for-byte untouched, and every affected exchange is
        re-run through the static verifier (memoized, so structure
        unchanged == known clean) before it can dispatch."""
        settings = self._adaptivity()
        if not settings.replan_enabled:
            return False
        node = nodes.get(stage_id)
        if node is None:
            return False
        est = int(getattr(node, "est_rows", 0) or 0)
        if est <= 0:
            return False
        # measured output rows: every plane that moves rows through the
        # coordinator records them in stream_metrics (bulk:
        # _record_exchange_bytes; streaming coalesce + pipelined drain:
        # stats.rows). A materialized MemoryScan is the fallback. The
        # peer plane is unmeasurable by design — its rows never cross
        # the coordinator — so those stages simply never trigger.
        sm0 = self.stream_metrics.get((query_id, stage_id), {})
        measured = sm0.get("rows")
        if measured is None and isinstance(scan, MemoryScanExec):
            if getattr(scan, "replicated", False):
                measured = int(scan.tasks[0].num_rows) if scan.tasks else 0
            else:
                measured = sum(int(t.num_rows) for t in scan.tasks)
        if not measured or int(measured) <= 0:
            return False
        measured = int(measured)
        if max(measured / est, est / measured) < (
            settings.replan_cardinality_factor
        ):
            return False
        affected = self._downstream_unsubmitted(stage_id, nodes,
                                                submitted)
        if not affected:
            return False
        from datafusion_distributed_tpu.plan.verify import (
            enforce_verification,
        )
        from datafusion_distributed_tpu.runtime.adaptivity import (
            note_replan,
        )

        try:
            for sid2 in affected:
                enforce_verification(
                    nodes[sid2].exchange, options=self.config_options,
                    context=f"replan stage {sid2}",
                )
        except Exception:
            return False  # never fail or degrade a query over re-costing
        ratio = measured / est
        for sid2 in affected:
            n2 = nodes[sid2]
            n2.est_rows = max(int(n2.est_rows * ratio), 1)
            n2.est_bytes = max(int(n2.est_bytes * ratio), 1)
        note_replan(query_id, stage_id, measured, est, len(affected))
        sm = self.stream_metrics.setdefault(
            (query_id, stage_id), {"plane": "bulk"}
        )
        sm["replanned_stages"] = len(affected)
        return True

    @staticmethod
    def _downstream_unsubmitted(stage_id: int, nodes, submitted) -> list:
        """Transitive consumers of `stage_id` that have not been
        submitted (not resolved, not in flight — i.e. still waiting on
        deps or parked in the ready backlog), in stage-id order."""
        rev: dict = {}
        for sid, n in nodes.items():
            for d in n.deps:
                rev.setdefault(d, []).append(sid)
        seen: set = set()
        stack = [stage_id]
        while stack:
            for c in rev.get(stack.pop(), ()):
                if c not in seen:
                    seen.add(c)
                    stack.append(c)
        return sorted(s for s in seen if s not in submitted)

    # -- streaming data plane -----------------------------------------------
    def _stream_stage_coalesced(
        self, exchange, producer: ExecutionPlan, query_id: str,
        stage_id: int, t_prod: int,
    ) -> Table:
        """Materialize an N:1 coalesce/broadcast boundary through the
        chunked streaming plane (runtime/streams.py): one puller per
        producer task, in-flight bytes bounded by
        `worker_connection_buffer_budget_bytes`, and production cancelled
        early once a downstream LIMIT's rows have arrived
        (`exchange.consumer_fetch`, stamped by the planner)."""
        from datafusion_distributed_tpu.runtime.streams import (
            stream_stage_chunks,
        )

        budget = int(self.config_options.get(
            "worker_connection_buffer_budget_bytes", 64 << 20
        ))
        chunk_rows = int(self.config_options.get("stream_chunk_rows", 65536))
        fetch = getattr(exchange, "consumer_fetch", None)

        prepared = self._prepare_stage_plan(producer)

        def make_puller(task_number: int):
            def body(worker, key, cancel):
                if hasattr(worker, "execute_task_stream"):
                    yield from worker.execute_task_stream(
                        key, chunk_rows=chunk_rows, cancel=cancel
                    )
                else:  # transport without a streaming surface
                    from datafusion_distributed_tpu.ops.table import (
                        host_view,
                        slice_view,
                    )
                    from datafusion_distributed_tpu.planner.statistics import (  # noqa: E501
                        row_width,
                    )

                    out = worker.execute_task(key)
                    zc = self._zero_copy()
                    if zc:
                        # chunks below are zero-copy views of one host
                        # rebind instead of per-chunk device slices
                        out = host_view(out)
                    width = row_width(out.schema())
                    n = int(out.num_rows)
                    for lo in range(0, max(n, 1), chunk_rows):
                        if cancel.is_set():
                            return
                        c = min(chunk_rows, n - lo)
                        yield (
                            slice_view(out, lo, c) if zc
                            else out.slice_rows(lo, c)
                        ), c * width

            def pull(cancel):
                # `xfer` binds when the transfer span opens below, before
                # any puller runs — pull spans nest under the transfer
                yield from self._pull_task_with_retry(
                    prepared, query_id, stage_id, task_number, t_prod,
                    body, cancel, trace_parent=xfer.span_id,
                )

            return pull

        from datafusion_distributed_tpu.planner.statistics import row_width

        width = row_width(producer.schema())

        def progress(done, total, rows, _bytes):
            self._producer_progress(stage_id, done, total, rows, width)

        tr = self._tr()
        with tr.span("transfer", "transfer", stage=stage_id,
                     plane="stream") as xfer:
            chunks, stats = stream_stage_chunks(
                [make_puller(i) for i in range(t_prod)], budget,
                row_target=fetch,
                max_concurrent=max(len(self.resolver.get_urls()), 1),
                on_progress=progress,
                on_chunk=self._chunk_observer(stage_id),
                pressure=self._store_pressure_probe(),
            )
            xfer.set(bytes=stats.bytes_streamed, rows=stats.rows,
                     chunks=stats.chunks, early_exit=stats.early_exit)
        self.stream_metrics[(query_id, stage_id)] = {
            "bytes_streamed": stats.bytes_streamed,
            "chunks": stats.chunks,
            "peak_in_flight": stats.peak_in_flight,
            "early_exit": stats.early_exit,
            "rows": stats.rows,
            "rows_per_s": round(stats.rows_per_s, 1),
            "bytes_per_s": round(stats.bytes_per_s, 1),
        }
        flat = [c for per in chunks for c in per]
        if not flat:
            schema = producer.schema()
            return Table.empty(schema, 8, _leaf_dictionaries(producer, schema))
        # capacity: exactly the streamed rows, 8-row aligned (chunk padding
        # and a pow2 round here would transiently double big gathers)
        cap = max(-(-stats.rows // 8) * 8, 8)
        return concat_tables(flat, capacity=cap)

    # -- task execution ------------------------------------------------------
    def _run_stage_tasks(
        self, producer: ExecutionPlan, query_id: str, stage_id: int,
        task_count: int,
    ) -> list[Table]:
        """Fan ALL tasks of a stage out concurrently — one thread per worker
        (the reference fans tasks out as concurrent async sends,
        `query_coordinator.rs:140-222`; round 1 ran them in a sequential
        Python loop, serializing the whole cluster). A failed task cancels
        the remaining ones (cancellation propagation)."""
        import concurrent.futures as cf

        from datafusion_distributed_tpu.planner.statistics import row_width

        width = row_width(producer.schema())
        obs = self._chunk_observer(stage_id)
        outs: dict[int, Table] = {}
        rows = 0
        done = 0

        def account(i: int, out: Table) -> None:
            nonlocal rows, done
            outs[i] = out
            rows += int(out.num_rows)
            done += 1
            if obs is not None:
                obs(out)
            self._producer_progress(stage_id, done, task_count, rows, width)

        # worker count is LIVE, re-checked per task in the sequential path:
        # a cluster of 1 that grows mid-stage (elastic join) promotes the
        # REMAINING tasks to the concurrent fan-out instead of serializing
        # the whole stage on the stale snapshot taken at stage start
        pending = list(range(task_count))
        probe = self._bailout_probe(producer, query_id, stage_id,
                                    task_count)
        if probe is not None:
            # self-correcting partial aggregation: run task 0 FIRST (one
            # task of lookahead), measure the partial's actual reduction,
            # and swap the remaining tasks to the per-row passthrough
            # when the sampled-NDV prediction was wrong. Deterministic by
            # construction — the decision depends only on task 0's
            # measured rows, and exactly tasks 1..n-1 swap — so repeated
            # runs stay byte-identical.
            i = pending.pop(0)
            account(i, self._run_stage_task(producer, query_id, stage_id,
                                            i, task_count))
            swapped = probe(outs[i])
            if swapped is not None:
                producer = swapped
        while pending and (
            task_count == 1 or self._live_worker_count() == 1
        ):
            i = pending.pop(0)
            account(i, self._run_stage_task(producer, query_id, stage_id, i,
                                            task_count))
        if pending:
            workers = self._live_worker_count()
            with cf.ThreadPoolExecutor(max_workers=workers) as pool:
                futs = {
                    pool.submit(self._run_stage_task, producer, query_id,
                                stage_id, i, task_count): i
                    for i in pending
                }
                try:
                    # drain in completion order so mid-execution LoadInfo
                    # flows while the slower producers are still running
                    # (bulk-plane "chunks" are whole task outputs)
                    for f in cf.as_completed(futs):
                        account(futs[f], f.result())
                except BaseException:
                    # `f.cancel()` only stops futures that never STARTED;
                    # the per-query cancel event reaches the in-flight ones
                    # — they abort at their next dispatch/execute checkpoint
                    # and release any already-staged slices (satellite of
                    # ISSUE 5: no orphaned tasks, no TTL-leaked TableStore
                    # entries)
                    self._signal_cancel()
                    for f in futs:
                        f.cancel()
                    raise
        return [outs[i] for i in range(task_count)]

    def _run_stage_task(
        self,
        stage_plan: ExecutionPlan,
        query_id: str,
        stage_id: int,
        task_number: int,
        task_count: int,
    ) -> Table:
        stage_plan = self._prepare_stage_plan(stage_plan)
        state = _RetryState()
        kt = (query_id, stage_id, task_number)
        tr = self._tr()
        with tr.span("task", "task",
                     parent=tr.reserved_id(("stage", stage_id)),
                     stage=stage_id, task=task_number) as tsp:
            while True:
                self._check_cancelled()
                with tr.span("attempt", "attempt",
                             attempt=state.attempt) as asp:
                    worker, key, plan_obj, store = (
                        self._dispatch_task_with_retry(
                            stage_plan, query_id, stage_id, task_number,
                            task_count, state=state,
                        )
                    )
                    try:
                        self._check_cancelled()
                    except TaskCancelledError:
                        # a sibling failed while this task was shipping:
                        # release the just-staged slices NOW instead of
                        # leaking them until the registry's TTL sweep
                        try:
                            self._cleanup_task(worker, key, plan_obj, store)
                        except Exception:
                            pass
                        raise
                    asp.set(worker=worker.url)
                    hedge_after = self._hedge_threshold()
                    try:
                        if hedge_after is not None and (
                            not self._stage_span_shipped(query_id,
                                                         stage_id)
                        ):
                            # hedge arm: race the primary against a
                            # speculative re-dispatch once its wall
                            # passes the sketch-derived threshold
                            worker, out = self._hedged_execute(
                                stage_plan, query_id, stage_id,
                                task_number, task_count,
                                (worker, key, plan_obj, store),
                                hedge_after, state, asp,
                            )
                        else:
                            try:
                                with tr.span("execute_rpc", "execute",
                                             worker=worker.url):
                                    out = self._execute_attempt(
                                        worker, key,
                                        cancel=self._cancel_event,
                                    )
                                # metrics are best-effort: a flaky
                                # progress RPC after a SUCCESSFUL execute
                                # must not discard the result, re-run the
                                # task, or count against the worker
                                try:
                                    self._record_task_progress(worker,
                                                               key)
                                except Exception:
                                    pass
                            finally:
                                # best-effort: with the result in hand a
                                # cleanup hiccup must not discard it (or
                                # re-execute the task), and on the
                                # failure path it must not MASK the
                                # execute error; cleanup is local-only
                                try:
                                    self._cleanup_task(worker, key,
                                                       plan_obj, store)
                                except Exception:
                                    pass
                    except BaseException as e:
                        # attribute the failure to the worker the ERROR
                        # names when it names one (a dead peer PRODUCER
                        # failing a consumer's pull must not quarantine
                        # the healthy consumer)
                        asp.set(error=type(e).__name__)
                        if self._handle_task_failure(
                            e, getattr(e, "worker_url", "") or worker.url,
                            kt, state,
                        ):
                            # a departed worker may have taken shipped
                            # peer-producer plans with it: re-ship them
                            # onto survivors and rewrite this stage plan's
                            # pull specs BEFORE the re-dispatch
                            self._heal_departed_peers(stage_plan, query_id)
                            continue
                        raise
                self._record_worker_success(worker.url)
                if tr.active:
                    tsp.set(bytes=table_nbytes(out),
                            rows=int(out.num_rows))
                return out

    # -- fault tolerance -----------------------------------------------------
    def _execute_attempt(self, worker, key, cancel=None) -> Table:
        """ONE bulk-plane execute attempt under the per-task deadline
        (`SET distributed.task_timeout_s`). Workers whose execute_task
        accepts a ``timeout`` get NATIVE enforcement — the gRPC client
        turns it into a wire deadline that cancels the stream server-side
        instead of leaking an open RPC per abandoned attempt. Workers
        without the parameter (MeshWorker, user duck-types) fall back to
        the coordinator-side thread deadline, which works against any
        transport but can only abandon, not cancel.

        ``cancel``: a pollable cancel handle (the per-query event, or a
        hedge attempt's combined loser-cancel) forwarded to workers whose
        surface declares it — chaos proxies poll it inside injected
        delays, so a cancelled attempt releases its slot at cancellation
        latency rather than the full injected delay."""
        timeout = self._opt_float("task_timeout_s")
        kw = {}
        if cancel is not None and self._worker_accepts_param(
            worker, "execute_task", "cancel"
        ):
            kw["cancel"] = cancel
        if not timeout:
            return worker.execute_task(key, **kw)
        if self._worker_accepts_timeout(worker):
            return worker.execute_task(key, timeout=timeout, **kw)
        return call_with_deadline(
            lambda: worker.execute_task(key, **kw), timeout, worker.url,
            key,
        )

    def _worker_accepts_timeout(self, worker,
                                method: str = "execute_task") -> bool:
        """Whether this worker type's ``method`` takes an EXPLICIT
        ``timeout=`` (see `_worker_accepts_param`)."""
        return self._worker_accepts_param(worker, method, "timeout")

    def _worker_accepts_param(self, worker, method: str,
                              param: str) -> bool:
        """Whether this worker type's ``method`` declares an EXPLICIT
        ``param`` (cached per (type, method, param) — signature
        inspection is not free per task). A bare ``**kwargs``
        deliberately does NOT count: a forwarding wrapper could swallow
        the kwarg without honoring it, silently disabling the deadline or
        the cancel plumbing — such workers get the coordinator-side
        fallback instead of a TypeError."""
        cache = getattr(self, "_timeout_sig_cache", None)
        if cache is None:
            cache = self._timeout_sig_cache = {}
        ck = (type(worker), method, param)
        hit = cache.get(ck)
        if hit is None:
            import inspect

            try:
                params = inspect.signature(
                    getattr(worker, method)
                ).parameters
                hit = param in params
            except (TypeError, ValueError, AttributeError):
                hit = False
            cache[ck] = hit
        return hit

    def _opt_float(self, name: str) -> float:
        default = _OPTION_DEFAULTS.get(name, 0.0)
        try:
            return float(self.config_options.get(name, default) or 0.0)
        except (TypeError, ValueError):
            return float(default)

    def _opt_int(self, name: str) -> int:
        default = _OPTION_DEFAULTS.get(name, 0)
        try:
            return int(self.config_options.get(name, default))
        except (TypeError, ValueError):
            return int(default)

    def _health_tracker(self):
        if self.health is None:
            from datafusion_distributed_tpu.runtime.health import (
                HealthPolicy,
                HealthTracker,
            )

            with _HEALTH_INIT_LOCK:
                if self.health is None:  # double-checked: fan-out threads
                    self.health = HealthTracker(HealthPolicy(
                        failure_threshold=self._opt_int(
                            "quarantine_threshold"
                        ),
                        quarantine_seconds=self._opt_float(
                            "quarantine_seconds"
                        ),
                    ))
        return self.health

    def _record_worker_failure(self, url: str) -> None:
        if url and self._health_tracker().record_failure(url):
            self.faults.bump("workers_quarantined")
            self._event("worker_quarantined", worker=url)

    def _record_worker_success(self, url: str) -> None:
        if self.health is not None and url:
            self.health.record_success(url)

    # -- straggler hedging ---------------------------------------------------
    def _hedge_budget(self):
        if self.hedges is None:
            from datafusion_distributed_tpu.runtime.metrics import (
                HedgeBudget,
            )

            with _HEDGE_INIT_LOCK:
                if self.hedges is None:  # double-checked: fan-out threads
                    self.hedges = HedgeBudget()
        return self.hedges

    def _hedge_threshold(self) -> Optional[float]:
        """Seconds an attempt may run before a speculative re-dispatch,
        or None with hedging off. max(sketch-p<hedge_quantile>,
        hedge_floor_s): the floor keeps a COLD sketch from hedging
        everything instantly (and the in-flight budget bounds whatever
        the floor still admits)."""
        from datafusion_distributed_tpu.ops.table import parse_bool_knob

        v = self.config_options.get("hedging", False)
        try:
            enabled = parse_bool_knob(v)
        except Exception:
            enabled = bool(v)
        if not enabled:
            return None
        q = min(max(self._opt_float("hedge_quantile"), 0.0), 1.0)
        floor = max(self._opt_float("hedge_floor_s"), 0.0)
        p = None
        if self.latency is not None and getattr(self.latency, "count", 0):
            try:
                p = self.latency.percentile(q)
            except Exception:
                p = None
        threshold = max(p or 0.0, floor)
        return threshold if threshold > 0 else None

    def _stage_span_shipped(self, query_id: str, stage_id: int) -> bool:
        """Whether this (query, stage) shipped as mesh SPANS: a span plan
        is shared across sibling tasks, so neither a lone-task
        re-dispatch nor a lone-task hedge is defined for it."""
        spans = getattr(self, "_span_shipped", None)
        if not spans:
            return False
        with self._span_lock:  # vs concurrent sibling-stage shipment
            return any(
                k[0] == query_id and k[1] == stage_id for k in spans
            )

    def _record_hedge_loss(self, url: str) -> None:
        """Hedge-loss mark, DISTINCT from a failure: never advances the
        circuit breaker (runtime/health.py record_hedge_loss)."""
        if not url:
            return
        tracker = self._health_tracker()
        mark = getattr(tracker, "record_hedge_loss", None)
        if callable(mark):
            mark(url)

    def _dispatch_hedge(self, stage_plan, query_id, stage_id, task_number,
                        task_count, primary_url, state):
        """Speculatively dispatch the SAME task to a different healthy
        worker; -> (worker, key, plan_obj, store) or None (no budget, no
        alternative candidate, or the dispatch itself failed — a hedge
        that cannot launch must never fail the primary attempt)."""
        try:
            urls = self.resolver.get_urls()
        except Exception:
            return None
        if not any(u != primary_url for u in urls):
            return None  # single-worker cluster: nowhere to hedge to
        budget = self._hedge_budget()
        if not budget.try_acquire(self._opt_int("hedge_budget")):
            self.faults.bump("hedge_budget_denied")
            return None
        ok = False
        try:
            disp = self._dispatch_task(
                stage_plan, query_id, stage_id, task_number, task_count,
                exclude=set(state.excluded) | {primary_url},
            )
            if disp[0].url == primary_url:
                # exclusion fell back to the primary (every alternative
                # quarantined): hedging the same worker is pure waste
                try:
                    self._cleanup_task(*disp)
                except Exception:
                    pass
                self.faults.bump("hedges_abandoned")
                return None
            ok = True
            return disp
        except Exception:
            self.faults.bump("hedges_abandoned")
            return None
        finally:
            if not ok:
                budget.release()

    def _hedged_execute(self, stage_plan, query_id, stage_id, task_number,
                        task_count, primary, threshold, state, asp):
        """Bulk-plane hedge race: run the already-dispatched ``primary``
        attempt in a thread; if it outlives ``threshold``, speculatively
        re-dispatch to a different worker and let the FIRST completed
        attempt win. The loser is cancelled through its per-attempt
        cancel handle and its thread releases its staged slices when the
        in-flight call resolves (execute's finally joins these threads,
        so the query never resolves with a release still pending).
        -> (winner worker, result Table). Raises the primary's error when
        every attempt fails (the normal retry loop takes over)."""
        import queue as _queue
        import threading as _threading

        tr = self._tr()
        results: "_queue.Queue" = _queue.Queue()
        race_lock = _threading.Lock()
        attempts: list = []

        def start(disp, speculative: bool) -> dict:
            ev = _threading.Event()
            att = {
                "worker": disp[0], "key": disp[1], "plan_obj": disp[2],
                "store": disp[3], "ev": ev, "spec": speculative,
                "lost": False,
            }
            cancel = _EitherSet(self._cancel_event, ev)

            def run() -> None:
                sp = tr.start_span(
                    "execute_rpc", "execute", parent=asp.span_id,
                    worker=att["worker"].url, hedge=speculative,
                )
                payload = None
                try:
                    out = self._execute_attempt(
                        att["worker"], att["key"], cancel=cancel
                    )
                except BaseException as e:
                    sp.set(error=type(e).__name__)
                    payload = (att, None, e)
                else:
                    payload = (att, out, None)
                finally:
                    tr.end_span(sp)
                    if speculative:
                        self._hedge_budget().release()
                # deliver-or-discard under the race lock: after the main
                # thread marks an attempt lost, nothing more enqueues
                with race_lock:
                    if not att["lost"]:
                        results.put(payload)
                if payload[2] is None and not att["lost"]:
                    # winner-side metrics (losers are being discarded: a
                    # cancelled attempt's wall must not feed the sketch)
                    try:
                        self._record_task_progress(att["worker"],
                                                   att["key"])
                    except Exception:
                        pass
                try:
                    self._cleanup_task(att["worker"], att["key"],
                                       att["plan_obj"], att["store"])
                except Exception:
                    pass

            t = _threading.Thread(target=run, daemon=True,
                                  name="dftpu-hedge")
            attempts.append(att)
            self._hedge_threads.append(t)
            t.start()
            return att

        start(primary, speculative=False)
        started = 1
        hedged = False
        first = None
        try:
            first = results.get(timeout=threshold)
        except _queue.Empty:
            disp = self._dispatch_hedge(
                stage_plan, query_id, stage_id, task_number, task_count,
                primary[0].url, state,
            )
            if disp is not None:
                hedged = True
                self.faults.bump("hedges_issued")
                self._event(
                    "hedge_issued", stage=stage_id, task=task_number,
                    primary=primary[0].url, hedge=disp[0].url,
                    threshold_ms=round(threshold * 1e3, 1),
                )
                start(disp, speculative=True)
                started = 2
        errors: list = []
        winner = None
        while winner is None:
            while first is None:
                try:
                    first = results.get(timeout=0.05)
                except _queue.Empty:
                    if self._cancelled():
                        self._abandon_attempts(attempts, race_lock)
                        self._check_cancelled()
            att, out, err = first
            first = None
            if err is None:
                winner = (att, out)
                break
            errors.append((att, err))
            if len(errors) >= started:
                # every attempt failed: surface the PRIMARY's error (the
                # retry loop's health/reroute attribution expects it) and
                # count the non-surfaced failures against their workers
                surfaced = next(
                    (e for a, e in errors if not a["spec"]),
                    errors[0][1],
                )
                self._note_failed_attempts(
                    [(a, e) for a, e in errors if e is not surfaced]
                )
                raise surfaced
        att, out = winner
        # the race resolved with a success: attempts that FAILED before
        # the win were genuine failures (breaker-visible); attempts still
        # running merely LOST (cancelled, breaker-neutral)
        self._note_failed_attempts(errors)
        failed = {id(a) for a, _e in errors}
        self._abandon_attempts(
            [a for a in attempts if a is not att], race_lock,
        )
        for a in attempts:
            if a is not att and id(a) not in failed:
                self._record_hedge_loss(a["worker"].url)
        if hedged:
            name = "hedge_won" if att["spec"] else "hedge_lost"
            self.faults.bump("hedges_won" if att["spec"] else
                             "hedges_lost")
            self._event(name, stage=stage_id, task=task_number,
                     worker=att["worker"].url)
        return att["worker"], out

    def _abandon_attempts(self, atts, race_lock) -> None:
        """Mark ``atts`` lost (their threads stop delivering and discard
        their own results/slices) and set their cancel handles."""
        for a in atts:
            with race_lock:
                a["lost"] = True
            a["ev"].set()

    def _note_failed_attempts(self, errors) -> None:
        """Health accounting for hedge-race attempts that FAILED with a
        genuine error (collected before any winner, so never
        cancellation-induced): a retryable infrastructure failure counts
        against its worker's breaker exactly as the unhedged path would
        count it — a worker that keeps crashing hedge attempts must not
        stay quarantine-proof just because a sibling attempt won."""
        member = set(self._full_membership_urls())
        for a, e in errors:
            if not is_retryable(e):
                continue  # query-semantic: no breaker input (as unhedged)
            url = getattr(e, "worker_url", "") or a["worker"].url
            if url in member:
                self._record_worker_failure(url)

    def _discard_attempt(self, att, it) -> None:
        """Release a losing (or abandoned) streaming attempt: close its
        chunk iterator (the worker-side stream's own cleanup runs in its
        finalizers) and drop its staged slices. Best-effort and silent —
        teardown of discarded work must never mask or fail anything."""
        try:
            if it is not None:
                it.close()
        except Exception:
            pass
        try:
            self._cleanup_task(att["worker"], att["key"],
                               att["plan_obj"], att["store"])
        except Exception:
            pass

    def _hedged_first_chunk(self, stage_plan, query_id, stage_id,
                            task_number, task_count, primary, body,
                            cancel, threshold, state, done, pull_span):
        """Streaming-plane hedge race over the FIRST chunk (which
        contains the task's execution — later chunks slice an already-
        materialized output). Returns the winning attempt's
        (worker, key, plan_obj, store, iterator, first_item); the caller
        adopts the iterator and streams it exactly like an unhedged pull,
        so the retry-while-nothing-yielded contract is preserved. Losers
        are cancelled per-attempt and release their own staged state.
        Raises the primary's error when every attempt fails."""
        import queue as _queue
        import threading as _threading

        tr = self._tr()
        timeout = self._opt_float("task_timeout_s")
        results: "_queue.Queue" = _queue.Queue()
        race_lock = _threading.Lock()
        attempts: list = []

        def start(disp, speculative: bool) -> dict:
            ev = _threading.Event()
            att = {
                "worker": disp[0], "key": disp[1], "plan_obj": disp[2],
                "store": disp[3], "ev": ev, "spec": speculative,
                "lost": False,
            }
            # the attempt's pollable cancel merges the CALLER's stream
            # cancel (LIMIT satisfied / sibling failure) with this
            # attempt's private loser-cancel and the per-query event
            combined = _EitherSet(
                cancel, _EitherSet(ev, self._cancel_event)
            )

            def run() -> None:
                sp = tr.start_span(
                    "pull_attempt", "execute", parent=pull_span.span_id,
                    worker=att["worker"].url, hedge=speculative,
                )
                it = None
                payload = None
                try:
                    it = iter(body(att["worker"], att["key"], combined))
                    if timeout:
                        first = call_with_deadline(
                            lambda: next(it, done), timeout,
                            att["worker"].url, att["key"],
                        )
                    else:
                        first = next(it, done)
                except BaseException as e:
                    sp.set(error=type(e).__name__)
                    payload = (att, None, None, e)
                else:
                    payload = (att, it, first, None)
                finally:
                    tr.end_span(sp)
                    if speculative:
                        self._hedge_budget().release()
                # deliver-or-discard under the race lock: once the main
                # thread marks this attempt lost, nothing more enqueues —
                # so a post-race drain of the queue sees every delivered
                # loser, and an undelivered loser discards itself here
                with race_lock:
                    lost = att["lost"]
                    if not lost:
                        results.put(payload)
                if payload[3] is not None:
                    # a FAILED attempt's staged state is dead no matter
                    # how the race resolves (the main thread never adopts
                    # an error): release it here — idempotent with the
                    # caller's primary-cleanup on the all-failed path
                    self._discard_attempt(att, it)
                elif lost:
                    self._discard_attempt(att, it)

            t = _threading.Thread(target=run, daemon=True,
                                  name="dftpu-hedge-pull")
            attempts.append(att)
            self._hedge_threads.append(t)
            t.start()
            return att

        start(primary, speculative=False)
        started = 1
        hedged = False
        first_res = None
        try:
            first_res = results.get(timeout=threshold)
        except _queue.Empty:
            disp = self._dispatch_hedge(
                stage_plan, query_id, stage_id, task_number, task_count,
                primary[0].url, state,
            )
            if disp is not None:
                hedged = True
                self.faults.bump("hedges_issued")
                self._event(
                    "hedge_issued", stage=stage_id, task=task_number,
                    primary=primary[0].url, hedge=disp[0].url,
                    threshold_ms=round(threshold * 1e3, 1),
                    plane="stream",
                )
                start(disp, speculative=True)
                started = 2
        errors: list = []
        winner = None
        while winner is None:
            while first_res is None:
                try:
                    first_res = results.get(timeout=0.05)
                except _queue.Empty:
                    if self._cancelled():
                        self._abandon_attempts(attempts, race_lock)
                        self._drain_discard(results)
                        self._check_cancelled()
            att, it, first, err = first_res
            first_res = None
            if err is None:
                winner = (att, it, first)
                break
            errors.append((att, err))
            if len(errors) >= started:
                # surface the PRIMARY's error for the retry loop's
                # attribution; count the non-surfaced failures here
                surfaced = next(
                    (e for a, e in errors if not a["spec"]),
                    errors[0][1],
                )
                self._note_failed_attempts(
                    [(a, e) for a, e in errors if e is not surfaced]
                )
                raise surfaced
        att, it, first = winner
        # failed-before-the-win attempts are breaker-visible failures;
        # still-running attempts merely lost the race (breaker-neutral)
        self._note_failed_attempts(errors)
        failed = {id(a) for a, _e in errors}
        self._abandon_attempts(
            [a for a in attempts if a is not att], race_lock,
        )
        # a loser that DELIVERED before being marked lost sits in the
        # queue: its iterator/slices are discarded here (its thread
        # already exited and will not)
        self._drain_discard(results)
        for a in attempts:
            if a is not att and id(a) not in failed:
                self._record_hedge_loss(a["worker"].url)
        if hedged:
            name = "hedge_won" if att["spec"] else "hedge_lost"
            self.faults.bump("hedges_won" if att["spec"] else
                             "hedges_lost")
            self._event(name, stage=stage_id, task=task_number,
                     worker=att["worker"].url, plane="stream")
        return (att["worker"], att["key"], att["plan_obj"],
                att["store"], it, first)

    def _drain_discard(self, results) -> None:
        """Discard every already-delivered losing attempt in ``results``
        (close iterators, release slices)."""
        import queue as _queue

        while True:
            try:
                late = results.get_nowait()
            except _queue.Empty:
                return
            att, it, _first, err = late
            if err is None:
                self._discard_attempt(att, it)

    def _handle_task_failure(self, exc, url, key_tuple, state) -> bool:
        """Record + classify a failed task attempt; True -> caller retries.

        Retry only the retryable taxonomy (TransportError /
        WorkerUnavailableError / TaskTimeoutError — runtime/errors.py):
        query-semantic failures are deterministic and re-executing them
        N more times would just burn the cluster before surfacing the
        SAME error. Each retried attempt excludes the workers that
        already failed this task, so the re-dispatch reroutes (the
        excluded-runner idea); exclusion falls away when it would leave
        no candidate (single-worker clusters retry in place).

        Only RETRYABLE (infrastructure) errors count toward quarantine:
        a query-semantic failure would raise identically on any worker,
        and tripping breakers on it would punish healthy endpoints."""
        member = set(self._full_membership_urls())
        if not is_retryable(exc):
            if url and member and url not in member and isinstance(
                exc, WorkerError
            ):
                # the failure is attributed to a worker that LEFT the
                # membership: whatever the attempt relied on — staged
                # slices, cached partitions, an in-flight execution —
                # died with it, so the "fatal" classification is an
                # artifact of the departure. Reclassify as retryable
                # infrastructure so the task re-stages onto survivors.
                self.faults.bump("departed_worker_faults")
            else:
                if isinstance(exc, WorkerError):
                    self.faults.bump("fatal_failures")
                return False
        if url and url in member:
            # departed workers get no breaker state: quarantining an
            # endpoint that no longer exists would only re-grow the
            # health map the membership prune just cleaned
            self._record_worker_failure(url)
        if self._stage_span_shipped(key_tuple[0], key_tuple[1]):
            # this (query, stage) actually shipped as mesh SPANS: a
            # span plan is shared across sibling tasks, so
            # re-dispatching a lone task elsewhere is undefined.
            # Keyed on what shipped, not on the width cache — a
            # membership change resetting the cache mid-stage must
            # not silently lift this guard
            return False
        if state.attempt >= self._opt_int("max_task_retries"):
            self.faults.bump("retries_exhausted")
            self._event(
                "retries_exhausted", stage=key_tuple[1],
                task=key_tuple[2], error=type(exc).__name__,
            )
            return False
        if isinstance(exc, TaskTimeoutError):
            self.faults.bump("task_timeouts")
        self.faults.bump("task_retries")
        self._event(
            "task_retry", stage=key_tuple[1], task=key_tuple[2],
            attempt=state.attempt, worker=url,
            error=type(exc).__name__,
        )
        if url:
            state.excluded.add(url)
        self._retry_backoff(key_tuple, state.attempt)
        state.attempt += 1
        return True

    def _retry_backoff(self, key_tuple, attempt: int) -> None:
        """Exponential backoff with DETERMINISTIC jitter: the jitter is a
        hash of (task identity, attempt), so a replayed failure schedule
        sleeps identically — fault-injection runs stay reproducible while
        concurrent retries still de-synchronize."""
        base = self._opt_float("task_retry_backoff_s")
        if base <= 0:
            return
        import time as _time
        import zlib as _zlib

        jitter = _zlib.crc32(
            repr((key_tuple, attempt)).encode()
        ) / 0xFFFFFFFF
        _time.sleep(base * (2.0 ** attempt) + base * jitter)

    def _dispatch_task_with_retry(self, stage_plan, query_id, stage_id,
                                  task_number, task_count, ttl=None,
                                  state=None, trace_parent=None):
        """Dispatch with retry + reroute. Standalone (peer-plane producers:
        ship now, execute at first pull) or as the shared dispatch phase of
        the execute/pull retry loops — ``state`` threads ONE attempt budget
        across both phases of a task. ``trace_parent``: explicit trace-span
        parent for callers whose thread has no span stack (streaming
        pullers)."""
        state = state if state is not None else _RetryState()
        kt = (query_id, stage_id, task_number)
        while True:
            self._check_cancelled()
            try:
                disp = self._dispatch_task(
                    stage_plan, query_id, stage_id, task_number, task_count,
                    ttl=ttl, exclude=state.excluded,
                    trace_parent=trace_parent,
                )
            except BaseException as e:
                if self._handle_task_failure(
                    e, getattr(e, "worker_url", "") or "", kt, state
                ):
                    continue
                raise
            if state.attempt and disp[0].url not in state.excluded:
                self.faults.bump("tasks_rerouted")
                self._event(
                    "task_rerouted", stage=stage_id, task=task_number,
                    worker=disp[0].url,
                )
            return disp

    def _pull_task_with_retry(self, stage_plan, query_id, stage_id,
                              task_number, task_count, body, cancel,
                              ttl=None, trace_parent=None):
        """Streaming-plane analogue of `_run_stage_task`'s retry loop:
        dispatch + run ``body(worker, key, cancel)`` (a chunk iterator),
        re-dispatching on retryable failures for as long as NOTHING has
        been yielded yet. Once a chunk is out, a replayed stream could
        double rows downstream, so mid-stream failures stay fatal.

        The execution deadline (`task_timeout_s`) covers the wait for the
        FIRST chunk — that wait contains the task's actual execution (the
        output materializes before any chunk can stream), so a hung worker
        converts into the retryable TaskTimeoutError here too; later
        chunks slice an already-materialized output and stream without
        per-chunk deadline overhead."""
        timeout = self._opt_float("task_timeout_s")
        state = _RetryState()
        kt = (query_id, stage_id, task_number)
        done = object()  # first-chunk sentinel: body produced nothing
        tr = self._tr()
        pull_parent = trace_parent
        if pull_parent is None and tr.active:
            pull_parent = tr.reserved_id(("stage", stage_id))
        while True:
            self._check_cancelled()
            # explicit start/end (no context manager): the span covers
            # the pull's full streaming lifetime across generator
            # suspensions, ending when the attempt resolves or the
            # consumer closes the stream
            pull_span = tr.start_span(
                "pull", "execute", parent=pull_parent,
                stage=stage_id, task=task_number, attempt=state.attempt,
            )
            try:
                worker, key, plan_obj, store = (
                    self._dispatch_task_with_retry(
                        stage_plan, query_id, stage_id, task_number,
                        task_count, ttl=ttl, state=state,
                        trace_parent=pull_span.span_id,
                    )
                )
            except BaseException as e:
                tr.end_span(pull_span.set(error=type(e).__name__))
                raise
            pull_span.set(worker=worker.url)
            yielded = False
            hedge_after = self._hedge_threshold()
            try:
                try:
                    if hedge_after is not None and (
                        not self._stage_span_shipped(query_id, stage_id)
                    ):
                        # hedge arm (streaming plane): race the FIRST
                        # chunk — the wait that contains the execution —
                        # against a speculative re-dispatch; the winner's
                        # iterator is adopted below, so nothing has been
                        # yielded before the race resolves and replay
                        # safety is untouched
                        worker, key, plan_obj, store, it, first = (
                            self._hedged_first_chunk(
                                stage_plan, query_id, stage_id,
                                task_number, task_count,
                                (worker, key, plan_obj, store),
                                body, cancel, hedge_after, state, done,
                                pull_span,
                            )
                        )
                        pull_span.set(worker=worker.url)
                    else:
                        it = iter(body(worker, key, cancel))
                        if timeout:
                            first = call_with_deadline(
                                lambda: next(it, done), timeout,
                                worker.url, key,
                            )
                        else:
                            first = next(it, done)
                    if first is not done:
                        yielded = True
                        yield first
                        for item in it:
                            yield item
                    # best-effort, as in _run_stage_task: a flaky metrics
                    # read must not fail a fully-streamed task
                    try:
                        self._record_task_progress(worker, key)
                    except Exception:
                        pass
                finally:
                    # best-effort for the same reason as _run_stage_task:
                    # never discard streamed chunks or mask the real error
                    try:
                        self._cleanup_task(worker, key, plan_obj, store)
                    except Exception:
                        pass
            except GeneratorExit:
                # the consumer abandoned the stream (satisfied LIMIT /
                # sibling failure cancellation) — not a worker fault:
                # cleanup already ran in the finally; just unwind
                tr.end_span(pull_span.set(abandoned=True))
                raise
            except BaseException as e:
                tr.end_span(pull_span.set(error=type(e).__name__))
                if cancel is not None and cancel.is_set():
                    # the stream was cancelled (satisfied LIMIT or a
                    # sibling's fatal error): teardown-induced failures
                    # are not worker faults and the output is already
                    # being discarded — no backoff, no health record,
                    # no re-dispatch
                    return
                if not yielded and self._handle_task_failure(
                    e, getattr(e, "worker_url", "") or worker.url, kt, state
                ):
                    # the failure may be a departed PEER PRODUCER feeding
                    # this streamed stage: re-ship it onto a survivor and
                    # rewrite the pull specs before the re-dispatch
                    self._heal_departed_peers(stage_plan, query_id)
                    continue
                raise
            tr.end_span(pull_span)
            self._record_worker_success(worker.url)
            return

    # -- shared task dispatch (bulk + streaming planes) ----------------------
    def _prepare_stage_plan(self, stage_plan: ExecutionPlan) -> ExecutionPlan:
        """Hook: last-moment stage-plan rewrite before shipping (the
        AdaptiveCoordinator resizes capacities from exact input stats)."""
        return stage_plan

    def _routable_urls(self, exclude=None) -> list[str]:
        """Candidate worker urls for a dispatch: quarantined workers (open
        circuit, runtime/health.py) are routed around, and a retry's
        ``exclude`` set steers the re-dispatch away from workers that
        already failed this task. Exclusion is best-effort — when it would
        leave no candidate (single-worker cluster), the excluded workers
        come back; quarantine is not — with every circuit open the query
        fails rather than hammer known-bad endpoints.

        Candidates come from LIVE membership on every call: a retry's
        ``exclude`` set is first PRUNED of urls that departed the cluster,
        so the no-candidate fallback keys on the membership of THIS
        attempt, not attempt 0's — a cluster that shrank mid-retry cannot
        exclude itself into a dead end, and a joiner is immediately
        eligible."""
        urls = self.resolver.get_urls()
        self._note_membership(urls)
        if not urls:
            raise _terminal(WorkerUnavailableError("cluster has no workers"))
        if exclude:
            # in-place: the caller's _RetryState.excluded forgets departed
            # workers for its NEXT attempts too
            exclude.intersection_update(urls)
        if self.health is not None:
            healthy = self.health.route_filter(urls)
            if not healthy:
                # RETRYABLE under elastic membership: time CAN conjure a
                # healthy worker — a quarantine expires into a half-open
                # probe, an outstanding probe resolves, a joiner arrives.
                # The retry backoff rides out the window without hammering
                # anything (this raise happens before any RPC), and the
                # retry budget still bounds a truly dead cluster
                raise WorkerUnavailableError(
                    f"no healthy workers remain ({len(urls)} quarantined)"
                )
            urls = healthy
        if exclude:
            candidates = [u for u in urls if u not in exclude]
            if candidates:
                urls = candidates
        return urls

    def _dispatch_task(self, stage_plan, query_id, stage_id, task_number,
                       task_count, ttl=None, exclude=None,
                       trace_parent=None):
        """Route, task-specialize, ship: -> (worker, key, plan_obj, store).
        ``ttl`` overrides the worker registry's idle-TTL for this entry
        (peer producers live until pulled or swept). ``exclude``: urls a
        retry must route around (the failed attempts' workers)."""
        disp = self._try_dispatch_span(stage_plan, query_id, stage_id,
                                       task_number, task_count, ttl=ttl)
        if disp is not None:
            return disp
        urls = self._routable_urls(exclude)
        if self.route_tasks is not None:
            url = self.route_tasks(query_id, stage_id, task_number, urls)
        else:
            url = urls[(stage_id + task_number) % len(urls)]  # round-robin
        worker = self.channels.get_worker(url)
        key = TaskKey(query_id, stage_id, task_number)
        store = worker.table_store
        tr = self._tr()
        with tr.span("dispatch", "dispatch", parent=trace_parent,
                     stage=stage_id, task=task_number, worker=url) as dsp:
            with tr.span("encode", "codec", stage=stage_id) as esp:
                from datafusion_distributed_tpu.runtime.codec import (
                    staging_attribution,
                )

                # per-query staged-byte attribution (estimate-vs-measured
                # loop): owned bytes this encode stages into the worker
                # store are charged to this query id
                with staging_attribution(query_id):
                    plan_obj = encode_plan(
                        _task_specialized(stage_plan, task_number), store
                    )
                if tr.active:
                    from datafusion_distributed_tpu.runtime.codec import (
                        collect_table_ids as _ctids,
                    )

                    # staged bytes: the slices this ship moves into the
                    # worker's TableStore (in-process: by reference; wire:
                    # serialized) — the store's RECORDED entry sizes, so
                    # encode spans and store accounting can never disagree
                    # (entry_nbytes is table_nbytes captured at put time)
                    esp.set(bytes=sum(
                        store.entry_nbytes(tid)
                        for tid in _ctids(plan_obj)
                    ))
            config = self.config_options
            if tr.active:
                # cross-wire trace context: rides the task envelope's
                # config dict. NEVER a compile-cache input — the worker
                # strips it before execute_plan, physical.py filters it
                # from cfg_items (span ids differ per task; keying on
                # them would force one XLA trace per task). The parent is
                # the span ABOVE the dispatch (the task attempt / pull),
                # so worker-side spans slot in as siblings of dispatch
                # and execute, where they belong on the timeline.
                ctx = tr.wire_ctx()
                ctx["parent"] = dsp.parent_id
                config = {**config, TRACE_CTX_KEY: ctx}
            ship_kw = {}
            ship_cancel = getattr(self, "_cancel_event", None)
            if ship_cancel is not None and self._worker_accepts_param(
                worker, "set_plan", "cancel"
            ):
                # surfaces that declare a dispatch cancel (chaos proxies)
                # get the per-query event so injected ship delays abort
                # at cancellation latency
                ship_kw["cancel"] = ship_cancel
            dispatch_timeout = self._opt_float("dispatch_timeout_s")
            if dispatch_timeout and self._worker_accepts_timeout(
                worker, "set_plan"
            ):
                # pass only when configured AND the surface declares it:
                # custom duck-typed workers predating the deadline
                # parameter keep working (no deadline) instead of dying
                # on a TypeError
                ship_kw["timeout"] = dispatch_timeout
            try:
                with tr.span("ship", "rpc", worker=url):
                    # a wire transport returns the framed bytes it put on
                    # the wire (GrpcWorkerClient.set_plan); in-process
                    # workers return None — no wire hop to attribute
                    shipped = worker.set_plan(
                        key, plan_obj, task_count, config=config,
                        headers=self.passthrough_headers, ttl=ttl,
                        **ship_kw,
                    )
            except BaseException:
                # a failed ship leaves no registry entry to own the staged
                # slices — release them here or they leak until process
                # exit
                from datafusion_distributed_tpu.runtime.codec import (
                    collect_table_ids,
                )

                store.remove(collect_table_ids(plan_obj))
                raise
            if tr.active and isinstance(shipped, int):
                dsp.set(wire_bytes=shipped)
        return worker, key, plan_obj, store

    def _try_dispatch_span(self, stage_plan, query_id, stage_id,
                           task_number, task_count, ttl=None):
        """Meshes-as-workers dispatch (SURVEY §2.10 "same-mesh = collective,
        off-mesh = RPC"): when every worker owns a device mesh
        (`MeshWorker.mesh_width`), a stage's tasks ship as contiguous
        SPANS — worker k gets tasks [kW, (k+1)W) as ONE span plan and runs
        them as a single SPMD program. Per-task keys stay the data-plane
        address, so peer pulls/streams work unchanged between meshes.
        Returns None when span dispatch does not apply (mixed cluster,
        custom routing, span-inexpressible plans)."""
        if self.route_tasks is not None:
            return None
        tok = self._note_membership()
        cached_w = getattr(self, "_mesh_span_width", None)
        if cached_w is not None and cached_w[0] == tok:
            span_w = cached_w[1]
        else:
            # cached per membership token (stored WITH the token and
            # ignored on mismatch — same stale-probe protection as
            # _workers_peer_capable)
            urls0 = self.resolver.get_urls()
            widths = [
                getattr(self.channels.get_worker(u), "mesh_width", 0)
                for u in urls0
            ]
            span_w = min(widths) if widths and all(
                w > 0 for w in widths
            ) else 0
            self._mesh_span_width = (tok, span_w)
        if span_w <= 0:
            return None
        from datafusion_distributed_tpu.runtime.mesh_worker import (
            span_specializable,
            span_specialized,
        )

        if not hasattr(self, "_span_lock"):
            # direct-call safety (tests invoke without execute): bare
            # writes here happen-before any sibling-stage thread shares
            # this coordinator (allowlisted DFTPU201, like execute's
            # fresh per-query resets)
            import threading as _threading

            self._span_lock = _threading.Lock()
            self._span_shipped = {}
            self._span_ok_cache = {}
        # keyed by (query, stage): per-task prepared plans are transient
        # objects (id() recycles within a query) but share one structure
        ok_key = (query_id, stage_id)
        with self._span_lock:
            # DFTPU201 fix: sibling-stage threads share this cache —
            # the check-then-insert ran unlocked before this lint
            ok = self._span_ok_cache.get(ok_key)
            if ok is None:
                ok = self._span_ok_cache[ok_key] = span_specializable(
                    stage_plan
                )
        if not ok:
            return None
        span = task_number // span_w
        key = TaskKey(query_id, stage_id, task_number)
        lo, hi = span * span_w, min((span + 1) * span_w, task_count)
        ship_key = (query_id, stage_id, lo)
        with self._span_lock:
            hit = self._span_shipped.get(ship_key)
            if hit is None:
                # route from live membership only when SHIPPING the span;
                # sibling tasks reuse the shipped worker below, so a
                # membership change between siblings cannot split one
                # span's tasks across two workers (only one of which
                # holds the span plan)
                urls = self.resolver.get_urls()
                url = urls[(stage_id + span) % len(urls)]
                worker = self.channels.get_worker(url)
                from datafusion_distributed_tpu.runtime.codec import (
                    staging_attribution,
                )

                with staging_attribution(query_id):
                    plan_obj = encode_plan(
                        span_specialized(stage_plan, lo, hi),
                        worker.table_store,
                    )
                try:
                    worker.set_stage_plan(
                        query_id, stage_id, lo, hi, task_count, plan_obj,
                        config=self.config_options,
                        headers=self.passthrough_headers,
                        ttl=ttl,
                    )
                except BaseException:
                    from datafusion_distributed_tpu.runtime.codec import (
                        collect_table_ids,
                    )

                    worker.table_store.remove(collect_table_ids(plan_obj))
                    raise
                hit = self._span_shipped[ship_key] = (plan_obj, worker)
        plan_obj, worker = hit
        return worker, key, plan_obj, worker.table_store

    def _record_task_progress(self, worker, key) -> None:
        tr = self._tr()
        # tracing reads the progress payload even with metrics collection
        # off: the worker-side spans ride it, and `collect_metrics=False`
        # must not silently amputate the cross-wire half of a trace the
        # user explicitly turned on
        if not self.collect_metrics and not tr.active:
            return
        progress = worker.task_progress(key) or {}
        # worker-side spans (decode/execute, runtime/worker.py) ride the
        # progress payload over BOTH transports; splice them into the
        # query trace under their propagated wire parent — this is the
        # cross-wire join making worker time attributable per task
        spans = progress.pop("spans", None)
        if spans and tr.active:
            tr.splice(spans)
        if not self.collect_metrics:
            return
        self.metrics[key] = progress
        elapsed = progress.get("elapsed_s")
        if elapsed is not None and self.latency is not None:
            self.latency.record(float(elapsed))

    def _cleanup_task(self, worker, key, plan_obj, store) -> None:
        # drop-driven cleanup: the task's cache entry AND its shipped
        # table slices are released as soon as its single partition is
        # consumed (reference: on_drop_stream + invalidate,
        # `impl_execute_task.rs:97-112`)
        worker.registry.invalidate(key)
        from datafusion_distributed_tpu.runtime.codec import (
            collect_table_ids,
        )

        store.remove(collect_table_ids(plan_obj))


@dataclass
class AdaptiveCoordinator(Coordinator):
    """Dynamic-planning coordinator (the reference's `dynamic_task_count`
    mode): consumer stages are re-sized from runtime LoadInfo — planning
    and execution interleave (`prepare_dynamic_plan.rs`). Both CAPACITIES
    (resize_for_inputs) and TASK COUNTS (compute_based_task_count analogue:
    ceil(bytes / bytes_per_task)) adapt.

    Mid-execution sampling: every dispatch path streams per-completion
    LoadInfo (`_producer_progress` — the reference's SamplerExec stream,
    `sampler.rs:30-42`); once `sample_fraction` of a stage's producer
    tasks have completed, the consumer's statistics are EXTRAPOLATED from
    that partial per-task sample and frozen — the sizing decision is taken
    while the remaining producers are still running, exactly the
    reference's 20%%-sample short-circuit (`prepare_dynamic_plan.rs:
    111-141,206-331`). In this bulk-synchronous host tier the consumer
    still launches only after its inputs materialize, so what the early
    freeze buys is the reference's decision protocol (sample-extrapolated
    sizing, available to e.g. pre-compile or pre-provision the consumer)
    rather than wall-clock overlap; stages whose producers finish before
    the threshold fall back to exact statistics."""

    #: declarative concurrency model: the co-shuffled-group barrier state
    #: mutates from sibling stage-DAG threads (see _finish_shuffle); the
    #: read-only group topology maps (_group_of/_group_members/
    #: _group_heads) are written once in execute before any fan-out
    _GUARDED_BY = {"_group_pending": "_group_lock"}

    #: compute_based_task_count divisor (prepare_dynamic_plan.rs:60-69 uses
    #: cpu_cost / bytes_per_partition_per_second; here exact bytes / this)
    bytes_per_task: int = 16 << 20
    #: fraction of producer tasks whose completion triggers the partial-
    #: sample decision (the reference short-circuits at 20% sampling)
    sample_fraction: float = 0.25
    #: safety margin applied to extrapolated rows (underestimating a
    #: capacity costs an overflow-retry; overestimating only pads)
    extrapolation_headroom: float = 1.25
    #: resize_for_inputs headroom; quadruples after an overflow so the
    #: session's overflow-retry CONVERGES — otherwise each retry replans
    #: wider and the adaptive resize shrinks straight back to the same
    #: overflowing capacity
    resize_headroom: float = 2.0

    #: multiplier applied to resize_headroom per overflow (and per pinned
    #: retry attempt — both schedules MUST share this constant)
    OVERFLOW_WIDEN_FACTOR = 4.0

    def __post_init__(self):
        # remember the CONSTRUCTED value: the post-query reset must restore
        # a caller-configured headroom, not clobber it with the class default
        self._base_resize_headroom = self.resize_headroom
        self._headroom_pinned = False

    def _checkpoint_eligible(self) -> bool:
        """Adaptive lattices derive from runtime LoadInfo (consumer task
        counts and capacities re-sized mid-query from sampled outputs):
        a restored checkpoint lattice could disagree with the one a
        resume would re-derive, so the adaptive coordinator opts out of
        checkpoint save/restore entirely — resumes under it degrade to
        full re-execution, never to a mismatched lattice."""
        return False

    def pin_overflow_headroom(self, attempt: int) -> None:
        """Widen the resize headroom for retry ``attempt`` of one query and
        PIN it: scalar subqueries execute through this same coordinator and
        their success must not reset the outer query's widened headroom to
        base mid-attempt (q11's HAVING subquery did exactly that, so the
        overflowing group-by re-ran at base headroom on every retry).
        Callers release with release_overflow_headroom() when the query's
        retry loop ends."""
        self.resize_headroom = (
            self._base_resize_headroom
            * (self.OVERFLOW_WIDEN_FACTOR ** attempt)
        )
        self._headroom_pinned = True

    def release_overflow_headroom(self) -> None:
        self._headroom_pinned = False
        self.resize_headroom = self._base_resize_headroom

    def execute(self, plan: ExecutionPlan) -> Table:
        self._load_info: dict[int, object] = {}
        self.task_count_decisions: list[tuple[int, int, int]] = []
        #: stage_id -> LoadInfo predicted from a partial producer sample
        self._predicted: dict[int, object] = {}
        #: stage_id -> mid-stream per-column sampler (fresh per query:
        #: stage ids repeat across queries)
        self._col_samplers: dict = {}
        #: stage_id -> (done, total) at decision time — test/introspection
        #: surface proving the decision predates producer completion
        self.partial_decisions: dict[int, tuple[int, int]] = {}
        self._solo_shuffles = _find_solo_shuffles(plan)
        # co-shuffled groups (join stages fed by >= 2 shuffles) adapt
        # TOGETHER: the shared consumer count is decided once, from the
        # combined runtime statistics of every feeding shuffle, before any
        # side's slices ship (prepare_dynamic_plan.rs re-injection analogue)
        self._group_of: dict = {}
        self._group_members: dict = {}
        self._group_heads: dict = {}
        self._group_pending: dict = {}
        # serializes group registration under the concurrent stage-DAG
        # scheduler (members of one co-shuffled group materialize in
        # sibling threads; the last-one-in decide must fire exactly once)
        self._group_lock = threading.Lock()
        #: stage_id -> (consumer head node, original exchange node_id) for
        #: the stage-cost model (compute_based_task_count analogue)
        self._stage_heads: dict = {}
        for head, shuffles in _shuffle_consumer_groups(plan):
            for s in shuffles:
                self._stage_heads[s.stage_id] = (head, s.node_id)
            if len(shuffles) >= 2:
                gid = tuple(sorted(s.stage_id for s in shuffles))
                self._group_members[gid] = [s.stage_id for s in shuffles]
                self._group_heads[gid] = head
                for s in shuffles:
                    self._group_of[s.stage_id] = gid
        try:
            out = super().execute(plan)
        except RuntimeError as e:
            if "overflow" in str(e):
                self.resize_headroom *= self.OVERFLOW_WIDEN_FACTOR
            raise
        # success: back to the constructed value so one query's widening does
        # not permanently inflate every later query on this coordinator —
        # UNLESS a retry loop pinned the headroom (pin_overflow_headroom)
        if not self._headroom_pinned:
            self.resize_headroom = self._base_resize_headroom
        return out

    def _partition_streams_enabled(self, exchange) -> bool:
        # adaptive mode recomputes consumer task counts from exact
        # materialized outputs; a partition stream would fix the count
        # in the producer request before those statistics exist
        return False

    def _peer_plane_enabled(self, exchange) -> bool:
        # same rationale: the peer plane fixes partition counts and pull
        # specs at plan-ship time, before runtime statistics exist
        return False

    # -- mid-execution sampling ------------------------------------------
    def _chunk_observer(self, stage_id):
        """Per-stage ColumnStreamSampler fed by in-flight chunks/outputs:
        per-column NDV + null fractions + velocity exist BEFORE the stage
        finishes (the reference SamplerExec's LoadInfo stream,
        `sampler.rs:30-42`)."""
        from datafusion_distributed_tpu.planner.adaptive import (
            ColumnStreamSampler,
        )

        samplers = getattr(self, "_col_samplers", None)
        if samplers is None:
            samplers = self._col_samplers = {}
        if stage_id not in samplers:
            samplers[stage_id] = ColumnStreamSampler()
        return samplers[stage_id].observe

    def _producer_progress(self, stage_id, done, total, rows, width):
        if stage_id in self._predicted or done >= total or done <= 0:
            return
        import math

        if done < max(1, math.ceil(total * self.sample_fraction)):
            return
        from datafusion_distributed_tpu.planner.adaptive import LoadInfo

        pred_rows = int(rows * total / done * self.extrapolation_headroom)
        sampler = getattr(self, "_col_samplers", {}).get(stage_id)
        if sampler is not None and sampler.sampled > 0:
            # freeze WITH the mid-stream column statistics; NDV is
            # extrapolated by producer coverage (hash-partitioned outputs
            # carry disjoint key values, so done/total of the producers
            # have seen ~done/total of the distinct values), null
            # fractions and velocity ride along
            info = sampler.load_info(pred_rows, width,
                                     ndv_scale=total / done)
        else:
            info = LoadInfo(rows=pred_rows, bytes=pred_rows * width)
        self._predicted[stage_id] = info
        self.partial_decisions[stage_id] = (done, total)

    def _seed_consumer_scan(self, exchange, scan) -> None:
        """Freeze the mid-execution prediction as the consumer's LoadInfo:
        `_stage_input_info` will size the consumer stage from the partial
        sample instead of re-measuring the final tables."""
        pred = self._predicted.get(exchange.stage_id)
        if pred is not None:
            self._load_info[scan.node_id] = pred

    def _consumer_task_count(self, exchange, outputs) -> int:
        """Recompute the consumer task count from producer-output bytes
        (dynamic_task_count semantics); the planned count is only an upper
        bound. Uses the mid-execution prediction when one was frozen,
        exact bytes otherwise.

        This method handles SOLO shuffles (consumer stage fed by exactly
        one shuffle). Co-shuffled siblings — a hash-join's sides must agree
        on `hash % t` — adapt together through the deferred group decision
        in `_finish_shuffle`/`_decide_group` (the reference re-plans whole
        stages for the same reason, `prepare_dynamic_plan.rs`).
        Coalesce/broadcast outputs are replicated single tables — task
        counts do not apply to them."""
        from datafusion_distributed_tpu.planner.statistics import row_width

        if not isinstance(exchange, ShuffleExchangeExec):
            return exchange.num_tasks
        if exchange.stage_id not in getattr(self, "_solo_shuffles", set()):
            return exchange.num_tasks
        if not outputs or self.bytes_per_task <= 0:
            return exchange.num_tasks
        pred = self._predicted.get(exchange.stage_id)
        if pred is not None:
            nbytes = pred.bytes
        else:
            width = row_width(outputs[0].schema())
            nbytes = sum(int(o.num_rows) for o in outputs) * width
        want = max(1, -(-nbytes // self.bytes_per_task))
        t = min(exchange.num_tasks, int(want))
        # cost-informed floor: size by the consumer STAGE's modeled device
        # work, not bytes alone (the compute_based_task_count of
        # `prepare_dynamic_plan.rs:60-69`) — a compute-heavy consumer
        # (join probe, multi-round aggregate) keeps more tasks than its
        # input bytes would suggest
        head_info = self._stage_heads.get(exchange.stage_id)
        if head_info is not None:
            from datafusion_distributed_tpu.planner.statistics import (
                PlanStatistics,
                compute_based_task_count,
                stage_cost,
            )

            head, orig_nid = head_info
            rows = (pred.rows if pred is not None
                    else sum(int(o.num_rows) for o in outputs))
            cost = stage_cost(
                head, PlanStatistics(rows={orig_nid: float(rows)})
            )
            t_cost = compute_based_task_count(
                cost, float(max(self.bytes_per_task, 1)), exchange.num_tasks
            )
            t = min(exchange.num_tasks, max(t, t_cost))
        self.task_count_decisions.append(
            (exchange.stage_id if exchange.stage_id is not None else -1,
             exchange.num_tasks, t)
        )
        return t

    def _finish_shuffle(self, exchange, outputs, producer):
        """Co-shuffled siblings defer their regroup until EVERY member of
        the group has materialized its producers; the shared consumer count
        is then decided once from the combined statistics. Solo shuffles
        keep the immediate path (base + adaptive `_consumer_task_count`).

        Under the stage-DAG scheduler the group members materialize
        CONCURRENTLY, so the group decision is a real barrier now, not a
        recursion-order artifact: registration is serialized by
        `_group_lock` and exactly the member that completes the group runs
        `_decide_group` (before its own stage job returns — the DAG edges
        guarantee the consumer stage is only released after every feed's
        job finished, i.e. after the decision filled the placeholders)."""
        gid = self._group_of.get(exchange.stage_id)
        if gid is None:
            return super()._finish_shuffle(exchange, outputs, producer)
        # placeholder scan, filled in-place when the group decides: the
        # consumer stage only reads it after all its feeds materialized
        # (sequential: recursion order; DAG: dependency edges + the
        # synchronous decide below)
        scan = MemoryScanExec([], producer.schema())
        complete = None
        with self._group_lock:
            pend = self._group_pending.setdefault(gid, {})
            pend[exchange.stage_id] = (exchange, outputs, scan)
            if len(pend) == len(self._group_members[gid]):
                complete = self._group_pending.pop(gid)
        if complete is not None:
            # heavy work (hash regroup) deliberately OUTSIDE the lock
            self._decide_group(gid, complete)
        return scan

    def _decide_group(self, gid, pend) -> None:
        from datafusion_distributed_tpu.planner.statistics import (
            PlanStatistics,
            compute_based_task_count,
            row_width,
            stage_cost,
        )

        head = self._group_heads[gid]
        planned = min(ex.num_tasks for ex, _, _ in pend.values())
        total_bytes = 0
        rows_stats: dict = {}
        # deterministic iteration: under the DAG scheduler dict insertion
        # order is COMPLETION order, which varies run to run — the
        # decision's inputs are order-independent sums/mins, but the
        # regroup + decision log below must not be
        for sid in sorted(pend):
            (ex, outputs, _scan) = pend[sid]
            pred = self._predicted.get(sid)
            if pred is not None:
                rows, nbytes = pred.rows, pred.bytes
            else:
                width = row_width(outputs[0].schema()) if outputs else 8
                rows = sum(int(o.num_rows) for o in outputs)
                nbytes = rows * width
            total_bytes += nbytes
            head_info = self._stage_heads.get(sid)
            if head_info is not None:
                rows_stats[head_info[1]] = float(rows)
        if self.bytes_per_task > 0:
            t_bytes = max(1, -(-int(total_bytes) // self.bytes_per_task))
        else:
            t_bytes = planned
        cost = stage_cost(head, PlanStatistics(rows=rows_stats))
        t_cost = compute_based_task_count(
            cost, float(max(self.bytes_per_task, 1)), planned
        )
        t = min(planned, max(t_bytes, t_cost))
        for sid in sorted(pend):
            (ex, outputs, scan) = pend[sid]
            scan.tasks[:] = _shuffle_regroup(
                outputs, ex.key_names, t, ex.per_dest_capacity,
                zero_copy=self._zero_copy(),
            )
            self.task_count_decisions.append((sid, ex.num_tasks, t))

    def _prepare_stage_plan(self, stage_plan):
        """Resize stage capacities from runtime LoadInfo (exact or
        partial-sample-predicted) — applied by BOTH the bulk and streaming
        dispatch paths."""
        info = self._stage_input_info(stage_plan)
        if info is None:
            return stage_plan
        from datafusion_distributed_tpu.planner.adaptive import (
            resize_for_inputs,
        )

        return resize_for_inputs(stage_plan, info,
                                 skew_headroom=self.resize_headroom)

    def _stage_input_info(self, stage_plan):
        from datafusion_distributed_tpu.planner.adaptive import (
            LoadInfo,
            collect_load_info,
        )

        scans = [
            n for n in stage_plan.collect(lambda n: not n.children())
            if isinstance(n, MemoryScanExec)
        ]
        if not scans:
            return None
        merged: Optional[LoadInfo] = None
        for s in scans:
            info = self._load_info.get(s.node_id)
            if info is None:
                info = collect_load_info(s.tasks)
                self._load_info[s.node_id] = info
            if merged is None or info.rows > merged.rows:
                merged = info
        return merged


def _shuffle_consumer_groups(plan: ExecutionPlan) -> list:
    """-> [(consumer head node, [feeding ShuffleExchangeExec nodes])] for
    every stage of the ORIGINAL plan tree. A head fed by ONE shuffle can
    re-size that shuffle independently; a head fed by several (a co-shuffled
    join) must re-size them TOGETHER or `hash % t` co-partitioning breaks —
    the situation the reference solves by re-running boundary injection per
    stage at runtime (`prepare_dynamic_plan.rs:26-141`)."""

    def frontier(node) -> list:
        out = []
        for c in node.children():
            if getattr(c, "is_exchange", False):
                out.append(c)
            else:
                out.extend(frontier(c))
        return out

    groups = []
    heads = [plan] + [
        e.children()[0]
        for e in plan.collect(lambda n: getattr(n, "is_exchange", False))
    ]
    for head in heads:
        shuffles = [
            f for f in frontier(head)
            if isinstance(f, ShuffleExchangeExec)
            and not isinstance(f, RangeShuffleExchangeExec)
            and f.stage_id is not None
        ]
        if shuffles:
            groups.append((head, shuffles))
    return groups


def _find_solo_shuffles(plan: ExecutionPlan) -> set:
    """ids of ShuffleExchangeExec nodes whose consumer stage is fed by no
    OTHER shuffle (safe to re-size independently; keyed by stage_id —
    materialization rebuilds nodes, object identity does not survive
    with_new_children)."""
    return {
        s[0].stage_id
        for _, s in _shuffle_consumer_groups(plan)
        if len(s) == 1
    }


def _task_specialized(plan: ExecutionPlan, task_number: int) -> ExecutionPlan:
    """Ship only this task's leaf slice (the reference strips other tasks'
    DistributedLeaf variants before sending, `query_coordinator.rs:346-382`).

    Inside an IsolatedArmExec that IS assigned to this task, partitioned
    scans contribute ALL their slices, concatenated: the arm executes on
    exactly one task, so it is the sole consumer of any exchange output or
    base-table slice in its subtree — indexing those by the OUTER task
    number would silently drop every slice but this task's (observed as
    q5's catalog channel vanishing when its arm landed on task 1 and the
    arm's scans held a single slice 0). This is the reference's inner
    `DistributedTaskContext` remap for union children
    (`children_isolator_union.rs:84-100`)."""

    from datafusion_distributed_tpu.runtime.peer import PeerShuffleScanExec

    def walk(node: ExecutionPlan, in_arm: bool) -> ExecutionPlan:
        if isinstance(node, StreamScanExec):
            # pipelined-shuffle feed: resolve to THIS task's partition by
            # blocking until it closes (the pipelined wait point — the
            # feed keeps streaming the remaining partitions meanwhile).
            # Inside an arm the sole consumer takes every partition,
            # concatenated, mirroring the MemoryScan in-arm concat.
            if in_arm:
                slices = node.all_slices()
                chosen = (slices[0] if len(slices) == 1 else concat_tables(
                    slices, capacity=sum(s.capacity for s in slices)
                ))
            elif task_number < node.num_partitions:
                chosen = node.task_slice(task_number)
            else:
                chosen = Table.empty(node.schema(), 8, node.dictionaries)
            return MemoryScanExec([chosen], node.schema(), pinned=True)
        if isinstance(node, PeerShuffleScanExec):
            if node.pinned_task is not None or node.pull_all:
                return node  # already specialized
            if node.replicated:
                # broadcast: EVERY virtual partition is the producer's full
                # output — pull exactly ONE, in or out of an arm (pull_all
                # here would duplicate the build side num_partitions x);
                # modulo guards a consumer stage forced wider than the
                # broadcast's planned fan-out by a sibling feed
                return node.pinned_copy(
                    task_number % max(node.num_partitions, 1)
                )
            # in an arm: the sole consumer pulls EVERY partition (same
            # argument as the MemoryScan concat below)
            return node.pinned_copy(task_number, pull_all=in_arm)
        if isinstance(node, IsolatedArmExec):
            if node.assigned_task != task_number:
                # ChildrenIsolatorUnion semantics: this arm belongs to
                # another task; ship an empty scan instead of the subtree
                schema = node.schema()
                empty = Table.empty(schema, 8, None)
                return MemoryScanExec([empty], schema, pinned=True)
            return walk(node.child, True)
        if in_arm:
            from datafusion_distributed_tpu.plan.physical import (
                ParquetScanExec,
            )

            if isinstance(node, ParquetScanExec):
                # the arm's task reads EVERY file group (same sole-consumer
                # argument as the MemoryScan case below)
                flat = [f for g in node.file_groups for f in g]
                groups = [[] for _ in range(task_number)] + [flat]
                return ParquetScanExec(
                    groups, node.schema(),
                    node.capacity * max(len(node.file_groups), 1),
                    projection=node.projection,
                    dictionaries=node.dictionaries,
                )
        if isinstance(node, MemoryScanExec) and node.replicated:
            # every task reads the same merged table
            return MemoryScanExec([node.tasks[0]], node.schema(),
                                  pinned=True)
        if isinstance(node, MemoryScanExec) and not node.pinned:
            if in_arm:
                if len(node.tasks) == 1:
                    chosen = node.tasks[0]
                else:
                    chosen = concat_tables(
                        node.tasks,
                        capacity=sum(t.capacity for t in node.tasks),
                    )
            elif task_number < len(node.tasks):
                chosen = node.tasks[task_number]
            else:
                from datafusion_distributed_tpu.plan.physical import _dicts_of

                ref = node.tasks[0]
                chosen = Table.empty(
                    node.schema(), ref.capacity, _dicts_of(ref)
                )
            return MemoryScanExec([chosen], node.schema(), pinned=True)
        children = [walk(c, in_arm) for c in node.children()]
        return node.with_new_children(children) if children else node

    return walk(plan, False)


def _shuffle_regroup(
    outputs: Sequence[Table], key_names, num_tasks: int,
    per_dest_capacity: int, zero_copy: bool = True, exact: bool = False,
) -> list[Table]:
    """Host-side hash regroup between stages. Uses the SAME hash as the
    in-mesh kernel so a query may mix mesh-internal and cross-mesh shuffles
    and keys still co-locate.

    ``zero_copy`` (the view-based data plane, default on): each producer
    output is hash-bucketed with ONE stable destination-major gather into a
    single host buffer, and every per-destination slice is a zero-copy VIEW
    of it — instead of one eager device gather (and a full-capacity copy)
    per destination. ``exact`` skips the per-destination capacity padding
    (the peer partition plane, where slices only feed chunk streams);
    without it the returned slices keep the legacy
    ``len(outputs) * per_dest_capacity`` padded shape that consumer stage
    plans (and their compiled-program caches) key on.

    The copying fallback prefers the native (C++) data plane for the hash +
    CSR bucket build (native/), falling back to device ops."""
    if zero_copy:
        host = _shuffle_regroup_host(
            outputs, key_names, num_tasks, per_dest_capacity, exact
        )
        if host is not None:
            return host
    from datafusion_distributed_tpu import native

    buckets: list[list[Table]] = [[] for _ in range(num_tasks)]
    for out in outputs:
        cols = [out.column(k).data for k in key_names]
        valids = [out.column(k).validity for k in key_names]
        if native.available():
            np_cols = [np.asarray(c) for c in cols]
            np_valids = [
                np.asarray(v) if v is not None else None for v in valids
            ]
            dtypes = [out.column(k).dtype for k in key_names]
            h = native.hash_rows(np_cols, np_valids, dtypes)
            live = np.arange(out.capacity) < int(out.num_rows)
            offsets, indices, counts = native.shuffle_buckets(
                h, live, num_tasks
            )
            for j in range(num_tasks):
                rows = indices[offsets[j] : offsets[j + 1]]
                idx = jnp.zeros(out.capacity, dtype=jnp.int32)
                idx = idx.at[: len(rows)].set(jnp.asarray(rows, dtype=jnp.int32))
                buckets[j].append(out.gather(idx, len(rows)))
            continue
        h = hash_columns(cols, valids)
        dest = (h % np.uint32(num_tasks)).astype(jnp.int32)
        live = out.row_mask()
        for j in range(num_tasks):
            buckets[j].append(out.compact(live & (dest == j)))
    slices = []
    # each of the len(outputs) producers contributes <= per_dest_capacity
    # rows to a destination (task counts may differ per stage)
    cap = max(len(outputs), 1) * per_dest_capacity
    for j in range(num_tasks):
        slices.append(concat_tables(buckets[j], capacity=cap))
    return slices


def _shuffle_regroup_host(
    outputs: Sequence[Table], key_names, num_tasks: int,
    per_dest_capacity: int, exact: bool,
) -> Optional[list[Table]]:
    """View-based regroup: per producer output, hash the keys (same native/
    device hash as the copying path), stable-sort row indices by
    destination, gather ONCE per column into a destination-major host
    buffer, and hand out per-destination row-range views of it. Row order
    within each destination matches the copying path exactly (stable sort
    == original order within a bucket), so results stay byte-identical.
    Returns None when an output is traced (concat under trace) — the
    copying path handles that."""
    import jax

    from datafusion_distributed_tpu import native
    from datafusion_distributed_tpu.ops.table import (
        Column,
        host_view,
        slice_view,
    )

    for out in outputs:
        if isinstance(out.num_rows, jax.core.Tracer):
            return None
    buckets: list[list[Table]] = [[] for _ in range(num_tasks)]
    for out in outputs:
        host = host_view(out)
        n = int(host.num_rows)
        np_cols = [np.asarray(host.column(k).data) for k in key_names]
        np_valids = [
            np.asarray(v) if (v := host.column(k).validity) is not None
            else None
            for k in key_names
        ]
        if native.available():
            dtypes = [host.column(k).dtype for k in key_names]
            h = np.asarray(native.hash_rows(np_cols, np_valids, dtypes))
        else:
            h = np.asarray(hash_columns(np_cols, np_valids))
        dest = (h[:n] % np.uint32(num_tasks)).astype(np.int64)
        order = np.argsort(dest, kind="stable")
        counts = np.bincount(dest, minlength=num_tasks)
        starts = np.concatenate(([0], np.cumsum(counts)))
        # ONE destination-major gather per column; every per-destination
        # slice below is a view of this buffer
        gathered = Table(
            host.names,
            tuple(
                Column(
                    np.asarray(c.data[:n])[order],
                    np.asarray(c.validity[:n])[order]
                    if c.validity is not None else None,
                    c.dtype, c.dictionary,
                )
                for c in host.columns
            ),
            np.int32(n),
        )
        for j in range(num_tasks):
            buckets[j].append(
                slice_view(gathered, int(starts[j]), int(counts[j]))
            )
    cap = max(len(outputs), 1) * per_dest_capacity
    slices = []
    for j in range(num_tasks):
        if exact and len(buckets[j]) == 1:
            slices.append(buckets[j][0])
            continue
        rows = sum(int(b.num_rows) for b in buckets[j])
        slices.append(concat_tables(
            buckets[j],
            capacity=(max(rows, 1) if exact else cap),
        ))
    return slices


def _range_regroup(outputs: Sequence[Table], sort_keys,
                   num_tasks: int) -> list[Table]:
    """Exact host-side range partition: concat, sort once, contiguous
    slices. Slice i's rows all order before slice i+1's, so consumers'
    local sorts + an order-preserving coalesce reproduce the global
    order (mesh-tier contract of RangeShuffleExchangeExec)."""
    from datafusion_distributed_tpu.ops.sort import sort_table

    total = concat_tables(
        outputs, capacity=sum(o.capacity for o in outputs)
    )
    s = sort_table(total, sort_keys)
    n = int(s.num_rows)
    per = -(-max(n, 1) // num_tasks)
    slices = []
    for i in range(num_tasks):
        count = max(min(per, n - i * per), 0)
        if count > 0:
            slices.append(s.slice_rows(i * per, count))
        else:
            from datafusion_distributed_tpu.plan.physical import _dicts_of

            slices.append(Table.empty(s.schema(), 8, _dicts_of(s)))
    return slices


def _leaf_dictionaries(plan: ExecutionPlan, schema) -> Optional[dict]:
    """Best-effort dictionaries for an empty result table: string columns in
    `schema` keep the codes minted at the leaves, so a zero-row fallback must
    carry the same dictionaries a real (bulk) result would — dictionary-
    dependent consumers (literal code lookups) break on a bare None."""
    from datafusion_distributed_tpu.plan.physical import ParquetScanExec

    from datafusion_distributed_tpu.runtime.peer import PeerShuffleScanExec

    out: dict = {}
    names = {f.name for f in schema.fields}
    for leaf in plan.collect(lambda n: not n.children()):
        dicts: dict = {}
        if isinstance(leaf, ParquetScanExec) and leaf.dictionaries:
            dicts = leaf.dictionaries
        elif isinstance(leaf, PeerShuffleScanExec) and leaf.dictionaries:
            dicts = leaf.dictionaries
        elif isinstance(leaf, StreamScanExec) and leaf.dictionaries:
            dicts = leaf.dictionaries
        elif isinstance(leaf, MemoryScanExec) and leaf.tasks:
            ref = leaf.tasks[0]
            dicts = {
                n: c.dictionary
                for n, c in zip(ref.names, ref.columns)
                if c.dictionary is not None
            }
        for name, d in dicts.items():
            if name in names and d is not None:
                out.setdefault(name, d)
    return out or None


def _mod_slices(table: Table, num_tasks: int) -> list[Table]:
    idx = jnp.arange(table.capacity, dtype=jnp.int32)
    live = table.row_mask()
    return [
        table.compact(live & ((idx % num_tasks) == i)) for i in range(num_tasks)
    ]
