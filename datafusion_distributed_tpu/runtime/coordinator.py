"""Coordinator: stage-wise distributed execution across workers.

The reference's `DistributedExec`/`QueryCoordinator` assign worker URLs per
task, ship task-specialized plans over a coordinator channel, then stream
results through the exchange network (`/root/reference/src/coordinator/`,
SURVEY.md §3.2). This is the host-runtime tier of the TPU design:

  in-mesh   -> runtime/mesh_executor.py (one SPMD program, collectives)
  cross-mesh/host -> THIS: each stage's tasks run on workers; the coordinator
  materializes stage outputs and performs the exchange semantics between
  stages (the DCN hop).

Stages execute bottom-up: every exchange boundary's producer subtree is
shipped to workers task-by-task (round-robin routing, the reference's
routed_urls default), executed, and the exchange (shuffle regroup /
broadcast / coalesce) is applied to the collected outputs; the boundary then
becomes an in-memory scan for the consumer stage — the Pending->Ready flip
of `Stage::Local -> Stage::Remote`.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu.ops.hash import hash_columns
from datafusion_distributed_tpu.ops.table import Table, concat_tables, round_up_pow2
from datafusion_distributed_tpu.plan.exchanges import (
    BroadcastExchangeExec,
    CoalesceExchangeExec,
    PartitionReplicatedExec,
    ShuffleExchangeExec,
)
from datafusion_distributed_tpu.plan.physical import (
    DistributedTaskContext,
    ExecutionPlan,
    MemoryScanExec,
)
from datafusion_distributed_tpu.runtime.codec import TableStore, encode_plan
from datafusion_distributed_tpu.runtime.worker import TaskKey, Worker


class WorkerResolver:
    """Cluster membership (the reference's WorkerResolver: get_urls)."""

    def get_urls(self) -> list[str]:
        raise NotImplementedError


class ChannelResolver:
    """URL -> worker channel (the reference's ChannelResolver)."""

    def get_worker(self, url: str) -> Worker:
        raise NotImplementedError


class InMemoryCluster(WorkerResolver, ChannelResolver):
    """N in-process workers (the InMemoryChannelResolver fake cluster used by
    the reference's whole TPC suite, `src/test_utils/`)."""

    def __init__(self, num_workers: int, ttl_seconds: float = 600.0):
        self.workers = {
            f"mem://worker-{i}": Worker(f"mem://worker-{i}", ttl_seconds)
            for i in range(num_workers)
        }

    def get_urls(self) -> list[str]:
        return list(self.workers.keys())

    def get_worker(self, url: str) -> Worker:
        return self.workers[url]


@dataclass
class Coordinator:
    resolver: WorkerResolver
    channels: ChannelResolver
    route_tasks: Optional[Callable] = None  # custom routing hook
    collect_metrics: bool = True
    metrics: dict = field(default_factory=dict)  # TaskKey -> worker metrics

    def execute(self, plan: ExecutionPlan) -> Table:
        """Run a distributed plan (exchange-staged) across the workers and
        return the (replicated) root result."""
        query_id = uuid.uuid4().hex
        resolved = self._materialize_exchanges(plan, query_id)
        # the root stage: a single consumer task
        out = self._run_stage_task(
            resolved, query_id, stage_id=-1, task_number=0, task_count=1
        )
        return out

    # -- stage materialization ----------------------------------------------
    def _materialize_exchanges(
        self, plan: ExecutionPlan, query_id: str
    ) -> ExecutionPlan:
        children = [
            self._materialize_exchanges(c, query_id) for c in plan.children()
        ]
        if children:
            plan = plan.with_new_children(children)
        if not getattr(plan, "is_exchange", False):
            return plan

        t = plan.num_tasks
        producer = plan.children()[0]
        stage_id = plan.stage_id if plan.stage_id is not None else 0
        if isinstance(plan, PartitionReplicatedExec):
            # producer is replicated: one task's output carries everything
            outputs = [
                self._run_stage_task(producer, query_id, stage_id, 0, t)
            ]
        else:
            outputs = [
                self._run_stage_task(producer, query_id, stage_id, i, t)
                for i in range(t)
            ]
        if isinstance(plan, ShuffleExchangeExec):
            slices = _shuffle_regroup(
                outputs, plan.key_names, t, plan.per_dest_capacity
            )
        elif isinstance(plan, (CoalesceExchangeExec, BroadcastExchangeExec)):
            cap = sum(o.capacity for o in outputs)
            merged = concat_tables(outputs, capacity=cap)
            slices = [merged] * t
        elif isinstance(plan, PartitionReplicatedExec):
            # producer is replicated: each consumer keeps its modulo slice of
            # task 0's output
            slices = _mod_slices(outputs[0], t)
        else:
            raise NotImplementedError(type(plan).__name__)
        return MemoryScanExec(slices, producer.schema())

    # -- task execution ------------------------------------------------------
    def _run_stage_task(
        self,
        stage_plan: ExecutionPlan,
        query_id: str,
        stage_id: int,
        task_number: int,
        task_count: int,
    ) -> Table:
        urls = self.resolver.get_urls()
        if self.route_tasks is not None:
            url = self.route_tasks(query_id, stage_id, task_number, urls)
        else:
            url = urls[(stage_id + task_number) % len(urls)]  # round-robin
        worker = self.channels.get_worker(url)
        key = TaskKey(query_id, stage_id, task_number)
        store = worker.table_store
        plan_obj = encode_plan(
            _task_specialized(stage_plan, task_number), store
        )
        worker.set_plan(key, plan_obj, task_count)
        try:
            out = worker.execute_task(key)
            if self.collect_metrics:
                self.metrics[key] = worker.task_progress(key) or {}
        finally:
            # drop-driven cleanup: the task's cache entry AND its shipped
            # table slices are released as soon as its single partition is
            # consumed (reference: on_drop_stream + invalidate,
            # `impl_execute_task.rs:97-112`)
            worker.registry.invalidate(key)
            from datafusion_distributed_tpu.runtime.codec import (
                collect_table_ids,
            )

            store.remove(collect_table_ids(plan_obj))
        return out


class AdaptiveCoordinator(Coordinator):
    """Dynamic-planning coordinator (the reference's `dynamic_task_count`
    mode): consumer stages are re-sized from the EXACT LoadInfo of their
    materialized inputs before execution — planning and execution interleave
    (`prepare_dynamic_plan.rs`), with real statistics instead of samples."""

    def __post_init_adaptive(self):
        pass

    def execute(self, plan: ExecutionPlan) -> Table:
        self._load_info: dict[int, object] = {}
        return super().execute(plan)

    def _materialize_exchanges(self, plan, query_id):
        resolved = super()._materialize_exchanges(plan, query_id)
        return resolved

    def _run_stage_task(self, stage_plan, query_id, stage_id, task_number,
                        task_count):
        info = self._stage_input_info(stage_plan)
        if info is not None:
            from datafusion_distributed_tpu.planner.adaptive import (
                resize_for_inputs,
            )

            stage_plan = resize_for_inputs(stage_plan, info)
        out = super()._run_stage_task(
            stage_plan, query_id, stage_id, task_number, task_count
        )
        return out

    def _stage_input_info(self, stage_plan):
        from datafusion_distributed_tpu.planner.adaptive import (
            LoadInfo,
            collect_load_info,
        )

        scans = [
            n for n in stage_plan.collect(lambda n: not n.children())
            if isinstance(n, MemoryScanExec)
        ]
        if not scans:
            return None
        merged: Optional[LoadInfo] = None
        for s in scans:
            info = self._load_info.get(s.node_id)
            if info is None:
                info = collect_load_info(s.tasks)
                self._load_info[s.node_id] = info
            if merged is None or info.rows > merged.rows:
                merged = info
        return merged


def _task_specialized(plan: ExecutionPlan, task_number: int) -> ExecutionPlan:
    """Ship only this task's leaf slice (the reference strips other tasks'
    DistributedLeaf variants before sending, `query_coordinator.rs:346-382`).
    The worker indexes its slice with task_index 0...task-local addressing is
    preserved because MemoryScanExec.load clamps by list length."""

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        if isinstance(node, MemoryScanExec) and not node.pinned:
            if task_number < len(node.tasks):
                chosen = node.tasks[task_number]
            else:
                from datafusion_distributed_tpu.plan.physical import _dicts_of

                ref = node.tasks[0]
                chosen = Table.empty(
                    node.schema(), ref.capacity, _dicts_of(ref)
                )
            return MemoryScanExec([chosen], node.schema(), pinned=True)
        children = [walk(c) for c in node.children()]
        return node.with_new_children(children) if children else node

    return walk(plan)


def _shuffle_regroup(
    outputs: Sequence[Table], key_names, num_tasks: int, per_dest_capacity: int
) -> list[Table]:
    """Host-side hash regroup between stages. Uses the SAME hash as the
    in-mesh kernel so a query may mix mesh-internal and cross-mesh shuffles
    and keys still co-locate. Prefers the native (C++) data plane for the
    hash + CSR bucket build (native/), falling back to device ops."""
    from datafusion_distributed_tpu import native

    buckets: list[list[Table]] = [[] for _ in range(num_tasks)]
    for out in outputs:
        cols = [out.column(k).data for k in key_names]
        valids = [out.column(k).validity for k in key_names]
        if native.available():
            np_cols = [np.asarray(c) for c in cols]
            np_valids = [
                np.asarray(v) if v is not None else None for v in valids
            ]
            dtypes = [out.column(k).dtype for k in key_names]
            h = native.hash_rows(np_cols, np_valids, dtypes)
            live = np.arange(out.capacity) < int(out.num_rows)
            offsets, indices, counts = native.shuffle_buckets(
                h, live, num_tasks
            )
            for j in range(num_tasks):
                rows = indices[offsets[j] : offsets[j + 1]]
                idx = jnp.zeros(out.capacity, dtype=jnp.int32)
                idx = idx.at[: len(rows)].set(jnp.asarray(rows, dtype=jnp.int32))
                buckets[j].append(out.gather(idx, len(rows)))
            continue
        h = hash_columns(cols, valids)
        dest = (h % np.uint32(num_tasks)).astype(jnp.int32)
        live = out.row_mask()
        for j in range(num_tasks):
            buckets[j].append(out.compact(live & (dest == j)))
    slices = []
    cap = num_tasks * per_dest_capacity
    for j in range(num_tasks):
        slices.append(concat_tables(buckets[j], capacity=cap))
    return slices


def _mod_slices(table: Table, num_tasks: int) -> list[Table]:
    idx = jnp.arange(table.capacity, dtype=jnp.int32)
    live = table.row_mask()
    return [
        table.compact(live & ((idx % num_tasks) == i)) for i in range(num_tasks)
    ]
