"""Multi-query serving tier: async frontend, global cross-query stage
scheduler, admission control.

The engine below this module executes ONE query at a time per session:
PR 4's stage-DAG scheduler overlaps stages *within* a query and PR 5 made
membership elastic, but nothing arbitrated *between* queries sharing the
worker pool and TableStore. This is the concurrency tier the reference
repo's `cli/` + `console/` serving layers sit on (SURVEY §5), shaped by
the fair-share scheduling argument of *Chasing Similarity* (PAPERS.md):
one heavy analytical query must not starve a stream of cheap ones.

Three cooperating pieces:

`ServingSession`
    The async frontend. ``submit(sql, priority=0) -> QueryHandle`` lets N
    clients run concurrently against one shared cluster + TableStore;
    each admitted query gets its OWN per-query `Coordinator` (isolating
    the cancel-event, retry state, and peer bookkeeping that live on the
    coordinator object) wired to SHARED health/fault/metrics/latency
    stores — a worker quarantined by one query stays routed-around for
    the next, and one MetricsStore holds every query's stage spans under
    its LRU + running-query pin.

`GlobalStageScheduler`
    The per-query stage-DAG scheduler generalized to the whole tier: ONE
    bounded slot pool executes ready stages from ALL admitted queries.
    Each per-query coordinator keeps its own DAG bookkeeping (dependency
    release order is a per-query concern) and submits ready stages here
    through its ``stage_pool`` hook; the policy decides which query's
    stage gets the next free slot. Fair share is STRIDE scheduling keyed
    on per-query accumulated stage wall-clock: every finished stage
    charges its measured wall to its query's pass value, and the pending
    stage belonging to the query with the LOWEST pass runs next — so a
    heavy q21 accumulates pass and cheap q1/q6 stages overtake it at
    every slot boundary. Selection is a pure function of (priority, pass
    values, seeded tie-break, arrival order): given a seed and identical
    completion timings the interleaving replays, and results are
    byte-identical under ANY interleaving by the stage-DAG scheduler's
    own contract. ``fair_share=False`` degrades to FIFO (arrival order),
    the comparison arm of the serving bench.

Admission control
    Keyed on the existing `plan_device_bytes` footprint estimate
    (planner/statistics.py — the same arithmetic the overflow-retry
    budget guard uses): a query whose estimate would push the sum of
    running-query footprints past ``admission_budget_bytes``, or that
    would exceed ``max_concurrent_queries``, QUEUES (FIFO within its
    priority class, higher class first) instead of OOMing the pool.
    Both knobs are live `SET distributed.*` options.

Prepared statements (`SessionContext.prepare`, sql/context.py) ride this
tier: `Prepared.submit(session, params)` binds parameter values into the
template and the PR 2 literal-hoisting + fingerprint machinery serves
every variant from one compiled program — zero new compiles on the
serving path (pinned by the recompile-budget gate's serving extension).
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
import time
import uuid
import zlib
from typing import Optional

from datafusion_distributed_tpu.runtime.errors import TaskCancelledError
from datafusion_distributed_tpu.runtime.metrics import (
    FaultCounters,
    LatencySketch,
    MetricsStore,
)

# -- handle states -----------------------------------------------------------
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"
#: shed under memory pressure (red-line load shedding): resolved with a
#: typed QueryPreemptedError, checkpoint frontier RETAINED so recover()
#: resumes the query when pressure clears
PREEMPTED = "preempted"

#: serving knob defaults, settable per session via `SET distributed.<knob>`
#: (validated at SET time, sql/context.py). The ADMISSION knobs
#: (max_concurrent_queries, admission_budget_bytes) are read LIVE at each
#: admission decision, so a SET mid-serving applies to the next
#: submit/admit; the SCHEDULER knobs (fair_share, serving_stage_slots)
#: bind when the ServingSession is constructed — the slot pool and its
#: policy are fixed for the session's lifetime.
#: admission_budget_bytes 0 = unlimited.
SERVING_DEFAULTS = {
    "max_concurrent_queries": 8,
    "admission_budget_bytes": 16e9,
    "fair_share": True,
    "serving_stage_slots": 0,  # 0 = auto: the live worker count
    #: query checkpoint/resume (runtime/checkpoint.py): admitted queries
    #: snapshot completed-stage outputs onto the workers so a fresh
    #: session's `recover()` resumes them from the staged frontier
    "checkpointing": False,
    #: SLO targets (runtime/telemetry.py SloTracker), read LIVE per
    #: stats()/snapshot: rolling p99 latency target in milliseconds and
    #: error-rate budget over the SLO window. None = no target declared
    #: (the tracker still reports the rolling p99/error rate).
    "slo_p99_ms": None,
    "slo_error_rate": None,
    #: red-line load shedding (with the enforced worker memory budget,
    #: `SET distributed.worker_memory_budget_bytes`): a worker whose
    #: RESIDENT staged bytes stay over budget x this factor — i.e. spill
    #: already failed to relieve it — triggers preemption of the
    #: lowest-priority running query (typed QueryPreemptedError, its
    #: checkpoint frontier retained for recover()). 0 disables shedding.
    "worker_memory_redline": 1.25,
}


class QueryHandle:
    """One submitted query's async surface: ``result()`` blocks for the
    pyarrow table (re-raising the query's error), ``cancel()`` stops a
    queued or running query, ``status()`` reports the lifecycle state.
    Timing fields (`submitted_s`, `admitted_s`, `finished_s`, monotonic)
    expose queue wait and run wall for the serving bench."""

    def __init__(self, session: "ServingSession", sql: str, df,
                 priority: int, est_bytes: int):
        self.query_id = uuid.uuid4().hex  # collision-free under any
        # concurrency: uuid4 per handle, never a shared counter
        self.sql = sql
        self.priority = int(priority)
        self.est_bytes = int(est_bytes)
        self.submitted_s = time.monotonic()
        self.admitted_s: Optional[float] = None
        self.finished_s: Optional[float] = None
        self._session = session
        self._df = df
        self._state = QUEUED
        self._result = None  # raw ops Table
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        # pre-installed into the per-query coordinator (its execute()
        # reuses it), so cancel() reaches in-flight dispatches directly
        self._cancel_event = threading.Event()
        self._coordinator = None
        # checkpoint-store record id (runtime/checkpoint.py) when the
        # session checkpoints; pre-set by recover() for resumed queries
        self._ckpt_record: Optional[str] = None
        # red-line load shedding (the session's memory monitor): set
        # BEFORE the cancel event fires so _drive classifies the
        # resulting TaskCancelledError as preemption, not a user cancel
        self._preempted = False
        # served from the result cache (runtime/result_cache.py): such
        # a query reserved NO admission budget and its ~0-byte "peak"
        # must never pollute the measured-bytes re-cost history
        self._cache_hit = False
        # measured peak staged bytes (TableStore attribution summed
        # across workers), harvested when the query resolves — the
        # measured side of the est_bytes admission loop
        self.peak_staged_bytes = 0
        # the coordinator-internal query id of the MAIN execute (stamped
        # by the driver) — the key into the distributed-tracing store,
        # isolating this handle's trace from every concurrent query's
        self.trace_query_id: Optional[str] = None

    # -- inspection ---------------------------------------------------------
    def status(self, detail: bool = False):
        """Lifecycle state string; ``detail=True`` returns a dict adding
        the admission estimate, the MEASURED per-query peak staged bytes
        (populated once the query resolves; the serving tier re-costs
        later admissions of the same SQL from it), and the preemption
        flag."""
        if not detail:
            return self._state
        return {
            "state": self._state,
            "priority": self.priority,
            "est_bytes": self.est_bytes,
            "peak_staged_bytes": self.peak_staged_bytes,
            "preempted": self._preempted,
            "queue_wait_s": self.queue_wait_s(),
            "wall_s": self.wall_s(),
        }

    def done(self) -> bool:
        return self._done.is_set()

    def queue_wait_s(self) -> Optional[float]:
        if self.admitted_s is None:
            return None
        return self.admitted_s - self.submitted_s

    def wall_s(self) -> Optional[float]:
        """Admission -> completion wall (the latency the serving bench
        reports); None while unresolved or never admitted."""
        if self.finished_s is None or self.admitted_s is None:
            return None
        return self.finished_s - self.admitted_s

    # -- results ------------------------------------------------------------
    def result_table(self, timeout: Optional[float] = None):
        """Raw device Table (qualified column names preserved)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.query_id[:8]} unresolved after {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._result

    def result(self, timeout: Optional[float] = None):
        """-> pyarrow Table with user-facing column names (the DataFrame
        .collect() convention)."""
        from datafusion_distributed_tpu.io.parquet import table_to_arrow
        from datafusion_distributed_tpu.sql.context import DataFrame

        return table_to_arrow(
            DataFrame._strip_quals(self.result_table(timeout))
        )

    def cancel(self) -> bool:
        """Request cancellation; -> whether the request landed on an
        unresolved query. A QUEUED query is removed from the admission
        queue immediately; a RUNNING one aborts at its coordinator's next
        dispatch/execute checkpoint (the per-query cancel event)."""
        return self._session._cancel(self)

    # -- distributed tracing -------------------------------------------------
    def query_trace(self):
        """This query's QueryTrace (None unless it ran with
        `SET distributed.tracing` on/sampled)."""
        from datafusion_distributed_tpu.runtime.tracing import (
            DEFAULT_TRACE_STORE,
        )

        if self.trace_query_id is None:
            return None
        return DEFAULT_TRACE_STORE.get(self.trace_query_id)

    def trace(self):
        """Chrome trace-event JSON dict of this query's distributed trace
        (load in Perfetto / chrome://tracing), or None if untraced."""
        from datafusion_distributed_tpu.runtime.tracing import (
            to_chrome_trace,
        )

        t = self.query_trace()
        return to_chrome_trace(t) if t is not None else None

    def trace_profile(self) -> str:
        """Text profile report of this query's trace ('' if untraced)."""
        from datafusion_distributed_tpu.runtime.tracing import (
            render_profile,
        )

        t = self.query_trace()
        return render_profile(t) if t is not None else ""

    # -- session-internal transitions ---------------------------------------
    def _finish(self, state: str, result=None,
                error: Optional[BaseException] = None) -> None:
        self._state = state
        self._result = result
        self._error = error
        self.finished_s = time.monotonic()
        self._coordinator = None  # shed per-query coordinator state
        self._df = None
        self._done.set()


class _StageJob:
    """One pending stage awaiting a global slot."""

    __slots__ = ("qid", "fn", "future", "seq", "cost_hint")

    def __init__(self, qid: str, fn, seq: int, cost_hint: int):
        self.qid = qid
        self.fn = fn
        self.future: cf.Future = cf.Future()
        self.seq = seq
        self.cost_hint = int(cost_hint)


class _QueryPool:
    """Per-query facade installed as `Coordinator.stage_pool`: tags every
    submitted stage with its query id so the global scheduler can apply
    the cross-query policy."""

    __slots__ = ("_sched", "_qid")

    def __init__(self, scheduler: "GlobalStageScheduler", qid: str):
        self._sched = scheduler
        self._qid = qid

    def submit(self, fn, cost_hint: int = 0) -> cf.Future:
        return self._sched.submit(self._qid, fn, cost_hint=cost_hint)


class GlobalStageScheduler:
    """Bounded slot pool executing ready stages from every admitted query
    under a fair-share (stride) or FIFO policy. See the module docstring
    for the policy; mechanically:

    - `submit(qid, fn)` enqueues a job and returns a standard
      `concurrent.futures.Future` (the coordinator's DAG loop `cf.wait`s
      on it unchanged).
    - N worker threads each loop: pick the best pending job, run it,
      charge its measured wall to its query's pass value.
    - pick order: highest priority class first; within a class the lowest
      EFFECTIVE pass — the accumulated pass plus a provisional charge of
      (in-flight stages x the query's mean stage wall). Charging only on
      completion would let a many-stage query flood every slot at pass 0
      before its first charge lands; the provisional term makes holding
      slots itself costly, so a cheap query's stage overtakes at the next
      slot boundary even while the heavy query's stages are still
      running. Ties break on (seeded registration-order hash, smaller
      stage cost hint, arrival seq) — total and deterministic given the
      seed (registration order, not uuids, feeds the hash, so a replayed
      workload replays its schedule).
    - a newly registered query starts at the MINIMUM pass of the live
      queries (the standard stride-scheduling join rule: a newcomer
      neither monopolizes the pool nor inherits an unpayable debt).
    """

    def __init__(self, slots: int, fair_share: bool = True, seed: int = 0):
        self.slots = max(int(slots), 1)
        self.fair_share = bool(fair_share)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: list[_StageJob] = []  # guarded-by: _lock
        self._pass: dict[str, float] = {}  # guarded-by: _lock; per-query: swept-by unregister_query
        self._prio: dict[str, int] = {}  # guarded-by: _lock; per-query: swept-by unregister_query
        self._weight: dict[str, float] = {}  # guarded-by: _lock; per-query: swept-by unregister_query
        self._qseq: dict[str, int] = {}  # guarded-by: _lock; per-query: swept-by unregister_query
        self._qseq_next = 0  # guarded-by: _lock
        #: per-query in-flight stage count + mean stage wall (EMA): the
        #: provisional-charge inputs
        self._running_stages: dict[str, int] = {}  # guarded-by: _lock; per-query: swept-by unregister_query
        self._mean_wall: dict[str, float] = {}  # guarded-by: _lock; per-query: swept-by unregister_query
        #: qids registered implicitly by submit() (direct coordinator
        #: use, no ServingSession driving unregister): reaped when their
        #: last job drains, so a long-lived scheduler does not grow
        #: per-query state for every ad-hoc query it ever served
        self._adhoc: set = set()  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        #: pick order, for tests/introspection: (qid, job seq) per slot
        #: grant, appended under the lock
        self.schedule_log: list[tuple] = []  # guarded-by: _lock
        self._in_flight = 0  # guarded-by: _lock
        self.peak_in_flight = 0  # guarded-by: _lock
        self._threads = [
            threading.Thread(target=self._loop, daemon=True,
                             name=f"dftpu-serve-{i}")
            for i in range(self.slots)
        ]
        for t in self._threads:
            t.start()

    # -- query registration -------------------------------------------------
    def register_query(self, qid: str, priority: int = 0,
                       weight: float = 1.0) -> None:
        with self._lock:
            live = [
                self._pass[q] for q in self._pass
                if self._prio.get(q) == priority
            ]
            self._pass.setdefault(qid, min(live) if live else 0.0)
            self._prio[qid] = int(priority)
            self._weight[qid] = max(float(weight), 1e-9)
            if qid not in self._qseq:
                self._qseq[qid] = self._qseq_next
                self._qseq_next += 1

    def unregister_query(self, qid: str) -> None:
        with self._lock:
            self._unregister_locked(qid)

    def _unregister_locked(self, qid: str) -> None:
        self._pass.pop(qid, None)
        self._prio.pop(qid, None)
        self._weight.pop(qid, None)
        self._qseq.pop(qid, None)
        self._running_stages.pop(qid, None)
        self._mean_wall.pop(qid, None)
        self._adhoc.discard(qid)

    # -- job surface --------------------------------------------------------
    def submit(self, qid: str, fn, cost_hint: int = 0) -> cf.Future:
        with self._cv:
            if self._closed:
                raise RuntimeError("serving scheduler is closed")
            if qid not in self._pass:
                # unregistered submitter (direct coordinator use): admit
                # ad hoc at the current minimum pass
                live = list(self._pass.values())
                self._pass[qid] = min(live) if live else 0.0
                self._prio.setdefault(qid, 0)
                self._weight.setdefault(qid, 1.0)
                self._adhoc.add(qid)
                if qid not in self._qseq:
                    self._qseq[qid] = self._qseq_next
                    self._qseq_next += 1
            job = _StageJob(qid, fn, self._seq, cost_hint)
            self._seq += 1
            self._pending.append(job)
            self._cv.notify()
            return job.future

    def _tie(self, qid: str) -> int:
        # seeded deterministic tie-break between equal-pass queries:
        # hashes the REGISTRATION order, not the uuid, so a replayed
        # workload (same arrival order, same seed) replays its schedule
        return zlib.crc32(
            f"{self.seed}:{self._qseq.get(qid, -1)}".encode()
        )

    def _effective_pass(self, qid: str) -> float:
        """Accumulated pass plus the provisional charge for stages this
        query is running RIGHT NOW (in-flight count x its mean stage
        wall): holding slots costs pass immediately, not at completion."""
        base = self._pass.get(qid, 0.0)
        running = self._running_stages.get(qid, 0)
        if not running:
            return base
        est = self._mean_wall.get(qid, 0.0) or 1e-3
        return base + running * est / self._weight.get(qid, 1.0)

    def _pick_locked(self) -> Optional[_StageJob]:
        if not self._pending:
            return None
        if self.fair_share:
            best = min(
                self._pending,
                key=lambda j: (
                    -self._prio.get(j.qid, 0),
                    self._effective_pass(j.qid),
                    self._tie(j.qid),
                    j.cost_hint,
                    j.seq,
                ),
            )
        else:  # FIFO: priority classes still order, arrival decides
            best = min(
                self._pending,
                key=lambda j: (-self._prio.get(j.qid, 0), j.seq),
            )
        self._pending.remove(best)
        return best

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if self._closed and not self._pending:
                    return
                job = self._pick_locked()
                if job is None:
                    continue
                if not job.future.set_running_or_notify_cancel():
                    continue  # cancelled while pending
                self.schedule_log.append((job.qid, job.seq))
                self._in_flight += 1
                self.peak_in_flight = max(
                    self.peak_in_flight, self._in_flight
                )
                self._running_stages[job.qid] = (
                    self._running_stages.get(job.qid, 0) + 1
                )
            t0 = time.monotonic()
            try:
                out = job.fn()
            except BaseException as e:
                job.future.set_exception(e)
            else:
                job.future.set_result(out)
            wall = time.monotonic() - t0
            with self._lock:
                self._in_flight -= 1
                left = self._running_stages.get(job.qid, 1) - 1
                if left > 0:
                    self._running_stages[job.qid] = left
                else:
                    self._running_stages.pop(job.qid, None)
                if job.qid in self._pass:
                    self._pass[job.qid] += wall / self._weight.get(
                        job.qid, 1.0
                    )
                    prev = self._mean_wall.get(job.qid)
                    self._mean_wall[job.qid] = (
                        wall if prev is None else 0.5 * prev + 0.5 * wall
                    )
                if (
                    job.qid in self._adhoc
                    and job.qid not in self._running_stages
                    and not any(j.qid == job.qid for j in self._pending)
                ):
                    # last job of an implicitly-registered query drained:
                    # reap its state (explicit registrations are owned by
                    # their ServingSession's unregister)
                    self._unregister_locked(job.qid)

    # -- lifecycle / introspection ------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "slots": self.slots,
                "policy": "fair_share" if self.fair_share else "fifo",
                "pending_stages": len(self._pending),
                "in_flight_stages": self._in_flight,
                "peak_in_flight": self.peak_in_flight,
                "query_pass": dict(self._pass),
            }

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)


def run_closed_loop(session: "ServingSession", client_workloads,
                    classify=None, timeout: float = 600.0) -> dict:
    """Drive N closed-loop clients against ``session``: client ``i``
    submits each SQL in ``client_workloads[i]`` in order, waiting for
    each result before the next (the serving bench harness, shared by
    `bench.py --serving` and `benchmarks/micro_bench.py`).

    ``classify(client_index) -> label`` buckets the per-query walls
    (submit -> resolve, queue wait included — the client-visible
    latency); default: one "all" bucket. A failing client records its
    error and stops; partial walls stay reportable.

    -> {"wall_s", "queries", "walls": {label: [seconds...]},
        "errors": [str...]}
    """
    classify = classify or (lambda ci: "all")
    walls: dict = {}
    errors: list = []
    lock = threading.Lock()
    t0 = time.monotonic()

    def client(ci: int) -> None:
        label = classify(ci)
        try:
            for sql in client_workloads[ci]:
                h = session.submit(sql)
                h.result(timeout=timeout)
                with lock:
                    walls.setdefault(label, []).append(
                        h.finished_s - h.submitted_s
                    )
        except BaseException as e:  # keep partial results reportable
            with lock:
                errors.append(f"client{ci}: {type(e).__name__}: {e}")

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(len(client_workloads))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return {
        "wall_s": time.monotonic() - t0,
        "queries": sum(len(v) for v in walls.values()),
        "walls": walls,
        "errors": errors,
    }


def percentile_ms(walls, q: float):
    """q-th percentile of a wall-seconds list, in ms (None if empty)."""
    if not walls:
        return None
    v = sorted(walls)
    return round(v[min(int(q * len(v)), len(v) - 1)] * 1e3, 1)


class ServingSession:
    """N concurrent clients over one SessionContext + one worker cluster.

    ::

        ctx = SessionContext(); register tables...
        with ServingSession(ctx, num_workers=4) as srv:
            h1 = srv.submit("select ...")
            h2 = srv.submit("select ...", priority=1)
            t1, t2 = h1.result(), h2.result()

    ``cluster`` may be any resolver+channels pair (InMemoryCluster,
    DynamicCluster, a chaos-wrapped cluster, GrpcCluster); by default an
    InMemoryCluster of ``num_workers`` spins up. Admission / policy knobs
    come from `SET distributed.*` (SERVING_DEFAULTS) with constructor
    overrides; ``seed`` makes scheduler tie-breaks reproducible.
    """

    def __init__(self, ctx, cluster=None, num_workers: int = 4,
                 num_tasks: int = 4,
                 max_concurrent_queries: Optional[int] = None,
                 admission_budget_bytes: Optional[float] = None,
                 fair_share: Optional[bool] = None,
                 stage_slots: Optional[int] = None,
                 checkpoints=None,
                 checkpointing: Optional[bool] = None,
                 seed: int = 0):
        from datafusion_distributed_tpu.runtime.coordinator import (
            InMemoryCluster,
        )
        from datafusion_distributed_tpu.runtime.health import (
            HealthPolicy,
            HealthTracker,
        )
        from datafusion_distributed_tpu.runtime.metrics import HedgeBudget

        self.ctx = ctx
        self.cluster = cluster if cluster is not None else InMemoryCluster(
            num_workers
        )
        self.num_tasks = int(num_tasks)
        self._overrides = {
            "max_concurrent_queries": max_concurrent_queries,
            "admission_budget_bytes": admission_budget_bytes,
            "fair_share": fair_share,
            "serving_stage_slots": stage_slots,
            "checkpointing": checkpointing,
        }
        # query checkpoint/resume (runtime/checkpoint.py): a passed
        # ``checkpoints`` store enables it implicitly — pass the SAME
        # store to a fresh session and `recover()` resumes whatever this
        # one leaves unresolved (the store outlives the session on
        # purpose: that IS the coordinator-loss recovery path)
        if checkpoints is None and bool(self._opt_over("checkpointing")):
            from datafusion_distributed_tpu.runtime.checkpoint import (
                CheckpointStore,
            )

            try:
                ckpt_cap = int(float(
                    self._opt("checkpoint_budget_bytes", 0) or 0
                ))
            except (TypeError, ValueError):
                ckpt_cap = 0
            checkpoints = CheckpointStore(budget_bytes=ckpt_cap)
        self.checkpoints = checkpoints
        # one cluster-wide speculative-attempt budget shared by every
        # per-query coordinator (the hedge stampede bound)
        self.hedge_budget = HedgeBudget()
        # shared across every per-query coordinator: quarantine/fault/
        # latency/span state outlives any single query
        self.health = HealthTracker(HealthPolicy(
            failure_threshold=int(self._opt("quarantine_threshold", 3)),
            quarantine_seconds=float(self._opt("quarantine_seconds", 30.0)),
        ))
        self.faults = FaultCounters()
        self.stage_metrics = MetricsStore()
        self.task_latency = LatencySketch()
        #: per-QUERY wall latency (admission -> completion): the p50/p99
        #: surface of the serving bench
        self.query_latency = LatencySketch()
        slots = int(self._opt_over("serving_stage_slots"))
        if slots <= 0:
            try:
                slots = max(len(self.cluster.get_urls()), 1)
            except Exception:
                slots = 4
        self.scheduler = GlobalStageScheduler(
            slots,
            fair_share=bool(self._opt_over("fair_share")),
            seed=seed,
        )
        self._lock = threading.Lock()
        # arrival order preserved
        self._queued: list[QueryHandle] = []  # guarded-by: _lock
        self._running: dict[str, QueryHandle] = {}  # guarded-by: _lock; per-query: swept-by _drive
        self._drivers: dict[str, threading.Thread] = {}  # guarded-by: _lock; per-query: swept-by _drive
        self._admitted_total = 0  # guarded-by: _lock
        self._completed = {DONE: 0, FAILED: 0, CANCELLED: 0,
                           PREEMPTED: 0}  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        # estimate-vs-reality admission loop: SQL text -> last MEASURED
        # peak staged bytes (TableStore attribution); queued admission
        # decisions re-cost from it, replacing the static
        # plan_device_bytes estimate once a real run measured the query
        self._measured_bytes: dict = {}  # guarded-by: _lock
        # cluster-wide telemetry (runtime/telemetry.py): ONE typed
        # registry is the exposition sink for every counter this tier
        # already keeps — faults, hedge budget, breaker state, latency
        # sketches, admission/queue state, SLO attainment, event-log
        # tallies — sampled via collector adapters at snapshot time.
        # `ObservabilityService(serving=...).get_metrics()` merges it
        # with the per-worker `get_metrics` RPC snapshots.
        from datafusion_distributed_tpu.runtime.eventlog import (
            default_event_log,
        )
        from datafusion_distributed_tpu.runtime.telemetry import (
            MetricRegistry,
            SloTracker,
            TelemetryHistory,
        )

        self.slo = SloTracker()
        self.telemetry = MetricRegistry()
        for collector in (
            self.faults.telemetry_families,
            self.hedge_budget.telemetry_families,
            self.health.telemetry_families,
            self._serving_families,
            self._slo_families,
            self._result_cache_families,
            default_event_log().telemetry_families,
            lambda: self.query_latency.telemetry_families(
                "dftpu_query_latency_seconds",
                "Per-query admission->completion wall (seconds).",
            ),
            lambda: self.task_latency.telemetry_families(
                "dftpu_task_latency_seconds",
                "Per-task execute wall (seconds).",
            ),
        ):
            self.telemetry.register_collector(collector)
        # bounded time-series ring over the registry: `_drive` samples
        # it as queries resolve (the resolution gate inside the history
        # keeps the grid uniform) and the console renders sparkline
        # columns from it
        self.history = TelemetryHistory(
            capacity=int(self._opt("telemetry_history_points", 240)),
            resolution_s=float(self._opt("telemetry_resolution_s", 1.0)),
        )
        # red-line memory monitor (load shedding): a daemon sampler over
        # the in-process workers' TableStores. Cheap when no store has a
        # budget set (a handful of int reads per tick); preempts the
        # lowest-priority running query when residency stays over
        # budget x `worker_memory_redline` AFTER spilling already ran.
        self._monitor_stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._memory_monitor, daemon=True,
            name="dftpu-mem-monitor",
        )
        self._monitor.start()

    # -- telemetry adapters (runtime/telemetry.py) --------------------------
    def _serving_families(self) -> list:
        """Admission/queue/completion state as typed families."""
        from datafusion_distributed_tpu.runtime.telemetry import family

        with self._lock:
            active = len(self._running)
            queued = len(self._queued)
            admitted = self._admitted_total
            completed = dict(self._completed)
            in_use = sum(r.est_bytes for r in self._running.values())
            queued_bytes = sum(q.est_bytes for q in self._queued)
        return [
            family("dftpu_serving_active_queries", "gauge",
                   "Admitted queries currently executing.",
                   [({}, active)]),
            family("dftpu_serving_queued_queries", "gauge",
                   "Queries waiting for admission.", [({}, queued)]),
            family("dftpu_serving_admitted", "counter",
                   "Queries ever admitted.", [({}, admitted)]),
            family("dftpu_serving_queries", "counter",
                   "Resolved queries by terminal state.",
                   [({"state": k}, v)
                    for k, v in sorted(completed.items())]),
            family("dftpu_serving_in_use_bytes", "gauge",
                   "Admission-estimate bytes of running queries.",
                   [({}, in_use)]),
            family("dftpu_serving_queued_bytes", "gauge",
                   "Admission-estimate bytes of queued queries.",
                   [({}, queued_bytes)]),
            family("dftpu_queries_preempted", "counter",
                   "Queries preempted by red-line load shedding "
                   "(checkpoint frontier retained for recover()).",
                   [({}, completed.get(PREEMPTED, 0))]),
        ]

    def _result_cache_families(self) -> list:
        """`dftpu_result_cache_*` families when the session context has
        ever created a cache (knob-on), eagerly zero-valued from its
        first snapshot; empty while the tier is off."""
        rc = getattr(self.ctx, "_result_cache", None)
        if rc is None:
            return []
        try:
            return rc.telemetry_families()
        except Exception:
            return []

    def _slo_families(self) -> list:
        return self.slo.telemetry_families(
            p99_target_ms=self._opt("slo_p99_ms", None),
            error_rate_target=self._opt("slo_error_rate", None),
        )

    def slo_snapshot(self) -> dict:
        """Rolling SLO attainment against the live `SET distributed.
        slo_p99_ms` / `slo_error_rate` targets (runtime/telemetry.py
        SloTracker) — also folded into `stats()["slo"]`."""
        return self.slo.snapshot(
            p99_target_ms=self._opt("slo_p99_ms", None),
            error_rate_target=self._opt("slo_error_rate", None),
        )

    # -- option plumbing ----------------------------------------------------
    def _opt(self, name: str, default):
        try:
            return self.ctx.config.distributed_options.get(name, default)
        except Exception:
            return default

    def _opt_over(self, name: str):
        """Constructor override > live `SET distributed.*` > default."""
        v = self._overrides.get(name)
        if v is not None:
            return v
        return self._opt(name, SERVING_DEFAULTS[name])

    def _max_concurrent(self) -> int:
        try:
            return max(int(self._opt_over("max_concurrent_queries")), 1)
        except (TypeError, ValueError):
            return int(SERVING_DEFAULTS["max_concurrent_queries"])

    def _budget_bytes(self) -> float:
        try:
            return float(self._opt_over("admission_budget_bytes"))
        except (TypeError, ValueError):
            return float(SERVING_DEFAULTS["admission_budget_bytes"])

    # -- submission ---------------------------------------------------------
    def submit(self, sql: str, priority: int = 0,
               _resume: Optional[str] = None) -> QueryHandle:
        """Parse, plan, and estimate the query NOW (client thread; the
        session plan cache makes repeats cheap), then admit or queue it.
        ``priority``: higher class admits and schedules first; FIFO
        within a class. ``_resume``: internal (recover()) — an existing
        checkpoint-store record id this submission resumes instead of
        registering a fresh one."""
        from datafusion_distributed_tpu.planner.statistics import (
            plan_device_bytes,
        )

        if self._closed:
            raise RuntimeError("serving session is closed")
        df = self.ctx.sql(sql)
        if df is None or not hasattr(df, "collect_coordinated_table"):
            raise ValueError(
                "serving submit requires a SELECT statement "
                "(DDL/SET-only scripts have no result to serve)"
            )
        # result-cache fast path (runtime/result_cache.py): consult the
        # whole-result cache BEFORE costing — a hit resolves on the
        # client thread with est_bytes=0, reserving NO admission budget
        # and no queue slot for execution it will skip (the bursty-
        # serving fast path; resumed queries always re-execute)
        if _resume is None:
            hit = self._cache_fast_path(sql, df, priority)
            if hit is not None:
                return hit
        # the admission footprint: the single-node physical plan's
        # device-buffer bound — the same plan_device_bytes estimate the
        # overflow-retry budget guard keys on (sql/context.py). Planning
        # here is cached by the session plan cache, so a repeated
        # template estimates for free.
        try:
            est = int(plan_device_bytes(df.physical_plan()))
        except Exception:
            est = 0  # unplannable estimate -> admit on count alone
        handle = QueryHandle(self, sql, df, priority, est)
        handle._ckpt_record = _resume
        with self._lock:
            if self._closed:
                # re-checked under the lock: a close() racing the
                # planning above must not strand a handle on a queue
                # nobody will ever admit from
                raise RuntimeError("serving session is closed")
            self._queued.append(handle)
            self._admit_locked()
        return handle

    def _result_cache(self):
        """The session context's ResultCache (None when the knob is
        off, or when the context predates the surface)."""
        try:
            return self.ctx.result_cache()
        except AttributeError:
            return None

    def _cache_fast_path(self, sql: str, df, priority: int):
        """A resolved QueryHandle served by reference from the
        whole-result cache, or None (cache off / miss / unkeyable).
        The handle never touches admission: it is admitted+done in one
        step, charged zero budget, and excluded from re-cost history."""
        rc = self._result_cache()
        if rc is None:
            return None
        try:
            key = df._result_cache_key(self.num_tasks)
        except Exception:
            key = None
        if key is None:
            return None
        cached = rc.lookup(key)
        if cached is None:
            return None
        h = QueryHandle(self, sql, df, priority, 0)
        h._cache_hit = True
        h.admitted_s = time.monotonic()
        with self._lock:
            if self._closed:
                raise RuntimeError("serving session is closed")
            self._admitted_total += 1
            self._completed[DONE] = self._completed.get(DONE, 0) + 1
        h._finish(DONE, result=cached)
        wall = h.wall_s()
        if wall is not None:
            self.query_latency.record(wall)
            self.slo.record(wall, ok=True)
        from datafusion_distributed_tpu.runtime.eventlog import log_event

        log_event("query_admitted", serving_query_id=h.query_id,
                  priority=h.priority, est_bytes=0, cache_hit=True,
                  queue_wait_s=0.0)
        log_event("query_done", serving_query_id=h.query_id,
                  cache_hit=True, priority=h.priority,
                  wall_s=round(wall, 6) if wall is not None else None)
        self.history.sample(self.telemetry)
        return h

    # -- admission control --------------------------------------------------
    def _recost_locked(self, h: QueryHandle) -> int:
        """Re-cost a queued admission decision from MEASURED reality:
        once a prior run of the same SQL measured its peak staged bytes
        (TableStore attribution), that replaces the static
        plan_device_bytes estimate — mis-estimated queries stop
        over/under-admitting on their second appearance."""
        measured = self._measured_bytes.get(h.sql)
        if measured is not None and measured > 0 and (
            measured != h.est_bytes
        ):
            h.est_bytes = int(measured)
        return h.est_bytes

    def _admissible_locked(self, h: QueryHandle) -> bool:
        if len(self._running) >= self._max_concurrent():
            return False
        if self._running and self._redline_hot():
            # a worker is over the hard red-line with queries running:
            # queue instead of piling more demand onto a pressured pool
            # (the monitor sheds if pressure persists)
            return False
        budget = self._budget_bytes()
        if budget and budget > 0:
            est = self._recost_locked(h)
            in_use = sum(r.est_bytes for r in self._running.values())
            if in_use + est > budget and self._running:
                # over budget with peers running -> wait; an EMPTY pool
                # always admits the head (a query bigger than the whole
                # budget must not starve forever)
                return False
        return True

    def _admit_locked(self) -> None:
        """Admit queued queries while capacity allows: highest priority
        class first, FIFO within the class, and STRICT head-of-class
        order — a large query at the head blocks its class until it fits
        (documented admission semantics: no small-query bypass, so
        arrival order within a class is also completion-start order).
        Runs even after close(): a closed session stops ACCEPTING
        queries, but what was already queued still admits and resolves
        (close(cancel_pending=True) cancels the backlog instead)."""
        while self._queued:
            # max() returns the FIRST maximal element, so this is exactly
            # head-of-highest-class with FIFO preserved within the class
            head = max(self._queued, key=lambda h: h.priority)
            if not self._admissible_locked(head):
                return
            self._queued.remove(head)
            self._start_locked(head)

    def _start_locked(self, h: QueryHandle) -> None:
        h._state = RUNNING
        h.admitted_s = time.monotonic()
        if self.checkpoints is not None and h._ckpt_record is None:
            # register the admitted query in the checkpoint store NOW:
            # from this point a coordinator/session loss leaves a
            # recoverable record behind
            h._ckpt_record = self.checkpoints.admit(h.sql, h.priority)
        self._admitted_total += 1
        self._running[h.query_id] = h
        self.scheduler.register_query(h.query_id, priority=h.priority)
        t = threading.Thread(
            target=self._drive, args=(h,), daemon=True,
            name=f"dftpu-query-{h.query_id[:8]}",
        )
        self._drivers[h.query_id] = t
        t.start()

    # -- per-query driver ---------------------------------------------------
    def _make_coordinator(self, h: QueryHandle):
        """Fresh per-query coordinator over the SHARED cluster: isolates
        every per-query attribute Coordinator.execute hangs on `self`
        (cancel event, peer-ship registry, span caches, retry state)
        while sharing the cross-query stores."""
        from datafusion_distributed_tpu.runtime.coordinator import (
            Coordinator,
        )

        sweeps = getattr(getattr(self.cluster, "plan", None),
                         "sweep_query", None)

        def on_query_end(query_id: str) -> None:
            # per-execute sweep (subquery executes included): chaos call
            # counters and the per-task/stream metric dicts for this
            # internal query id are shed the moment it resolves
            if callable(sweeps):
                sweeps(query_id)
            coord.sweep_query(query_id)

        checkpointer = None
        if self.checkpoints is not None and h._ckpt_record is not None:
            from datafusion_distributed_tpu.runtime.checkpoint import (
                QueryCheckpointer,
            )

            checkpointer = QueryCheckpointer(
                self.checkpoints, h._ckpt_record,
                resolver=self.cluster, channels=self.cluster,
            )
        coord = Coordinator(
            resolver=self.cluster, channels=self.cluster,
            # GIL-atomic snapshot: a live `SET distributed.*` from a
            # client thread must not explode this copy mid-iteration
            config_options=self.ctx.config.distributed_snapshot(),
            passthrough_headers=dict(self.ctx.config.passthrough_headers),
            health=self.health,
            faults=self.faults,
            stage_metrics=self.stage_metrics,
            latency=self.task_latency,
            stage_pool=_QueryPool(self.scheduler, h.query_id),
            cancel_event=h._cancel_event,
            on_query_end=on_query_end,
            hedges=self.hedge_budget,
            checkpoints=checkpointer,
            result_cache=self._result_cache(),
        )
        return coord

    def _drive(self, h: QueryHandle) -> None:
        from datafusion_distributed_tpu.runtime.eventlog import log_event

        wait = h.queue_wait_s()
        log_event("query_admitted", serving_query_id=h.query_id,
                  priority=h.priority, est_bytes=h.est_bytes,
                  queue_wait_s=round(wait, 6) if wait is not None
                  else None)
        coord = None
        try:
            if h._cancel_event.is_set():
                raise TaskCancelledError("cancelled before execution")
            coord = h._coordinator = self._make_coordinator(h)
            out = h._df.collect_coordinated_table(
                coordinator=coord, num_tasks=self.num_tasks
            )
            if getattr(coord, "last_query_id", None) is None:
                # the coordinator never executed: the result cache
                # served this query while it sat in the queue (or a
                # concurrent identical submission's single-flight fill)
                h._cache_hit = True
            h._finish(DONE, result=out)
        except TaskCancelledError as e:
            if h._preempted:
                # red-line load shedding rode the cancel path: surface
                # the TYPED error and keep the checkpoint frontier —
                # recover() resumes this query when pressure clears
                from datafusion_distributed_tpu.runtime.errors import (
                    QueryPreemptedError,
                )

                h._finish(PREEMPTED, error=QueryPreemptedError(
                    f"query {h.query_id[:8]} preempted by memory "
                    "red-line load shedding; its checkpoint frontier "
                    "is retained — ServingSession.recover() resumes it"
                ))
            else:
                h._finish(CANCELLED, error=e)
        except BaseException as e:
            h._finish(FAILED, error=e)
        finally:
            # measured side of the admission loop: the coordinator's
            # sweep harvested per-store staging attribution into
            # staged_peak_bytes; bind it to the handle and (for resolved
            # runs) re-cost future admissions of this SQL from it
            peak = int(getattr(coord, "staged_peak_bytes", 0) or 0)
            h.peak_staged_bytes = peak
            # cache-served completions never update the measured-bytes
            # history: their ~0-byte "peak" would poison the re-cost
            # loop into under-admitting the next COLD run of this SQL
            if h._state == DONE and peak > 0 and not h._cache_hit:
                with self._lock:
                    self._measured_bytes[h.sql] = peak
                    while len(self._measured_bytes) > 256:
                        self._measured_bytes.pop(
                            next(iter(self._measured_bytes))
                        )
            if self.checkpoints is not None and h._ckpt_record is not None:
                if h._state in (DONE, CANCELLED):
                    # resolved: the record and its staged slices are
                    # dead weight (and would leak) — release them.
                    # FAILED stays recoverable — and PREEMPTED stays
                    # recoverable ON PURPOSE: the retained completed-
                    # stage frontier is what recover() resumes from
                    # after load shedding.
                    self.checkpoints.release(h._ckpt_record, self.cluster)
            self._stamp_trace(h, coord)
            self.scheduler.unregister_query(h.query_id)
            wall = h.wall_s()
            if wall is not None and h._state == DONE:
                self.query_latency.record(wall)
            # SLO window (runtime/telemetry.py): DONE counts against the
            # latency target, FAILED burns error budget; CANCELLED is
            # operator-initiated and charges neither
            if h._state == DONE:
                self.slo.record(wall, ok=True)
            elif h._state == FAILED:
                self.slo.record(wall, ok=False)
            log_event(
                f"query_{h._state}", serving_query_id=h.query_id,
                query_id=getattr(coord, "last_query_id", None),
                wall_s=round(wall, 6) if wall is not None else None,
                priority=h.priority, cache_hit=h._cache_hit,
            )
            with self._lock:
                self._running.pop(h.query_id, None)
                self._drivers.pop(h.query_id, None)
                self._completed[h._state] = (
                    self._completed.get(h._state, 0) + 1
                )
                self._admit_locked()
            # time-series point per resolved query (the history's own
            # resolution gate bounds the grid; a quiet tier simply has
            # no new points, matching a scrape-on-change model)
            lat = self.query_latency.summary()
            self.history.sample(self.telemetry, extra={
                "p99_ms": (lat["p99"] * 1e3
                           if lat.get("p99") is not None else None),
            })

    def _stamp_trace(self, h: QueryHandle, coord) -> None:
        """Bind the handle to its MAIN execute's trace (the last query id
        the coordinator ran — subquery executes resolved earlier) and
        annotate the trace root with the serving tier's admission
        queue-wait, so the profile shows the full submit->result story."""
        qid = getattr(coord, "last_query_id", None)
        if qid is None:
            return
        h.trace_query_id = qid
        wait = h.queue_wait_s()
        if wait is not None:
            from datafusion_distributed_tpu.runtime.tracing import (
                DEFAULT_TRACE_STORE,
            )

            DEFAULT_TRACE_STORE.annotate(
                qid, admission_wait_s=round(wait, 6),
                serving_query_id=h.query_id, priority=h.priority,
            )

    # -- query recovery (runtime/checkpoint.py) ------------------------------
    def recover(self, store=None, cluster=None) -> list:
        """Resume every admitted-but-unresolved query recorded in
        ``store`` (default: this session's checkpoint store) — the
        fresh-coordinator half of checkpoint/resume. Each record's SQL
        resubmits through normal admission at its original priority; the
        new query's coordinator restores completed stages from the
        checkpointed frontier (fingerprint-validated against the
        re-planned query) and re-executes only what is missing or
        invalid, falling back to full re-execution when nothing
        restores. ``cluster`` is accepted for call-site symmetry with
        the docs but must be the session's own cluster (the staged
        slices live on its workers). -> the new QueryHandles, in record
        order."""
        if store is not None and store is not self.checkpoints:
            own = self.checkpoints
            if own is not None and own.stats()["queries"]:
                # the session's own store already tracks queries: silently
                # switching would orphan their records
                raise ValueError(
                    "recover(store=...) on a session whose own checkpoint "
                    "store already tracks queries"
                )
            # adopt (an auto-created empty store — e.g. from
            # `SET distributed.checkpointing` — is simply replaced):
            # resumed queries re-save into the recovered store
            self.checkpoints = store
        store = self.checkpoints
        if store is None:
            return []
        if cluster is not None and cluster is not self.cluster:
            raise ValueError(
                "recover() must run against the cluster holding the "
                "checkpointed slices (the session's own cluster)"
            )
        handles = []
        for rec in store.incomplete():
            store.mark_resumed(rec.record_id)
            self.faults.bump("queries_recovered")
            handles.append(
                self.submit(rec.sql, priority=rec.priority,
                            _resume=rec.record_id)
            )
        return handles

    # -- memory red-line monitor / load shedding -----------------------------
    def _redline_factor(self) -> float:
        try:
            return float(self._opt_over("worker_memory_redline"))
        except (TypeError, ValueError):
            return float(SERVING_DEFAULTS["worker_memory_redline"])

    def _worker_stores(self) -> list:
        """The in-process workers' TableStores (wire workers report via
        their own budget enforcement; the monitor cannot see them)."""
        stores = []
        try:
            urls = self.cluster.get_urls()
        except Exception:
            return stores
        for url in urls:
            try:
                s = getattr(self.cluster.get_worker(url), "table_store",
                            None)
            except Exception:
                continue
            if s is not None and hasattr(s, "under_pressure"):
                stores.append((url, s))
        return stores

    def _redline_hot(self) -> bool:
        """Any worker's RESIDENT staged bytes over budget x red-line
        (spill already failed to relieve it)? Plain int reads only —
        this runs on the 50 ms monitor tick and under the admission
        lock, so it must never walk a store's full stats()."""
        factor = self._redline_factor()
        if factor <= 0:
            return False
        for _url, s in self._worker_stores():
            b = getattr(s, "budget_bytes", 0)
            if b and s.nbytes() > b * factor:
                return True
        return False

    def _memory_monitor(self) -> None:
        """Daemon sampler: while any worker store sits over the hard
        red-line, shed load — preempt the LOWEST-PRIORITY running query
        (largest measured staged bytes within the class) through the
        existing cancel path, typed as QueryPreemptedError with its
        checkpoint frontier retained. One preemption in flight at a
        time: the next only fires if pressure persists after the victim
        resolved (natural hysteresis)."""
        while not self._monitor_stop.wait(0.05):
            try:
                self._check_redline()
            except Exception:
                pass  # the monitor must never die mid-session

    @staticmethod
    def _current_staged(h: QueryHandle, stores) -> int:
        """Bytes currently attributed to ``h``'s main execute across the
        worker stores (the over-budget tie-break among equal-priority
        shed candidates)."""
        qid = getattr(h._coordinator, "last_query_id", None)
        if not qid:
            return 0
        total = 0
        for _url, s in stores:
            try:
                total += s.query_current_nbytes(qid)
            except Exception:
                pass
        return total

    def _check_redline(self) -> None:
        factor = self._redline_factor()
        if factor <= 0:
            return
        hot = []
        stores = self._worker_stores()
        for url, s in stores:
            # budget_bytes is a plain attribute and nbytes() a two-line
            # locked int read: the 20 Hz tick must not contend the
            # store lock with a stats() walk over every staged entry
            b = getattr(s, "budget_bytes", 0)
            if b:
                n = s.nbytes()
                if n > b * factor:
                    hot.append((url, n, b))
        if not hot:
            return
        with self._lock:
            running = list(self._running.values())
            if any(h._preempted for h in running):
                return  # a shed is already unwinding: wait for it
            candidates = [h for h in running if not h.done()]
            if not candidates:
                return
            victim = min(candidates, key=lambda h: (
                h.priority,
                -self._current_staged(h, stores),
                -(h.admitted_s or 0.0),
            ))
            victim._preempted = True
        self.faults.bump("queries_preempted")
        from datafusion_distributed_tpu.runtime.eventlog import log_event

        log_event(
            "query_preempt_requested",
            serving_query_id=victim.query_id, priority=victim.priority,
            hot_workers=[u for u, _n, _b in hot],
            resident_bytes=max(n for _u, n, _b in hot),
            budget_bytes=max(b for _u, _n, b in hot),
        )
        # the existing cancel path does the unwinding (slice release,
        # coordinator teardown); _drive types the result as PREEMPTED
        victim._cancel_event.set()

    # -- cancellation -------------------------------------------------------
    def _cancel(self, h: QueryHandle) -> bool:
        with self._lock:
            if h in self._queued:
                self._queued.remove(h)
                h._finish(CANCELLED, error=TaskCancelledError(
                    "cancelled while queued"
                ))
                self._completed[CANCELLED] += 1
                self._admit_locked()
                queued_cancel = True
            else:
                queued_cancel = False
        if queued_cancel:
            if self.checkpoints is not None and h._ckpt_record is not None:
                # a RESUMED query cancelled while still queued: its
                # record (and staged frontier) is explicitly abandoned
                self.checkpoints.release(h._ckpt_record, self.cluster)
            return True
        if h.done():
            return False
        # running (or racing admission): the pre-installed cancel event
        # reaches the coordinator's dispatch/execute checkpoints
        h._cancel_event.set()
        return True

    # -- observability ------------------------------------------------------
    def stats(self) -> dict:
        """The console/observability surface: active/queued/admitted
        counts, footprint accounting, scheduler state, latency summary."""
        with self._lock:
            running = list(self._running.values())
            queued = list(self._queued)
            out = {
                "active": len(running),
                "queued": len(queued),
                "admitted_total": self._admitted_total,
                "completed": dict(self._completed),
                "in_use_bytes": sum(r.est_bytes for r in running),
                "queued_bytes": sum(q.est_bytes for q in queued),
                "budget_bytes": self._budget_bytes(),
                "max_concurrent_queries": self._max_concurrent(),
            }
        out["scheduler"] = self.scheduler.stats()
        out["latency"] = self.query_latency.summary()
        out["hedging"] = self.hedge_budget.stats()
        # enforced-memory surface: per-worker residency vs budget plus
        # spill counters (in-process stores only) and the red-line factor
        out["memory"] = {
            "redline_factor": self._redline_factor(),
            "measured_queries": len(self._measured_bytes),
            "workers": {
                url: {
                    k: v for k, v in s.stats().items()
                    if k in ("nbytes", "peak_nbytes", "budget_bytes",
                             "spilled_nbytes", "spills", "refaults",
                             "spill_files")
                }
                for url, s in self._worker_stores()
            },
        }
        # rolling SLO attainment vs the live targets (empty targets
        # still report the window's p99/error rate)
        out["slo"] = self.slo_snapshot()
        if self.checkpoints is not None:
            out["checkpoints"] = self.checkpoints.stats()
        rc = getattr(self.ctx, "_result_cache", None)
        if rc is not None:
            out["result_cache"] = rc.stats()
        return out

    # -- lifecycle ----------------------------------------------------------
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted query resolved; -> drained."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        while True:
            with self._lock:
                busy = bool(self._running) or bool(self._queued)
            if not busy:
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.005)

    def close(self, cancel_pending: bool = False,
              timeout: float = 30.0) -> None:
        """Stop ACCEPTING queries and shut down. By default the backlog
        still resolves — already-queued queries admit and run during the
        drain (graceful); ``cancel_pending=True`` cancels them instead.
        Either way every handle resolves — no stranded result() waiters."""
        with self._lock:
            self._closed = True
            queued = list(self._queued) if cancel_pending else []
        for h in queued:
            self._cancel(h)
        if not self.drain(timeout=timeout):
            # the graceful window expired with queries still in flight:
            # cancel them so their handles resolve CANCELLED — closing
            # the scheduler under them would fail their next stage
            # submission with a scheduler-internal error instead
            with self._lock:
                stuck = list(self._running.values()) + list(self._queued)
            for h in stuck:
                self._cancel(h)
            self.drain(timeout=10.0)
        self._monitor_stop.set()
        self.scheduler.close()

    def __enter__(self) -> "ServingSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close(cancel_pending=True)
