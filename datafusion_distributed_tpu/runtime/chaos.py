"""Deterministic fault injection for the host-runtime tier.

A seeded `FaultPlan` wraps a cluster's workers (`wrap_cluster` /
`ChaosWorker`) and injects faults at the coordinator-visible call sites:

  set_plan   crash-on-ship (dispatch failures); kind="corrupt_plan"
             mutates the encoded plan in transit — the worker's
             post-decode fingerprint check (plan/verify.py DFTPU043 via
             runtime/worker.py) must convert it into the classified fatal
             PlanIntegrityError instead of wrong results
  execute    crash-mid-execute / transient transport errors / slow-worker
             delays, applied uniformly to execute_task,
             execute_task_stream, execute_task_partitions and
             transfer_partitions; kind="segment_lost" (transfer-only)
             tears the next shm segment mid-stream, asserting the pull
             degrades to the wire path instead of failing the query

Membership churn (`MembershipEvent`): seeded `leave`/`join`/`drain`
events scheduled by site/stage/task like the fault kinds above, applied
to the wrapped cluster's dynamic-membership surface
(runtime/coordinator.py `DynamicCluster`) when the triggering call
arrives — a departed worker's endpoint then fails retryably, exercising
the coordinator's live re-routing and peer-producer re-ship paths.

PER-CALL decisions are DETERMINISTIC and thread-order independent: each
(site, stage, task, nth-call) tuple hashes with the seed to a unit float
compared against the spec's rate, so an uncapped schedule replays
identically under the same seed regardless of how the stage fan-out's
threads interleave. Per-stage / total caps (`max_per_stage`, `max_total`)
bound how many faults fire — `FaultSpec(site="execute", rate=1.0,
max_per_stage=1)` is the canonical "one worker crash per stage" schedule
of tests/test_fault_tolerance.py. Caveat: a cap slot is consumed in call
ARRIVAL order, so capped schedules keep their fire COUNT deterministic at
rate=1.0 but may attribute a fault to a different (task, worker) across
runs when sibling tasks race for the slot; assertions on a capped
schedule should target results/counters, not which task was hit (the
suite's determinism test uses uncapped specs for exactly this reason).

This mirrors what Zerrow (arXiv:2504.06151) treats as part of pipeline
correctness: failure paths — including buffer cleanup after a failed
attempt — are exercised on purpose, not discovered in production.
"""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from datafusion_distributed_tpu.runtime.errors import (
    TransportError,
    WorkerError,
    WorkerUnavailableError,
)

#: injection sites a FaultSpec may name
SITES = ("set_plan", "execute")


@dataclass
class FaultSpec:
    """One fault family: where, what, how often, and bounds."""

    site: str  # "set_plan" | "execute"
    #: "crash" | "transport" | "delay" | "corrupt_plan" | "straggler" |
    #: "oom". "delay" rolls per CALL (uniform injected latency);
    #: "straggler" is WORKER-PINNED: one seeded decision per (query, url)
    #: makes that worker sticky-slow for the REST of the query at every
    #: matching call — the real tail-latency pathology (one slow machine,
    #: not a uniformly slow cluster) the hedger exists to beat. Caps
    #: count straggler WORKERS elected, not delayed calls. "oom"
    #: COLLAPSES the target worker's enforced memory budget mid-query
    #: (TableStore.set_budget to ``budget_bytes``, or half its current
    #: resident bytes when unset) and delegates the call: the spill/
    #: backpressure/shedding machinery must absorb it — results stay
    #: byte-identical, zero leaked slices, zero leaked spill files.
    #: "skew" is a WORKLOAD-shaping fault, not an error: the matching
    #: task's bulk output has ``skew_fraction`` of its ``skew_column``
    #: values overwritten with the column's row-0 value, concentrating
    #: a hot key so the downstream hash shuffle lands one hot partition
    #: (the input the skew-aware splitter in runtime/adaptivity.py
    #: corrects for). Seeded and query-scoped like every other kind —
    #: replaying the same schedule reshapes the same tasks — but it
    #: CHANGES DATA by design, so A/B comparisons must run BOTH arms
    #: under the same skew schedule. Bulk execute_task only: the
    #: streaming/partition planes pass through untouched.
    kind: str = "crash"
    rate: float = 1.0  # per-call probability (seed-hashed, deterministic)
    delay_s: float = 0.0  # for kind="delay"/"straggler": injected latency
    #: for kind="oom": the collapsed budget (None = half the worker's
    #: resident staged bytes at injection time, minimum 1)
    budget_bytes: Optional[int] = None
    #: for kind="skew": the shuffle-key column to concentrate — None
    #: targets the task output's FIRST column (the planner emits the
    #: group/shuffle key first, under internal names like ``__g0`` that
    #: a spec cannot know); a NAMED column that is absent makes the fire
    #: a no-op. ``skew_fraction`` of the task's rows are overwritten
    #: with the row-0 hot value.
    skew_column: Optional[str] = None
    skew_fraction: float = 0.8
    #: restrict to these worker urls (substring match); None = any worker
    workers: Optional[Sequence[str]] = None
    #: restrict to these stage ids; None = any stage
    stages: Optional[Sequence[int]] = None
    #: restrict to these task numbers; None = any task
    tasks: Optional[Sequence[int]] = None
    #: at most this many fires per stage (None = unbounded)
    max_per_stage: Optional[int] = None
    #: at most this many fires total (None = unbounded)
    max_total: Optional[int] = None

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r} (expected one of {SITES})"
            )

    def _matches(self, site: str, url: str, stage_id: int,
                 task_number: int) -> bool:
        if site != self.site:
            return False
        if self.workers is not None and not any(
            w in url for w in self.workers
        ):
            return False
        if self.stages is not None and stage_id not in self.stages:
            return False
        if self.tasks is not None and task_number not in self.tasks:
            return False
        return True


#: membership actions a MembershipEvent may name (runtime/coordinator.py
#: DynamicCluster surface)
MEMBERSHIP_ACTIONS = ("leave", "join", "drain")


@dataclass
class MembershipEvent:
    """One scheduled membership mutation: WHEN a call matching
    (site, stages, tasks) arrives for the ``nth_call`` time, the target
    ``url`` leaves / joins / starts draining the wrapped DynamicCluster —
    the elastic analogue of a FaultSpec, scheduled by site/stage/task like
    the existing fault kinds. Events fire exactly once. Like capped fault
    specs, the trigger slot is consumed in call ARRIVAL order, so under a
    concurrent stage fan-out the triggering (task, worker) may vary across
    runs while the EVENT SET stays deterministic — assert on results and
    membership state, not on which call pulled the trigger."""

    action: str  # "leave" | "join" | "drain"
    url: str  # the worker that leaves/joins/drains
    site: str = "execute"  # triggering call site ("set_plan" | "execute")
    #: restrict triggering calls to these stage ids; None = any stage
    stages: Optional[Sequence[int]] = None
    #: restrict triggering calls to these task numbers; None = any task
    tasks: Optional[Sequence[int]] = None
    #: fire on the nth MATCHING call (0 = the first)
    nth_call: int = 0
    #: leave only: release the departing worker's registry/store (process
    #: death); False leaks on purpose (for testing leak detection itself)
    release: bool = True

    def __post_init__(self):
        if self.action not in MEMBERSHIP_ACTIONS:
            raise ValueError(
                f"unknown membership action {self.action!r} "
                f"(expected one of {MEMBERSHIP_ACTIONS})"
            )
        if self.site not in SITES:
            raise ValueError(
                f"unknown membership trigger site {self.site!r} "
                f"(expected one of {SITES})"
            )

    def _matches(self, site: str, stage_id: int, task_number: int) -> bool:
        if site != self.site:
            return False
        if self.stages is not None and stage_id not in self.stages:
            return False
        if self.tasks is not None and task_number not in self.tasks:
            return False
        return True


class FaultPlan:
    """Seeded, thread-safe fault schedule shared by a cluster's
    ChaosWorkers. `fired` records every injected fault (site, url, stage,
    task, kind) — tests assert against it, and a failure report quoting it
    plus the seed reproduces the schedule. ``membership`` adds scheduled
    `leave`/`join`/`drain` events applied to the wrapped cluster's
    dynamic-membership surface at the same call sites."""

    def __init__(self, seed: int, specs: Sequence[FaultSpec],
                 membership: Sequence[MembershipEvent] = (),
                 query_scoped: bool = False):
        self.seed = int(seed)
        self.specs = list(specs)
        self.membership = list(membership)
        #: per-QUERY call counting (the multi-query serving tier): each
        #: query's (stage, task) call counts start at zero and the hash
        #: input stays query-free, so every concurrent query replays the
        #: IDENTICAL seeded fault schedule regardless of how the queries
        #: interleave — per-query chaos determinism. Off (the default),
        #: counts accumulate plan-wide across queries/attempts, the
        #: pre-serving behavior every existing schedule was written
        #: against. Caps (max_per_stage / max_total) stay plan-global in
        #: both modes: they bound total injected damage, not per-query
        #: schedules.
        self.query_scoped = bool(query_scoped)
        # forensic log of injected faults: bounded so a long-lived
        # serving process under sustained chaos (soak tests) cannot grow
        # it forever — schedules assert on far fewer than the cap
        self.fired: list[dict] = []  # guarded-by: _lock; per-query: bounded 4096
        self._lock = threading.Lock()
        #: (spec_idx, query_scope, site, stage, task) -> call count (the
        #: nth-call input of the hash, so repeated attempts of one task
        #: re-roll; query_scope is "" unless query_scoped)
        self._calls: dict[tuple, int] = {}  # guarded-by: _lock
        self._per_stage: dict[tuple, int] = {}  # guarded-by: _lock
        self._totals: dict[int, int] = {}  # guarded-by: _lock
        #: (spec_idx, query_scope, url) -> elected straggler? ONE seeded
        #: decision per key; True keeps delaying every later matching
        #: call — the sticky-slow-worker fault (kind="straggler")
        self._stragglers: dict[tuple, bool] = {}  # guarded-by: _lock
        #: event idx -> matching-call count / fired flag
        self._member_calls: dict[int, int] = {}  # guarded-by: _lock
        self._member_fired: set = set()  # guarded-by: _lock

    _FIRED_CAP = 4096

    def _note_fired_locked(self, rec: dict) -> None:
        """Record an injected fault; oldest entries roll off past the
        cap so a long-lived serving process never grows the log
        unboundedly."""
        self.fired.append(rec)
        if len(self.fired) > self._FIRED_CAP:
            del self.fired[: len(self.fired) - self._FIRED_CAP]

    def membership_due(self, site: str, url: str, key) -> list:
        """Membership events whose trigger this call just satisfied (each
        fires once); the caller applies them to the cluster."""
        if not self.membership:
            return []
        stage_id = getattr(key, "stage_id", -1)
        task_number = getattr(key, "task_number", 0)
        due = []
        with self._lock:
            for i, ev in enumerate(self.membership):
                if i in self._member_fired:
                    continue
                if not ev._matches(site, stage_id, task_number):
                    continue
                nth = self._member_calls.get(i, 0)
                self._member_calls[i] = nth + 1
                if nth != ev.nth_call:
                    continue
                self._member_fired.add(i)
                self._note_fired_locked({
                    "site": site, "url": ev.url, "stage_id": stage_id,
                    "task_number": task_number,
                    "kind": f"membership_{ev.action}", "nth_call": nth,
                    "trigger_url": url,
                })
                due.append(ev)
        return due

    def _unit(self, spec_idx: int, site: str, stage_id: int,
              task_number: int, nth: int) -> float:
        h = hashlib.sha256(
            f"{self.seed}:{spec_idx}:{site}:{stage_id}:"
            f"{task_number}:{nth}".encode()
        ).digest()
        return int.from_bytes(h[:8], "big") / float(1 << 64)

    def decide(self, site: str, url: str, key) -> Optional[FaultSpec]:
        """The fault (if any) to inject for this call. At most one spec
        fires per call (first declared wins)."""
        stage_id = getattr(key, "stage_id", -1)
        task_number = getattr(key, "task_number", 0)
        qscope = (getattr(key, "query_id", "") or "") if (
            self.query_scoped
        ) else ""
        with self._lock:
            for i, spec in enumerate(self.specs):
                if not spec._matches(site, url, stage_id, task_number):
                    continue
                if spec.kind == "straggler":
                    if self._straggler_locked(i, spec, qscope, url, site,
                                              stage_id, task_number):
                        return spec
                    continue
                ck = (i, qscope, site, stage_id, task_number)
                nth = self._calls.get(ck, 0)
                self._calls[ck] = nth + 1
                if spec.max_total is not None and (
                    self._totals.get(i, 0) >= spec.max_total
                ):
                    continue
                sk = (i, stage_id)
                if spec.max_per_stage is not None and (
                    self._per_stage.get(sk, 0) >= spec.max_per_stage
                ):
                    continue
                if self._unit(i, site, stage_id, task_number,
                              nth) >= spec.rate:
                    continue
                self._totals[i] = self._totals.get(i, 0) + 1
                self._per_stage[sk] = self._per_stage.get(sk, 0) + 1
                self._note_fired_locked({
                    "site": site, "url": url, "stage_id": stage_id,
                    "task_number": task_number, "kind": spec.kind,
                    "nth_call": nth,
                })
                return spec
        return None

    def _straggler_locked(self, i: int, spec: FaultSpec, qscope: str,
                          url: str, site: str, stage_id: int,
                          task_number: int) -> bool:
        """Sticky straggler election (caller holds `_lock`): decide ONCE
        per (spec, query-scope, url) whether this worker is slow, then
        answer every later matching call from that verdict — the rest of
        the query sees one consistently slow endpoint, not independent
        per-call coin flips. Caps bound ELECTIONS, not delayed calls."""
        sk = (i, qscope, url)
        verdict = self._stragglers.get(sk)
        if verdict is None:
            if spec.max_total is not None and (
                self._totals.get(i, 0) >= spec.max_total
            ):
                verdict = False
            else:
                h = hashlib.sha256(
                    f"{self.seed}:{i}:straggler:{qscope}:{url}".encode()
                ).digest()
                unit = int.from_bytes(h[:8], "big") / float(1 << 64)
                verdict = unit < spec.rate
            self._stragglers[sk] = verdict
            if verdict:
                self._totals[i] = self._totals.get(i, 0) + 1
                self._note_fired_locked({
                    "site": site, "url": url, "stage_id": stage_id,
                    "task_number": task_number, "kind": "straggler",
                    "nth_call": 0,
                })
        return verdict

    def sweep_query(self, query_id: str) -> int:
        """Release the per-query call-count state for a COMPLETED query
        (meaningful under ``query_scoped``: each in-flight query holds its
        own counters, and a long-lived serving process must shed them when
        the query resolves); -> entries removed. The coordinator's
        ``on_query_end`` hook is the natural caller."""
        if not query_id:
            return 0
        with self._lock:
            dead = [ck for ck in self._calls if ck[1] == query_id]
            for ck in dead:
                del self._calls[ck]
            sticky = [
                sk for sk in self._stragglers if sk[1] == query_id
            ]
            for sk in sticky:
                del self._stragglers[sk]
        return len(dead) + len(sticky)


def _interruptible_sleep(delay_s: float, cancel=None,
                         poll_s: float = 0.005) -> None:
    """Injected-delay sleep honoring the call's cancel handle: the delay
    is chopped into ``poll_s`` increments and aborts as soon as
    ``cancel.is_set()`` — so a hedged/cancelled loser stuck in an
    injected delay releases its slot at CANCELLATION latency, not after
    the full delay, and chaos tests measure the real cancel plumbing
    (the per-query cancel event / a hedge attempt's loser-cancel ride in
    through the worker surface's ``cancel=`` parameter)."""
    if delay_s <= 0:
        return
    if cancel is None:
        time.sleep(delay_s)
        return
    deadline = time.monotonic() + delay_s
    while not cancel.is_set():
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return
        time.sleep(min(poll_s, remaining))


def _raise_for(spec: FaultSpec, site: str, url: str, key) -> None:
    if spec.kind == "crash":
        raise WorkerUnavailableError(
            f"[chaos] injected worker crash at {site}",
            worker_url=url, task=key,
        )
    if spec.kind == "transport":
        raise TransportError(
            f"[chaos] injected transient transport error at {site}",
            worker_url=url, task=key,
        )
    raise WorkerError(
        f"[chaos] unknown fault kind {spec.kind!r}",
        worker_url=url, task=key,
    )


def _apply_skew(table, spec: FaultSpec):
    """kind="skew": concentrate a hot key in the task's bulk output —
    the first ``skew_fraction`` of ``skew_column``'s live rows are
    overwritten with the column's row-0 value (and row-0 validity), on
    COPIES of the host arrays; capacity, row count, schema, and every
    other column are untouched. A missing/absent column or an empty
    task degrades to a no-op rather than failing the call."""
    import numpy as np

    from datafusion_distributed_tpu.ops.table import Column, Table

    name = spec.skew_column or (table.names[0] if table.names else None)
    if not name or name not in table.names or table.num_rows <= 0:
        return table
    hot = int(int(table.num_rows) * min(max(spec.skew_fraction, 0.0), 1.0))
    if hot <= 0:
        return table
    col = table.column(name)
    data = np.asarray(col.data).copy()
    data[:hot] = data[0]
    validity = col.validity
    if validity is not None:
        validity = np.asarray(validity).copy()
        validity[:hot] = validity[0]
    cols = tuple(
        Column(data, validity, col.dtype, col.dictionary)
        if n == name else table.column(n)
        for n in table.names
    )
    return Table(tuple(table.names), cols, table.num_rows)


#: encoded-plan int fields that are STRUCTURAL (they enter the plan
#: fingerprint), so perturbing one yields a plan that decodes cleanly but
#: fingerprints differently — the exact "silently different program"
#: corruption the post-decode check exists to catch
_CORRUPTIBLE_KEYS = ("slots", "per_dest", "capacity", "out_cap", "fetch")


def _corrupt_plan_obj(plan_obj: dict) -> dict:
    """Deep-copied ``plan_obj`` with the first structural int field
    perturbed (deterministic walk: dict insertion order). The perturbed
    value is DOUBLED, not incremented: every corruptible field is a
    capacity-like count whose validity survives doubling (power-of-two
    slots stay powers of two), so the corrupted plan decodes AND executes
    cleanly — producing a silently different program, the exact hazard
    the post-decode fingerprint check exists to catch. Falls back to
    appending a bogus column to the first encoded schema when no numeric
    field exists (pure-scan plans)."""
    import copy

    obj = copy.deepcopy(plan_obj)
    done = []

    def walk(o):
        if done:
            return
        if isinstance(o, dict):
            for k, v in o.items():
                if k in _CORRUPTIBLE_KEYS and isinstance(v, int) and not (
                    isinstance(v, bool)
                ) and v > 0:
                    o[k] = v * 2
                    done.append(k)
                    return
            for v in o.values():
                walk(v)
        elif isinstance(o, list):
            for v in o:
                walk(v)

    walk(obj)
    if not done:

        def walk_schema(o):
            if done:
                return
            if isinstance(o, dict):
                if isinstance(o.get("schema"), list):
                    o["schema"] = o["schema"] + [["__chaos", "int32", True]]
                    done.append("schema")
                    return
                for v in o.values():
                    walk_schema(v)
            elif isinstance(o, list):
                for v in o:
                    walk_schema(v)

        walk_schema(obj)
    return obj


class ChaosWorker:
    """Fault-injecting proxy around a Worker (or any duck-typed worker
    client): intercepts the coordinator-visible call sites, delegates
    everything else untouched. `kind="delay"` sleeps then delegates —
    paired with `SET distributed.task_timeout_s` it exercises the
    hung-worker -> TaskTimeoutError conversion."""

    def __init__(self, inner, plan: FaultPlan, cluster=None):
        self._inner = inner
        self._plan = plan
        self._cluster = cluster  # ChaosCluster, for membership events

    def _membership(self, site: str, key) -> None:
        """Apply any membership events this call triggers, then fail the
        call if THIS worker is no longer a member: a departed worker's
        endpoint is dead — staged slices and shipped plans went with it —
        and the coordinator's retry machinery must re-stage onto
        survivors."""
        if self._cluster is None:
            return
        for ev in self._plan.membership_due(site, self.url, key):
            self._cluster.apply_membership(ev)
        if self._cluster.is_departed(self.url):
            raise WorkerUnavailableError(
                f"[chaos] worker left the cluster at {site}",
                worker_url=self.url, task=key,
            )

    # -- intercepted control plane ------------------------------------------
    def set_plan(self, key, plan_obj, task_count, cancel=None, **kw):
        # ``cancel`` is consumed HERE (the injected delay polls it), not
        # forwarded: the inner worker surface has no dispatch-cancel
        # parameter — the coordinator only passes it because this proxy
        # declares it
        self._membership("set_plan", key)
        spec = self._plan.decide("set_plan", self.url, key)
        if spec is not None:
            if spec.kind in ("delay", "straggler"):
                _interruptible_sleep(spec.delay_s, cancel)
            elif spec.kind == "oom":
                self._apply_oom(spec)
            elif spec.kind == "corrupt_plan":
                # in-transit corruption: a DEEP copy is mutated (the
                # in-process transport shares the dict object with the
                # coordinator, which must keep its pristine copy for
                # retries/cleanup). The worker's post-decode fingerprint
                # check must refuse this plan (PlanIntegrityError), not
                # execute it.
                plan_obj = _corrupt_plan_obj(plan_obj)
            else:
                _raise_for(spec, "set_plan", self.url, key)
        return self._inner.set_plan(key, plan_obj, task_count, **kw)

    # -- intercepted data plane ---------------------------------------------
    def _apply_oom(self, spec: FaultSpec) -> None:
        """Collapse this worker's enforced memory budget (seeded
        per-worker budget collapse): spill engages immediately on the
        resident entries, and subsequent staging runs under the
        collapsed budget. No error is raised — memory pressure is a
        DEGRADATION fault, and the resilience machinery (spill,
        backpressure, shedding) must absorb it without changing
        results."""
        store = getattr(self._inner, "table_store", None)
        if store is None or not hasattr(store, "set_budget"):
            return
        budget = spec.budget_bytes
        if budget is None:
            budget = max(store.nbytes() // 2, 1)
        store.set_budget(budget)

    def _execute_fault(self, key, cancel=None):
        self._membership("execute", key)
        spec = self._plan.decide("execute", self.url, key)
        if spec is not None:
            if spec.kind in ("delay", "straggler"):
                _interruptible_sleep(spec.delay_s, cancel)
            elif spec.kind == "oom":
                self._apply_oom(spec)
            elif spec.kind == "skew":
                # workload-shaping, not an error: manifests on the RESULT
                # of bulk execute_task (the caller applies _apply_skew);
                # call-time is a no-op so stream/partition paths that
                # share this fault site pass through untouched
                pass
            elif spec.kind == "segment_lost":
                # transfer-specific: ARM the client's tear-next-segment
                # hook and delegate — the fault manifests mid-stream as
                # a vanished shm segment (the window a dying producer
                # leaves behind), and the assertion is that the pull
                # DEGRADES to the wire path, not that this call raises.
                # On clients without the hook (in-process workers, other
                # data-plane calls) the schedule slot is a no-op.
                if hasattr(self._inner, "_chaos_tear_next_segment"):
                    self._inner._chaos_tear_next_segment = True
            else:
                _raise_for(spec, "execute", self.url, key)
        return spec

    def execute_task(self, key, cancel=None):
        # deliberately NO timeout= parameter: advertising one would make
        # the coordinator delegate deadline enforcement to the inner
        # worker, which cannot see this proxy's injected delay — the
        # coordinator's thread deadline must cover the whole (faulty)
        # call. ``cancel`` IS declared: the coordinator's attempt-cancel
        # plumbing (per-query event, hedge loser-cancel) reaches the
        # injected delay's poll loop through it; the inner in-process
        # worker has no cancel surface, so it is consumed here.
        spec = self._execute_fault(key, cancel)
        out = self._inner.execute_task(key)
        if spec is not None and spec.kind == "skew":
            out = _apply_skew(out, spec)
        return out

    def execute_task_stream(self, key, **kw):
        # inject at CALL time, not first-iteration: the coordinator's
        # retry-while-nothing-yielded window must see the fault before
        # any chunk is out. The stream's own cancel event (already part
        # of the surface) doubles as the delay's interrupt.
        self._execute_fault(key, kw.get("cancel"))
        return self._inner.execute_task_stream(key, **kw)

    def execute_task_partitions(self, key, *a, **kw):
        self._execute_fault(key, kw.get("cancel"))
        return self._inner.execute_task_partitions(key, *a, **kw)

    def transfer_partitions(self, key, *a, **kw):
        # explicit proxy (NOT __getattr__ passthrough) so transfer pulls
        # sit under the same execute-site fault schedule as the other
        # data-plane calls — including kind="segment_lost", which arms
        # the client's tear hook in _execute_fault and lets the stream
        # proceed into the torn-segment window
        self._execute_fault(key, kw.get("cancel"))
        return self._inner.transfer_partitions(key, *a, **kw)

    # -- transparent delegation ---------------------------------------------
    def __getattr__(self, name):
        return getattr(self._inner, name)


@dataclass
class ChaosCluster:
    """Resolver+channels facade over a real cluster, handing out
    ChaosWorker proxies. The inner workers' PEER channels stay unwrapped
    (peer pulls model worker<->worker links; this harness injects at the
    coordinator<->worker boundary). Membership events in the FaultPlan
    are applied through the inner cluster's dynamic-membership surface
    (DynamicCluster / GrpcCluster add/remove/drain); the membership API
    itself — `add_worker`, `drain_worker`, `membership_epoch`,
    `membership_snapshot`, `workers`, ... — passes through via
    `__getattr__` so a coordinator sees the chaos-wrapped cluster as the
    elastic cluster it wraps."""

    inner: "object"
    plan: FaultPlan
    _proxies: dict = field(default_factory=dict)  # guarded-by: _proxy_lock
    # DFTPU201 fix: stage fan-out threads resolve workers concurrently
    # with chaos membership events popping proxies from worker-call
    # threads; the bare check-then-insert could mint two proxies for one
    # url (splitting the fault plan's nth-call view of that worker) or
    # resurrect a departed worker's proxy mid-pop
    _proxy_lock: threading.Lock = field(default_factory=threading.Lock)

    def get_urls(self) -> list[str]:
        return self.inner.get_urls()

    def get_worker(self, url: str) -> ChaosWorker:
        with self._proxy_lock:
            if url not in self._proxies:
                self._proxies[url] = ChaosWorker(
                    self.inner.get_worker(url), self.plan, cluster=self
                )
            return self._proxies[url]

    # -- membership events ----------------------------------------------------
    def apply_membership(self, ev: MembershipEvent) -> None:
        if ev.action == "leave":
            self.inner.remove_worker(ev.url, release=ev.release)
            with self._proxy_lock:
                self._proxies.pop(ev.url, None)
        elif ev.action == "join":
            self.inner.add_worker(ev.url)
        else:  # drain
            self.inner.drain_worker(ev.url)

    def is_departed(self, url: str) -> bool:
        probe = getattr(self.inner, "is_departed", None)
        return bool(probe(url)) if callable(probe) else False

    def __getattr__(self, name: str):
        # dynamic-membership + introspection passthrough (only reached for
        # attributes not defined on the facade itself)
        return getattr(self.inner, name)


def wrap_cluster(cluster, plan: FaultPlan) -> ChaosCluster:
    """Wrap any resolver+channels cluster (InMemoryCluster, GrpcCluster)
    in the fault-injection harness."""
    return ChaosCluster(cluster, plan)


def one_crash_per_stage(seed: int, kind: str = "crash",
                        max_total: Optional[int] = None) -> FaultPlan:
    """The canonical acceptance schedule: the first task dispatch of every
    stage hits one injected fault, forcing a retry+reroute per stage."""
    return FaultPlan(seed, [
        FaultSpec(site="execute", kind=kind, rate=1.0, max_per_stage=1,
                  max_total=max_total),
    ])
