"""Fingerprint-keyed result & sub-plan cache (`SET distributed.
result_cache`) — the serving tier's answer to repeated and
literal-variant traffic.

Two tiers share one byte-budgeted TableStore:

- **Whole-result cache**: keyed on (post-hoist structural plan
  fingerprint, hoisted-literal parameter vectors, full PlannerConfig
  snapshot, catalog generation, task profile) — see
  `plan/fingerprint.py result_cache_key`. Identical and literal-variant
  resubmissions skip planning *and* execution entirely and return the
  staged result Table BY REFERENCE through the zero-copy TableStore
  surface (a hit is the same buffers the cold run produced — byte
  identity is structural, not re-verified). Single-flight: concurrent
  submissions of one key block on the owner's fill instead of
  stampeding duplicate executions.
- **Sub-plan cache**: exchange-subtree frontiers keyed CROSS-QUERY by
  the pre-hoist subtree fingerprint checkpoint.py already computes
  (literal values are structural there, so two queries differing only
  in literals never share a frontier). A new query's coordinator
  restores a cached frontier through the same
  `_materialize_exchange_node` hook the checkpoint/resume path rides —
  slices live in THIS cache's store, so a hit never consults departed
  workers.

Residency: the owned TableStore enforces
`SET distributed.result_cache_budget_bytes` by SPILLING cold entries
via SpillManager instead of evicting them — `get` refaults byte-exactly
with the pytree aux structure preserved, so a refaulted hit triggers
zero new XLA traces. Invalidation: `register_table` bumps
`catalog.generation`; `sync`/`invalidate_generation` drop every entry
staged under an older generation (whole-result keys also carry the
generation, so a stale entry can never even be looked up).

Entries are deliberately process-lifetime (they outlive the queries
that filled them, exactly like checkpoint slices): store inserts run
under ``staging_attribution(None)`` so query-end leak sweeps never flag
them, and each logical entry is tracked as a ``result-cache-entry``
with the leak harness until invalidated/cleared.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from datafusion_distributed_tpu.runtime import leakcheck as _leakcheck
from datafusion_distributed_tpu.runtime.codec import (
    CodecError,
    TableStore,
    staging_attribution,
)

__all__ = ["ResultCache"]

#: how long a single-flight waiter blocks on the owner before giving up
#: and executing itself (a wedged owner must not deadlock the tier; the
#: duplicate fill displaces harmlessly)
_FLIGHT_WAIT_S = 600.0

#: reused-coordinator bound on per-execute fingerprint maps (fresh
#: coordinators sweep via end_query; a user-held coordinator that never
#: sweeps sheds its oldest execute's map instead of growing forever)
_QUERY_FPS_BOUND = 32


def _key_fp(key) -> Optional[str]:
    """The display fingerprint of a whole-result key (event labels)."""
    if isinstance(key, tuple):
        for part in key:
            if isinstance(part, str):
                return part[:16]
    return None


def _log(kind: str, **fields) -> None:
    """Best-effort event-log emission — cache observability must never
    fail (or slow) the query path it annotates."""
    try:
        from datafusion_distributed_tpu.runtime.eventlog import log_event

        log_event(kind, **fields)
    except Exception:
        pass


class _Entry:
    """One whole-result entry: the staged result's table id plus the
    bookkeeping invalidation and stats need."""

    __slots__ = ("tid", "nbytes", "generation")

    def __init__(self, tid: str, nbytes: int, generation):
        self.tid = tid
        self.nbytes = nbytes
        self.generation = generation


class _SubplanEntry:
    """One cached exchange frontier: per-slice table ids plus the scan
    annotations a restore must reproduce exactly."""

    __slots__ = ("tids", "replicated", "pinned", "t_prod", "nbytes",
                 "generation")

    def __init__(self, tids, replicated, pinned, t_prod, nbytes,
                 generation):
        self.tids = tids
        self.replicated = replicated
        self.pinned = pinned
        self.t_prod = t_prod
        self.nbytes = nbytes
        self.generation = generation


class ResultCache:
    """Whole-result + sub-plan cache over one spill-backed TableStore.

    Thread-safe: serving client threads probe `lookup`, per-query driver
    threads race `begin`/`fill`, and coordinator stage threads call the
    sub-plan surface — all against one instance. Store I/O (staging,
    refault, spill) always runs OUTSIDE the cache lock (DFTPU205)."""

    def __init__(self, budget_bytes: int = 0) -> None:
        self._lock = threading.Lock()
        # single-flight rendezvous: waiters block here until the owner
        # fills or fails their key (condition over the SAME lock, so
        # the wait atomically releases the cache state it re-checks)
        self._flight_cv = threading.Condition(self._lock)
        # the residency tier: byte-budgeted, spills cold entries via
        # SpillManager and refaults byte-exactly on get (codec.py)
        self._store = TableStore(budget_bytes=int(budget_bytes or 0))
        self._results: dict = {}  # guarded-by: _lock
        self._subplans: dict = {}  # guarded-by: _lock
        self._flights: set = set()  # guarded-by: _lock
        # execute-scoped pre-hoist exchange fingerprints (the sub-plan
        # keys), stamped by Coordinator.execute via begin_query
        self._query_fps: dict = {}  # guarded-by: _lock; per-query: swept-by end_query; per-query: bounded 32
        self._generation = None  # guarded-by: _lock
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.fills = 0  # guarded-by: _lock
        self.subplan_hits = 0  # guarded-by: _lock
        self.subplan_misses = 0  # guarded-by: _lock
        self.subplan_fills = 0  # guarded-by: _lock
        self.invalidations = 0  # guarded-by: _lock

    # -- configuration -------------------------------------------------------
    def set_budget(self, budget_bytes) -> None:
        """Replace the enforced byte budget (0/None = unlimited); the
        store rebalances (spills) immediately."""
        self._store.set_budget(budget_bytes)

    def sync(self, generation=None, budget_bytes=None) -> None:
        """Reconcile with the session: adopt the live catalog generation
        (dropping entries staged under an older one — the lazy half of
        `register_table` invalidation, covering direct catalog writes)
        and the live budget knob."""
        if generation is not None:
            self.invalidate_generation(generation)
        if budget_bytes is not None:
            try:
                b = int(float(budget_bytes or 0))
            except (TypeError, ValueError):
                b = 0
            if b != self._store.budget_bytes:
                self._store.set_budget(b)

    # -- invalidation --------------------------------------------------------
    def invalidate_generation(self, generation) -> int:  # releases: result-cache-entry
        """Drop every entry staged under a generation other than
        ``generation`` and adopt it; -> entries dropped. Idempotent and
        cheap when nothing changed (the register_table hot path)."""
        dead_tids: list = []
        dropped = 0
        with self._lock:
            if generation == self._generation:
                return 0
            self._generation = generation
            for key in [k for k, e in self._results.items()
                        if e.generation != generation]:
                e = self._results.pop(key)
                dead_tids.append(e.tid)
                if _leakcheck.enabled():
                    _leakcheck.note_release(
                        "result-cache-entry", (id(self), e.tid)
                    )
                dropped += 1
            for fp in [f for f, e in self._subplans.items()
                       if e.generation != generation]:
                e = self._subplans.pop(fp)
                dead_tids.extend(e.tids)
                if _leakcheck.enabled():
                    _leakcheck.note_release(
                        "result-cache-entry", (id(self), "sp:" + fp)
                    )
                dropped += 1
            if dropped:
                self.invalidations += dropped
        if dead_tids:
            # store release OUTSIDE the cache lock: a spilled victim's
            # slot unlink happens under the store's own lock
            self._store.remove(dead_tids)
        if dropped:
            _log("result_cache_invalidate", entries=dropped,
                 generation=generation)
        return dropped

    # -- whole-result surface ------------------------------------------------
    def lookup(self, key, query_id=None):
        """Non-blocking peek (the serving tier's pre-costing admission
        probe): the cached Table or None. A miss is NOT counted — the
        executing path's `begin` counts it exactly once."""
        if key is None:
            return None
        with self._lock:
            e = self._results.get(key)
        if e is None:
            return None
        return self._fetch(key, e, query_id)

    def begin(self, key, query_id=None):
        """Single-flight consult: -> ("hit", table) or ("miss", None).
        On a miss the CALLER owns execution and MUST resolve the flight
        with `fill` (success) or `fail` (error) — concurrent callers of
        the same key block here until then instead of executing
        duplicates."""
        deadline = time.monotonic() + _FLIGHT_WAIT_S
        while True:
            entry = None
            with self._flight_cv:
                e = self._results.get(key)
                if e is not None:
                    entry = e
                elif key not in self._flights:
                    self._flights.add(key)
                    self.misses += 1
                    miss = True
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        # wedged owner: execute ourselves — the
                        # duplicate fill displaces, never corrupts
                        self.misses += 1
                        miss = True
                    else:
                        self._flight_cv.wait(timeout=min(remaining, 1.0))
                        continue
            if entry is None:
                if miss:
                    _log("result_cache_miss", fingerprint=_key_fp(key),
                         query_id=query_id)
                    return ("miss", None)
                continue
            t = self._fetch(key, entry, query_id)
            if t is not None:
                return ("hit", t)
            # entry vanished between peek and fetch (raced invalidate):
            # loop — next pass either sees a fresh entry or owns a miss

    def _fetch(self, key, entry: _Entry, query_id):
        """Resolve an entry's Table outside the cache lock (a spilled
        entry refaults byte-exactly here); None if it raced away."""
        try:
            t = self._store.get(entry.tid)
        except CodecError:
            return None
        with self._lock:
            self.hits += 1
        _log("result_cache_hit", fingerprint=_key_fp(key),
             nbytes=entry.nbytes, query_id=query_id)
        return t

    def fill(self, key, table, query_id=None) -> None:  # acquires: result-cache-entry (managed)
        """Install an executed result and wake the key's waiters.
        Unattributed staging: entries outlive the filling query, so the
        query-end leak sweep must not claim them."""
        tid = "rc-" + uuid.uuid4().hex
        with staging_attribution(None):
            self._store.put_as(tid, table)
        nbytes = self._store.entry_nbytes(tid)
        stale = None
        with self._flight_cv:
            old = self._results.get(key)
            if old is not None:
                # raced duplicate execution (flight-timeout path): the
                # newest fill wins, the displaced entry releases below
                stale = old.tid
                if _leakcheck.enabled():
                    _leakcheck.note_release(
                        "result-cache-entry", (id(self), old.tid)
                    )
            self._results[key] = _Entry(tid, nbytes, self._generation)
            self.fills += 1
            if _leakcheck.enabled():
                _leakcheck.note_acquire(
                    "result-cache-entry", (id(self), tid),
                    tag="ResultCache.fill",
                )
            self._flights.discard(key)
            self._flight_cv.notify_all()
        if stale is not None:
            self._store.remove([stale])
        _log("result_cache_fill", fingerprint=_key_fp(key),
             nbytes=nbytes, query_id=query_id)

    def fail(self, key) -> None:
        """The owning execution failed: release the flight so one waiter
        takes over ownership (its next `begin` pass claims the miss)."""
        with self._flight_cv:
            self._flights.discard(key)
            self._flight_cv.notify_all()

    # -- sub-plan surface (Coordinator._materialize_exchange_node) -----------
    def begin_query(self, query_id: str, plan) -> None:
        """Stamp one Coordinator.execute: fingerprint the plan's
        pristine exchange subtrees (pre-hoist — shared helper with the
        checkpoint tier, so sub-plan keys and checkpoint keys can never
        drift) under the execute's query id."""
        from datafusion_distributed_tpu.runtime.checkpoint import (
            exchange_fingerprints,
        )

        fps = exchange_fingerprints(plan)
        with self._lock:
            while len(self._query_fps) >= _QUERY_FPS_BOUND:
                self._query_fps.pop(next(iter(self._query_fps)))
            self._query_fps[query_id] = fps

    def end_query(self, query_id: str) -> None:
        """Query-end sweep of the execute's fingerprint map (the cached
        frontiers themselves stay — they are the cross-query point)."""
        with self._lock:
            self._query_fps.pop(query_id, None)

    def restore_subplan(self, query_id: str, stage_id: int):
        """-> (slices, replicated, pinned, t_prod) for a cached frontier
        matching this execute's stage fingerprint, or None. Slices are
        served from THIS cache's store (refaulting if spilled), so a
        restore never consults any worker."""
        with self._lock:
            fp = (self._query_fps.get(query_id) or {}).get(stage_id)
            if fp is None:
                return None
            e = self._subplans.get(fp)
            if e is None:
                self.subplan_misses += 1
                return None
            tids = e.tids
            meta = (e.replicated, e.pinned, e.t_prod)
        slices = []
        for tid in tids:
            try:
                slices.append(self._store.get(tid))
            except CodecError:
                return None  # raced invalidate mid-restore: re-execute
        with self._lock:
            self.subplan_hits += 1
        _log("result_cache_subplan_hit", fingerprint=fp[:16],
             stage=stage_id, query_id=query_id)
        return (slices, *meta)

    def save_subplan(self, query_id: str, stage_id: int, slices,  # acquires: result-cache-entry (managed)
                     replicated: bool, pinned: bool,
                     t_prod: int) -> Optional[int]:
        """Stage a just-materialized frontier under its subtree
        fingerprint; -> staged bytes or None (unfingerprintable stage /
        already cached / raced sibling)."""
        with self._lock:
            fp = (self._query_fps.get(query_id) or {}).get(stage_id)
            if fp is None or fp in self._subplans:
                return None
            gen = self._generation
        tids = []
        total = 0
        with staging_attribution(None):
            for t in slices:
                tid = "rcsp-" + uuid.uuid4().hex
                self._store.put_as(tid, t)
                tids.append(tid)
                total += self._store.entry_nbytes(tid)
        stale = None
        with self._lock:
            if fp in self._subplans:
                stale = tids  # raced sibling saved first: drop ours
            else:
                self._subplans[fp] = _SubplanEntry(
                    tuple(tids), replicated, pinned, t_prod, total, gen
                )
                self.subplan_fills += 1
                if _leakcheck.enabled():
                    _leakcheck.note_acquire(
                        "result-cache-entry", (id(self), "sp:" + fp),
                        tag="ResultCache.save_subplan",
                    )
        if stale is not None:
            self._store.remove(stale)
            return None
        _log("result_cache_subplan_fill", fingerprint=fp[:16],
             stage=stage_id, nbytes=total, query_id=query_id)
        return total

    # -- lifecycle -----------------------------------------------------------
    def clear(self) -> int:  # releases: result-cache-entry
        """Drop every cached entry (and its store bytes / spill files);
        -> entries dropped. The test-facing zero-leak teardown."""
        dead: list = []
        with self._lock:
            for e in self._results.values():
                dead.append(e.tid)
                if _leakcheck.enabled():
                    _leakcheck.note_release(
                        "result-cache-entry", (id(self), e.tid)
                    )
            for fp, e in self._subplans.items():
                dead.extend(e.tids)
                if _leakcheck.enabled():
                    _leakcheck.note_release(
                        "result-cache-entry", (id(self), "sp:" + fp)
                    )
            n = len(self._results) + len(self._subplans)
            self._results.clear()
            self._subplans.clear()
            self._query_fps.clear()
        if dead:
            self._store.remove(dead)
        return n

    close = clear

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            out = {
                "hits": self.hits,
                "misses": self.misses,
                "fills": self.fills,
                "subplan_hits": self.subplan_hits,
                "subplan_misses": self.subplan_misses,
                "subplan_fills": self.subplan_fills,
                "invalidations": self.invalidations,
                "entries": len(self._results),
                "subplan_entries": len(self._subplans),
                "generation": self._generation,
            }
        probes = out["hits"] + out["misses"]
        out["hit_rate"] = (out["hits"] / probes) if probes else 0.0
        s = self._store.stats()
        for k in ("nbytes", "budget_bytes", "spilled_nbytes", "spills",
                  "refaults", "spill_files"):
            out[k] = s[k]
        return out

    def telemetry_families(self) -> list:
        """Typed-registry adapter (runtime/telemetry.py): the
        `dftpu_result_cache_*` families, eagerly present (zero-valued)
        from the first snapshot so dashboards never see a gap between
        'cache off' and 'cache cold'."""
        from datafusion_distributed_tpu.runtime.telemetry import family

        st = self.stats()
        return [
            family("dftpu_result_cache_hits", "counter",
                   "Cache hits by tier (result = whole-result, "
                   "subplan = exchange-frontier).",
                   [({"tier": "result"}, st["hits"]),
                    ({"tier": "subplan"}, st["subplan_hits"])]),
            family("dftpu_result_cache_misses", "counter",
                   "Cache misses by tier.",
                   [({"tier": "result"}, st["misses"]),
                    ({"tier": "subplan"}, st["subplan_misses"])]),
            family("dftpu_result_cache_invalidations", "counter",
                   "Entries dropped by catalog-generation bumps.",
                   [({}, st["invalidations"])]),
            family("dftpu_result_cache_bytes", "gauge",
                   "Resident cached bytes (owned, spill-blind).",
                   [({}, st["nbytes"])]),
            family("dftpu_result_cache_spilled_bytes", "gauge",
                   "Cached bytes currently spilled to the disk segment.",
                   [({}, st["spilled_nbytes"])]),
            family("dftpu_result_cache_entries", "gauge",
                   "Live entries by tier.",
                   [({"tier": "result"}, st["entries"]),
                    ({"tier": "subplan"}, st["subplan_entries"])]),
        ]
