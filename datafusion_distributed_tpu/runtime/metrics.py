"""Metrics collection + explain_analyze rendering.

The reference collects DataFusion per-node metrics on workers, protobuf-ships
them to the coordinator's MetricsStore, and `explain_analyze` stitches them
back into the plan display labeled by task
(`/root/reference/src/metrics/task_metrics_rewriter.rs`,
`stage.rs display_plan_ascii`). TPU twist: metrics inside a jitted program
must be *traced outputs*, so operators record row-count scalars into the
ExecContext during tracing and the executors return them alongside the
result; host-side wall-clock and bytes metrics attach per task afterwards.

Formats mirror DistributedMetricsFormat::{Aggregated, PerTask}.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from datafusion_distributed_tpu.plan.physical import ExecutionPlan


@dataclass
class MetricsStore:
    """(task_label -> node_id -> {metric: value}); the watch-map analogue of
    the reference's MetricsStore (`metrics_store.rs`)."""

    per_task: dict = field(default_factory=dict)

    def insert(self, task_label: str, node_metrics: dict) -> None:
        self.per_task[task_label] = node_metrics

    def aggregated(self) -> dict:
        """node_id -> {metric: summed value across tasks}."""
        out: dict = {}
        for metrics in self.per_task.values():
            for nid, mm in metrics.items():
                slot = out.setdefault(nid, {})
                for name, v in mm.items():
                    slot[name] = slot.get(name, 0) + v
        return out

    def per_task_view(self) -> dict:
        """node_id -> {metric_taskN: value} (PerTask format)."""
        out: dict = {}
        for label, metrics in sorted(self.per_task.items()):
            for nid, mm in metrics.items():
                slot = out.setdefault(nid, {})
                for name, v in mm.items():
                    slot[f"{name}_{label}"] = v
        return out


class FaultCounters:
    """Thread-safe counters for the fault-tolerant execution layer
    (retries, reroutes, timeouts, quarantine trips). Surfaced through
    `Coordinator.faults` and `ObservabilityService.get_fault_counters`;
    mergeable across coordinators like the latency sketch."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def as_dict(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def merge(self, other: "FaultCounters") -> "FaultCounters":
        for name, n in other.as_dict().items():
            self.bump(name, n)
        return self


def explain_analyze(
    plan: ExecutionPlan,
    store: MetricsStore,
    per_task: bool = False,
    diagnostics: "Optional[list]" = None,
) -> str:
    """Render the plan tree with metrics stitched into each node line.

    ``diagnostics``: verifier findings (plan/verify.py Diagnostic list, or
    a VerifyResult) rendered per node id next to the runtime metrics —
    e.g. a "literal not hoistable — plan will not share compiles" warning
    lands on the exact Filter it applies to. None = run the verifier here
    so explain_analyze always shows static findings alongside metrics."""
    from datafusion_distributed_tpu.plan.verify import (
        VerifyResult,
        diag_suffix,
        verify_physical_plan,
    )

    node_metrics = store.per_task_view() if per_task else store.aggregated()
    if diagnostics is None:
        result = verify_physical_plan(plan)
    elif isinstance(diagnostics, VerifyResult):
        result = diagnostics
    else:
        result = VerifyResult(diagnostics)
    diag_by_node = result.by_node()
    lines = []

    def walk(node: ExecutionPlan, indent: int) -> None:
        mm = node_metrics.get(node.node_id, {})
        suffix = ""
        if mm:
            inner = ", ".join(f"{k}={_fmt(v)}" for k, v in sorted(mm.items()))
            suffix = f"  [{inner}]"
        suffix += diag_suffix(diag_by_node.get(node.node_id, ()))
        marker = ""
        if getattr(node, "is_exchange", False):
            marker = f" ── stage {node.stage_id}"
        lines.append("  " * indent + node.display() + marker + suffix)
        for c in node.children():
            walk(c, indent + 1)

    walk(plan, 0)
    return "\n".join(lines)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4f}"
    return str(v)


class LatencySketch:
    """Mergeable log-bucketed latency sketch (the DDSketch role in the
    reference: per-task latency distributions shipped as sketch bytes and
    merged coordinator-side, `metrics/latency_metric.rs:3-13`,
    worker.proto PercentileLatency).

    Buckets are powers of gamma, giving a fixed RELATIVE accuracy
    (gamma=1.02 -> ~2% error on any quantile) with tiny fixed state —
    mergeable by adding bucket counts, exactly the property DDSketch is
    used for."""

    def __init__(self, gamma: float = 1.02, min_value: float = 1e-6):
        import math

        self.gamma = gamma
        self.min_value = min_value
        self._log_gamma = math.log(gamma)
        self.buckets: dict[int, int] = {}
        self.count = 0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def record(self, value: float) -> None:
        import math

        v = max(float(value), self.min_value)
        idx = int(math.ceil(math.log(v / self.min_value) / self._log_gamma))
        self.buckets[idx] = self.buckets.get(idx, 0) + 1
        self.count += 1
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def merge(self, other: "LatencySketch") -> "LatencySketch":
        assert other.gamma == self.gamma
        for idx, c in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + c
        self.count += other.count
        for bound in ("min", "max"):
            ov = getattr(other, bound)
            sv = getattr(self, bound)
            if ov is not None:
                pick = min if bound == "min" else max
                setattr(self, bound, ov if sv is None else pick(sv, ov))
        return self

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 1] -> value with <= gamma relative error."""
        if self.count == 0:
            return None
        target = max(1, int(round(q * self.count)))
        seen = 0
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if seen >= target:
                # bucket midpoint in log space
                return self.min_value * self.gamma ** (idx - 0.5)
        return self.max

    def summary(self) -> dict:
        return {
            "count": self.count,
            "min": self.min,
            "p50": self.percentile(0.50),
            "p75": self.percentile(0.75),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
            "max": self.max,
        }

    def to_dict(self) -> dict:
        """Wire format (the sketch-bytes analogue)."""
        return {
            "gamma": self.gamma,
            "min_value": self.min_value,
            "buckets": {str(k): v for k, v in self.buckets.items()},
            "count": self.count,
            "min": self.min,
            "max": self.max,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LatencySketch":
        s = cls(gamma=d["gamma"], min_value=d["min_value"])
        s.buckets = {int(k): v for k, v in d["buckets"].items()}
        s.count = d["count"]
        s.min = d["min"]
        s.max = d["max"]
        return s
