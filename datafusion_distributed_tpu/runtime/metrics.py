"""Metrics collection + explain_analyze rendering.

The reference collects DataFusion per-node metrics on workers, protobuf-ships
them to the coordinator's MetricsStore, and `explain_analyze` stitches them
back into the plan display labeled by task
(`/root/reference/src/metrics/task_metrics_rewriter.rs`,
`stage.rs display_plan_ascii`). TPU twist: metrics inside a jitted program
must be *traced outputs*, so operators record row-count scalars into the
ExecContext during tracing and the executors return them alongside the
result; host-side wall-clock and bytes metrics attach per task afterwards.

Formats mirror DistributedMetricsFormat::{Aggregated, PerTask}.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from datafusion_distributed_tpu.plan.physical import ExecutionPlan


@dataclass
class MetricsStore:
    """(task_label -> node_id -> {metric: value}); the watch-map analogue of
    the reference's MetricsStore (`metrics_store.rs`)."""

    per_task: dict = field(default_factory=dict)

    def insert(self, task_label: str, node_metrics: dict) -> None:
        self.per_task[task_label] = node_metrics

    def aggregated(self) -> dict:
        """node_id -> {metric: summed value across tasks}."""
        out: dict = {}
        for metrics in self.per_task.values():
            for nid, mm in metrics.items():
                slot = out.setdefault(nid, {})
                for name, v in mm.items():
                    slot[name] = slot.get(name, 0) + v
        return out

    def per_task_view(self) -> dict:
        """node_id -> {metric_taskN: value} (PerTask format)."""
        out: dict = {}
        for label, metrics in sorted(self.per_task.items()):
            for nid, mm in metrics.items():
                slot = out.setdefault(nid, {})
                for name, v in mm.items():
                    slot[f"{name}_{label}"] = v
        return out


def explain_analyze(
    plan: ExecutionPlan,
    store: MetricsStore,
    per_task: bool = False,
) -> str:
    """Render the plan tree with metrics stitched into each node line."""
    node_metrics = store.per_task_view() if per_task else store.aggregated()
    lines = []

    def walk(node: ExecutionPlan, indent: int) -> None:
        mm = node_metrics.get(node.node_id, {})
        suffix = ""
        if mm:
            inner = ", ".join(f"{k}={_fmt(v)}" for k, v in sorted(mm.items()))
            suffix = f"  [{inner}]"
        marker = ""
        if getattr(node, "is_exchange", False):
            marker = f" ── stage {node.stage_id}"
        lines.append("  " * indent + node.display() + marker + suffix)
        for c in node.children():
            walk(c, indent + 1)

    walk(plan, 0)
    return "\n".join(lines)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4f}"
    return str(v)
