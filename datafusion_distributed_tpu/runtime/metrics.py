"""Metrics collection + explain_analyze rendering.

The reference collects DataFusion per-node metrics on workers, protobuf-ships
them to the coordinator's MetricsStore, and `explain_analyze` stitches them
back into the plan display labeled by task
(`/root/reference/src/metrics/task_metrics_rewriter.rs`,
`stage.rs display_plan_ascii`). TPU twist: metrics inside a jitted program
must be *traced outputs*, so operators record row-count scalars into the
ExecContext during tracing and the executors return them alongside the
result; host-side wall-clock and bytes metrics attach per task afterwards.

Formats mirror DistributedMetricsFormat::{Aggregated, PerTask}.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from datafusion_distributed_tpu.plan.physical import ExecutionPlan


#: bound on distinct queries whose stage spans a MetricsStore retains
#: (least-recently-touched evicted first — a long-lived serving process
#: must not grow forever; queries still RUNNING are pinned and never
#: evicted, so a burst of short queries cannot erase an in-flight heavy
#: query's spans before its own explain_analyze reads them)
_STAGE_SPAN_QUERY_CAP = 64


@dataclass
class MetricsStore:
    """(task_label -> node_id -> {metric: value}); the watch-map analogue of
    the reference's MetricsStore (`metrics_store.rs`). Also holds the
    concurrent stage scheduler's per-stage wall-clock spans
    (submit -> start -> materialized) and per-query wall clocks, rendered
    by `explain_analyze` as a critical-path summary whose
    `sum(stage wall) / query wall` overlap factor is the proof that
    independent stages actually ran concurrently.

    Thread-safe: under the multi-query serving tier one store is shared
    by every in-flight query's coordinator, so span recording, the
    running-query pin set, and LRU eviction all serialize on one lock."""

    per_task: dict = field(default_factory=dict)  # guarded-by: _lock
    #: query_id -> {stage_id: {"submit_s","start_s","end_s","wall_s",
    #:                          "queue_s","plane"}} (LRU-ordered: a touch
    #: moves the query to the end; eviction pops from the front)
    stage_spans: dict = field(default_factory=dict)  # guarded-by: _lock; per-query: bounded 64
    #: query_id -> total query wall seconds
    query_walls: dict = field(default_factory=dict)  # guarded-by: _lock; per-query: bounded 64

    def __post_init__(self):
        import threading

        self._lock = threading.Lock()
        #: queries currently executing — exempt from LRU eviction
        self._running: set = set()  # guarded-by: _lock

    def insert(self, task_label: str, node_metrics: dict) -> None:
        # DFTPU201 fix: concurrent task threads insert into one shared
        # store under the serving tier; an unlocked dict write raced the
        # snapshot reads below
        with self._lock:
            self.per_task[task_label] = node_metrics

    # -- query lifetime (eviction pinning) ----------------------------------
    def begin_query(self, query_id: str) -> None:
        """Pin ``query_id``: its spans/wall survive any LRU pressure until
        `finish_query`. Coordinator.execute brackets every query with
        these; a begin without a finish (caller died mid-query) is still
        bounded — the pin set only holds in-flight queries."""
        with self._lock:
            self._running.add(query_id)

    def finish_query(self, query_id: str) -> None:
        with self._lock:
            self._running.discard(query_id)
            self._evict_lru()

    def running_queries(self) -> set:
        with self._lock:
            return set(self._running)

    def _evict_lru(self) -> None:
        """Evict least-recently-touched NON-running queries down to the
        cap (caller holds the lock). If running queries alone exceed the
        cap the store grows past it — never evict a live query."""
        for store in (self.stage_spans, self.query_walls):
            if len(store) <= _STAGE_SPAN_QUERY_CAP:
                continue
            for qid in list(store):
                if len(store) <= _STAGE_SPAN_QUERY_CAP:
                    break
                if qid in self._running:
                    continue
                store.pop(qid)

    def _touch(self, store: dict, query_id: str) -> None:
        hit = store.pop(query_id, None)
        if hit is not None:
            store[query_id] = hit  # move-to-end: LRU

    # -- stage scheduling spans ---------------------------------------------
    def record_stage_span(self, query_id: str, stage_id: int,
                          submit_s: float, start_s: float, end_s: float,
                          plane: str = "") -> None:
        """One stage's scheduler span, in seconds on a shared monotonic
        clock: ``submit_s`` when the scheduler enqueued it, ``start_s``
        when a pool slot picked it up, ``end_s`` when its output
        materialized. ``wall_s`` (start->end) is the stage's true
        execution span; queue wait is reported separately so a bounded
        stage_parallelism does not inflate the overlap arithmetic."""
        with self._lock:
            self._touch(self.stage_spans, query_id)
            spans = self.stage_spans.setdefault(query_id, {})
            spans[stage_id] = {
                "submit_s": submit_s,
                "start_s": start_s,
                "end_s": end_s,
                "wall_s": max(end_s - start_s, 0.0),
                "queue_s": max(start_s - submit_s, 0.0),
                "plane": plane,
            }
            self._evict_lru()

    def record_query_wall(self, query_id: str, wall_s: float) -> None:
        with self._lock:
            self._touch(self.query_walls, query_id)
            self.query_walls[query_id] = wall_s
            self._evict_lru()

    def _span_query(self, query_id: Optional[str]) -> Optional[str]:
        if query_id is not None:
            return query_id if query_id in self.stage_spans else None
        return next(reversed(self.stage_spans), None)

    def stage_schedule_summary(self, query_id: Optional[str] = None) -> dict:
        """{"query_id", "stages", "sum_stage_wall_s", "query_wall_s",
        "overlap_factor", "max_concurrent"} for ``query_id`` (default: the
        most recent query). overlap_factor = sum(stage wall)/query wall —
        1.0 means fully serial; >1.0 proves inter-stage overlap.
        max_concurrent is the peak number of stage spans covering one
        instant (computed from the recorded intervals)."""
        with self._lock:
            qid = self._span_query(query_id)
            if qid is None:
                return {}
            spans = dict(self.stage_spans[qid])
            wall = self.query_walls.get(qid)
        total = sum(s["wall_s"] for s in spans.values())
        events = []
        for s in spans.values():
            events.append((s["start_s"], 1))
            events.append((s["end_s"], -1))
        peak = cur = 0
        for _, d in sorted(events):
            cur += d
            peak = max(peak, cur)
        return {
            "query_id": qid,
            "stages": dict(spans),
            "sum_stage_wall_s": total,
            "query_wall_s": wall,
            "overlap_factor": (total / wall) if wall else None,
            "max_concurrent": peak,
        }

    def render_stage_schedule(self, query_id: Optional[str] = None) -> str:
        """Human-readable critical-path summary (explain_analyze appends
        this below the plan tree when spans exist)."""
        s = self.stage_schedule_summary(query_id)
        if not s:
            return ""
        lines = [f"-- stage schedule (query {s['query_id'][:8]}) --"]
        t0 = min(
            (sp["submit_s"] for sp in s["stages"].values()), default=0.0
        )
        for sid in sorted(s["stages"]):
            sp = s["stages"][sid]
            label = "root " if sid == -1 else f"stage {sid}"
            plane = f"  [{sp['plane']}]" if sp.get("plane") else ""
            lines.append(
                f"{label:<9} wall {sp['wall_s']:.4f}s  "
                f"+{sp['start_s'] - t0:.4f}s start  "
                f"queue {sp['queue_s']:.4f}s{plane}"
            )
        wall = s["query_wall_s"]
        if wall:
            lines.append(
                f"sum(stage wall) {s['sum_stage_wall_s']:.4f}s / "
                f"query wall {wall:.4f}s = overlap factor "
                f"{s['overlap_factor']:.2f}x "
                f"(peak {s['max_concurrent']} concurrent stages)"
            )
        else:
            lines.append(
                f"sum(stage wall) {s['sum_stage_wall_s']:.4f}s "
                f"(peak {s['max_concurrent']} concurrent stages)"
            )
        return "\n".join(lines)

    def aggregated(self) -> dict:
        """node_id -> {metric: summed value across tasks}."""
        with self._lock:
            per_task = dict(self.per_task)
        out: dict = {}
        for metrics in per_task.values():
            for nid, mm in metrics.items():
                slot = out.setdefault(nid, {})
                for name, v in mm.items():
                    slot[name] = slot.get(name, 0) + v
        return out

    def per_task_view(self) -> dict:
        """node_id -> {metric_taskN: value} (PerTask format)."""
        with self._lock:
            per_task = dict(self.per_task)
        out: dict = {}
        for label, metrics in sorted(per_task.items()):
            for nid, mm in metrics.items():
                slot = out.setdefault(nid, {})
                for name, v in mm.items():
                    slot[f"{name}_{label}"] = v
        return out


class HedgeBudget:
    """In-flight budget for speculative (hedged) task attempts — the
    stampede guard of the straggler hedger (runtime/coordinator.py): a
    cold latency sketch or a genuinely slow stage makes EVERY task look
    hedge-worthy, and without a bound the hedger would double the
    cluster's load exactly when it is already slow. One budget is shared
    by every per-query coordinator under the serving tier, so the bound
    is cluster-wide, not per-query.

    `try_acquire(limit)` admits a hedge while fewer than ``limit``
    speculative attempts are in flight (the limit is passed per call so
    a live `SET distributed.hedge_budget` applies to the next hedge
    decision); the hedge releases its slot when its attempt resolves."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._in_flight = 0  # guarded-by: _lock
        self.peak_in_flight = 0  # guarded-by: _lock
        self.denied = 0  # guarded-by: _lock

    def try_acquire(self, limit: int) -> bool:
        with self._lock:
            if limit <= 0 or self._in_flight >= limit:
                self.denied += 1
                return False
            self._in_flight += 1
            self.peak_in_flight = max(
                self.peak_in_flight, self._in_flight
            )
            return True

    def release(self) -> None:
        with self._lock:
            self._in_flight = max(self._in_flight - 1, 0)

    def stats(self) -> dict:
        with self._lock:
            return {
                "in_flight": self._in_flight,
                "peak_in_flight": self.peak_in_flight,
                "denied": self.denied,
            }

    def telemetry_families(self) -> list:
        """Typed-registry adapter (runtime/telemetry.py)."""
        from datafusion_distributed_tpu.runtime.telemetry import family

        s = self.stats()
        return [
            family("dftpu_hedges_in_flight", "gauge",
                   "Speculative (hedged) attempts currently in flight.",
                   [({}, s["in_flight"])]),
            family("dftpu_hedges_peak_in_flight", "gauge",
                   "High-water mark of concurrent hedged attempts.",
                   [({}, s["peak_in_flight"])]),
            family("dftpu_hedges_denied", "counter",
                   "Hedge attempts denied by the in-flight budget.",
                   [({}, s["denied"])]),
        ]


class FaultCounters:
    """Thread-safe counters for the fault-tolerant execution layer
    (retries, reroutes, timeouts, quarantine trips). Surfaced through
    `Coordinator.faults` and `ObservabilityService.get_fault_counters`;
    mergeable across coordinators like the latency sketch."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}  # guarded-by: _lock

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def as_dict(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def merge(self, other: "FaultCounters") -> "FaultCounters":
        for name, n in other.as_dict().items():
            self.bump(name, n)
        return self

    def telemetry_families(self) -> list:
        """Typed-registry adapter (runtime/telemetry.py): every fault
        counter as one `dftpu_faults{kind=...}` counter family — the
        single exposition sink for the retry/quarantine/hedge/checkpoint
        counters this store already accumulates."""
        from datafusion_distributed_tpu.runtime.telemetry import family

        return [family(
            "dftpu_faults", "counter",
            "Fault-tolerance transitions by kind (retries, reroutes, "
            "timeouts, quarantines, hedges, checkpoints).",
            [({"kind": k}, v) for k, v in sorted(self.as_dict().items())],
        )]


def explain_analyze(
    plan: ExecutionPlan,
    store: MetricsStore,
    per_task: bool = False,
    diagnostics: "Optional[list]" = None,
    trace_store=None,
) -> str:
    """Render the plan tree with metrics stitched into each node line.

    ``diagnostics``: verifier findings (plan/verify.py Diagnostic list, or
    a VerifyResult) rendered per node id next to the runtime metrics —
    e.g. a "literal not hoistable — plan will not share compiles" warning
    lands on the exact Filter it applies to. None = run the verifier here
    so explain_analyze always shows static findings alongside metrics.

    ``trace_store``: the distributed-tracing store whose per-query
    profile report is appended when the executed query was traced (None =
    the process-wide default store, runtime/tracing.py)."""
    from datafusion_distributed_tpu.plan.verify import (
        VerifyResult,
        diag_suffix,
        verify_physical_plan,
    )

    node_metrics = store.per_task_view() if per_task else store.aggregated()
    if diagnostics is None:
        result = verify_physical_plan(plan)
    elif isinstance(diagnostics, VerifyResult):
        result = diagnostics
    else:
        result = VerifyResult(diagnostics)
    diag_by_node = result.by_node()
    lines = []

    def walk(node: ExecutionPlan, indent: int) -> None:
        mm = node_metrics.get(node.node_id, {})
        suffix = ""
        if mm:
            inner = ", ".join(f"{k}={_fmt(v)}" for k, v in sorted(mm.items()))
            suffix = f"  [{inner}]"
        suffix += diag_suffix(diag_by_node.get(node.node_id, ()))
        marker = ""
        if getattr(node, "is_exchange", False):
            marker = f" ── stage {node.stage_id}"
        lines.append("  " * indent + node.display() + marker + suffix)
        for c in node.children():
            walk(c, indent + 1)

    walk(plan, 0)
    # the schedule block binds to THIS plan's execution (the coordinator
    # stamps `_last_query_id` at submit): a store holding spans for many
    # queries must not render some other query's critical path here —
    # an unstamped plan (never coordinator-executed) renders none
    qid = getattr(plan, "_last_query_id", None)
    if qid is not None and store.stage_spans:
        schedule = store.render_stage_schedule(qid)
        if schedule:
            lines.append("")
            lines.append(schedule)
    # distributed-tracing profile fold (runtime/tracing.py): when the
    # query ran with `SET distributed.tracing` on, append its per-query
    # profile — top spans by self time, per-stage data-plane bytes/sec,
    # queue-wait vs execute split, fault events
    if qid is not None:
        from datafusion_distributed_tpu.runtime.tracing import (
            DEFAULT_TRACE_STORE,
            render_profile,
        )

        ts = trace_store if trace_store is not None else DEFAULT_TRACE_STORE
        trace = ts.get(qid)
        if trace is not None:
            profile = render_profile(trace)
            if profile:
                lines.append("")
                lines.append(profile)
    return "\n".join(lines)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4f}"
    return str(v)


class LatencySketch:
    """Mergeable log-bucketed latency sketch (the DDSketch role in the
    reference: per-task latency distributions shipped as sketch bytes and
    merged coordinator-side, `metrics/latency_metric.rs:3-13`,
    worker.proto PercentileLatency).

    Buckets are powers of gamma, giving a fixed RELATIVE accuracy
    (gamma=1.02 -> ~2% error on any quantile) with tiny fixed state —
    mergeable by adding bucket counts, exactly the property DDSketch is
    used for."""

    def __init__(self, gamma: float = 1.02, min_value: float = 1e-6):
        import math
        import threading

        self.gamma = gamma
        self.min_value = min_value
        self._log_gamma = math.log(gamma)
        self.buckets: dict[int, int] = {}  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock
        self.min: Optional[float] = None  # guarded-by: _lock
        self.max: Optional[float] = None  # guarded-by: _lock
        # the serving tier shares ONE sketch across every concurrent
        # query's coordinator + driver threads: the read-modify-write on
        # buckets/count must serialize or updates are silently lost
        self._lock = threading.Lock()

    def record(self, value: float) -> None:
        import math

        v = max(float(value), self.min_value)
        idx = int(math.ceil(math.log(v / self.min_value) / self._log_gamma))
        with self._lock:
            self.buckets[idx] = self.buckets.get(idx, 0) + 1
            self.count += 1
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def merge(self, other: "LatencySketch") -> "LatencySketch":
        assert other.gamma == self.gamma
        with other._lock:
            obuckets = dict(other.buckets)
            ocount, omin, omax = other.count, other.min, other.max
        with self._lock:
            for idx, c in obuckets.items():
                self.buckets[idx] = self.buckets.get(idx, 0) + c
            self.count += ocount
            for bound, ov in (("min", omin), ("max", omax)):
                sv = getattr(self, bound)
                if ov is not None:
                    pick = min if bound == "min" else max
                    setattr(self, bound, ov if sv is None else pick(sv, ov))
        return self

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 1] -> value with <= gamma relative error."""
        with self._lock:
            if self.count == 0:
                return None
            buckets = dict(self.buckets)
            count, vmax = self.count, self.max
        target = max(1, int(round(q * count)))
        seen = 0
        for idx in sorted(buckets):
            seen += buckets[idx]
            if seen >= target:
                # bucket midpoint in log space
                return self.min_value * self.gamma ** (idx - 0.5)
        return vmax

    def summary(self) -> dict:
        return {
            "count": self.count,
            "min": self.min,
            "p50": self.percentile(0.50),
            "p75": self.percentile(0.75),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
            "max": self.max,
        }

    def telemetry_families(self, name: str, help_text: str = "") -> list:
        """Typed-registry adapter (runtime/telemetry.py): the sketch as
        a prometheus-style summary — `<name>{quantile=...}` gauges plus
        `<name>_observations` — under a caller-chosen metric name (one
        sketch class serves both the task- and query-latency roles)."""
        from datafusion_distributed_tpu.runtime.telemetry import family

        s = self.summary()
        quantiles = [
            ({"quantile": q}, s[q])
            for q in ("p50", "p95", "p99")
            if s.get(q) is not None
        ]
        fams = [family(
            f"{name}_observations", "counter",
            f"Observations recorded into {name}.", [({}, s["count"])],
        )]
        if quantiles:
            fams.append(family(
                name, "gauge",
                help_text or f"Log-bucketed latency sketch {name} "
                             "(seconds).",
                quantiles,
            ))
        return fams

    def to_dict(self) -> dict:
        """Wire format (the sketch-bytes analogue)."""
        with self._lock:
            return {
                "gamma": self.gamma,
                "min_value": self.min_value,
                "buckets": {str(k): v for k, v in self.buckets.items()},
                "count": self.count,
                "min": self.min,
                "max": self.max,
            }

    @classmethod
    def from_dict(cls, d: dict) -> "LatencySketch":
        s = cls(gamma=d["gamma"], min_value=d["min_value"])
        s.buckets = {int(k): v for k, v in d["buckets"].items()}
        s.count = d["count"]
        s.min = d["min"]
        s.max = d["max"]
        return s
